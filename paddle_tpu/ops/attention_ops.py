"""Fused attention program ops backed by the Pallas flash kernel.

No reference equivalent exists (2018 codebase computes attention as
unfused matmul+softmax ops, e.g. nets.scaled_dot_product_attention in
python/paddle/fluid/nets.py) — this op is the TPU-native upgrade: one
program op that lowers to kernels/flash_attention.py, O(T) memory.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import flags
from ..core.enforce import EnforceNotMet
from ..framework.registry import register_op, single_input


@register_op("fused_attention")
def _fused_attention(ctx, ins, attrs):
    """Q,K,V: [B, T, n_head*d].  Out: [B, T, n_head*d].
    attrs: n_head, causal, scale (0 => 1/sqrt(d))."""
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    n_head = int(attrs["n_head"])
    causal = bool(attrs.get("causal", False))
    B, T, E = q.shape
    d = E // n_head
    orig_dtype = q.dtype
    from .math_ops import amp_inputs
    q, k, v = amp_inputs(q, k, v)

    def split(x):
        return x.reshape(B, T, n_head, d).transpose(0, 2, 1, 3)

    scale = float(attrs.get("scale", 0.0)) or None
    cp_axis = getattr(ctx, "cp_axis", None)
    if cp_axis is not None:
        # context-parallel plane (transpiler/context_parallel.py): this
        # trace runs inside shard_map with the sequence sharded over
        # cp_axis — T here is the LOCAL chunk; ring attention rotates
        # K/V around the axis with exact cross-chunk causal masking
        from ..parallel.ring_attention import ring_attention
        if scale is not None and abs(scale - d ** -0.5) > 1e-9:
            raise EnforceNotMet(
                "fused_attention under context parallelism uses the "
                "default 1/sqrt(d) scale")
        o = ring_attention(q.reshape(B, T, n_head, d),
                           k.reshape(B, T, n_head, d),
                           v.reshape(B, T, n_head, d),
                           cp_axis, causal=causal)
        return {"Out": [o.reshape(B, T, E).astype(orig_dtype)]}
    if flags.get_flag("use_pallas_kernels"):
        from ..kernels.flash_attention import flash_attention
        o = flash_attention(split(q), split(k), split(v), causal=causal,
                            scale=scale, interpret=ctx.pallas_interpret())
    else:
        import numpy as np
        import jax
        qh, kh, vh = split(q), split(k), split(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * (
            scale or 1.0 / np.sqrt(d))
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        w = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
    out = o.transpose(0, 2, 1, 3).reshape(B, T, E).astype(orig_dtype)
    return {"Out": [out]}


@register_op("fused_lm_head_loss")
def _fused_lm_head_loss(ctx, ins, attrs):
    """Chunked, rematerialized LM-head + softmax-CE (TPU-first fusion;
    the reference computes fc -> softmax_with_cross_entropy materializing
    the full [N, V] logits — operators/softmax_with_cross_entropy_op.cc).

    X [N, D] activations, W [D, V] head weights, Label [N] int ->
    Loss [1] = mean_n (logsumexp(x_n W) - (x_n W)[y_n]).

    lax.scan over token chunks with jax.checkpoint: HBM holds only one
    [chunk, V] logits block at a time, forward AND backward (backward
    recomputes each block), instead of full [N, V] fwd plus its
    gradient — the dominant HBM cost of big-vocab LM training.  Matmul
    runs in bf16 under FLAGS_amp_bf16 with f32 accumulation.
    """
    from .math_ops import amp_inputs
    x = single_input(ins, "X")
    w = single_input(ins, "W")
    label = single_input(ins, "Label").reshape(-1).astype(jnp.int32)
    n, d = x.shape
    chunk = int(attrs.get("chunk_size", 2048))
    chunk = min(chunk, n)
    n_chunks = (n + chunk - 1) // chunk
    pad = n_chunks * chunk - n
    if (flags.get_flag("use_pallas_kernels") and n % 256 == 0
            and d <= 2048):
        # vocab-streamed Pallas head (kernels/lm_head.py): logits never
        # hit HBM, 1 fwd + 3 bwd matmul passes — the [N,V] HBM round
        # trips of the scan path below were the top cost of the v5e
        # flagship step (docs/profile_r03)
        from ..kernels.lm_head import lm_head_xent
        xb, wb = amp_inputs(x, w)
        losses = lm_head_xent(xb, wb, label, chunk=chunk,
                              interpret=ctx.pallas_interpret())
        return {"Loss": [(jnp.sum(losses) / n).reshape(1)]}
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        label = jnp.pad(label, (0, pad), constant_values=-1)
    xs = x.reshape(n_chunks, chunk, d)
    ys = label.reshape(n_chunks, chunk)

    def chunk_loss(w, x_c, y_c):
        xb, wb = amp_inputs(x_c, w)
        logits = jnp.matmul(xb, wb,
                            preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, jnp.maximum(y_c, 0)[:, None], axis=1)[:, 0]
        valid = (y_c >= 0).astype(jnp.float32)
        return jnp.sum((lse - gold) * valid)

    remat_loss = jax.checkpoint(chunk_loss)

    if bool(attrs.get("unroll", False)):
        # unrolled chunks: XLA can overlap/schedule across chunks at the
        # cost of code size (attr for A/B; scan is the default)
        total = jnp.zeros((), jnp.float32)
        for ci in range(n_chunks):
            total = total + remat_loss(w, xs[ci], ys[ci])
    else:
        def body(acc, xy):
            x_c, y_c = xy
            return acc + remat_loss(w, x_c, y_c), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (xs, ys))
    return {"Loss": [(total / n).reshape(1)]}
