"""Fused attention program ops backed by the Pallas flash kernel.

No reference equivalent exists (2018 codebase computes attention as
unfused matmul+softmax ops, e.g. nets.scaled_dot_product_attention in
python/paddle/fluid/nets.py) — this op is the TPU-native upgrade: one
program op that lowers to kernels/flash_attention.py, O(T) memory.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core import flags
from ..core.enforce import EnforceNotMet
from ..framework.registry import register_op, single_input


@register_op("fused_attention")
def _fused_attention(ctx, ins, attrs):
    """Q,K,V: [B, T, n_head*d].  Out: [B, T, n_head*d].
    attrs: n_head, causal, scale (0 => 1/sqrt(d))."""
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    n_head = int(attrs["n_head"])
    causal = bool(attrs.get("causal", False))
    B, T, E = q.shape
    d = E // n_head
    orig_dtype = q.dtype
    from .math_ops import amp_inputs
    q, k, v = amp_inputs(q, k, v)

    def split(x):
        return x.reshape(B, T, n_head, d).transpose(0, 2, 1, 3)

    scale = float(attrs.get("scale", 0.0)) or None
    cp_axis = getattr(ctx, "cp_axis", None)
    if cp_axis is not None:
        # context-parallel plane (transpiler/context_parallel.py): this
        # trace runs inside shard_map with the sequence sharded over
        # cp_axis — T here is the LOCAL chunk; ring attention rotates
        # K/V around the axis with exact cross-chunk causal masking
        from ..parallel.ring_attention import ring_attention
        if scale is not None and abs(scale - d ** -0.5) > 1e-9:
            raise EnforceNotMet(
                "fused_attention under context parallelism uses the "
                "default 1/sqrt(d) scale")
        o = ring_attention(q.reshape(B, T, n_head, d),
                           k.reshape(B, T, n_head, d),
                           v.reshape(B, T, n_head, d),
                           cp_axis, causal=causal)
        return {"Out": [o.reshape(B, T, E).astype(orig_dtype)]}
    if flags.get_flag("use_pallas_kernels"):
        from ..kernels.flash_attention import flash_attention
        o = flash_attention(split(q), split(k), split(v), causal=causal,
                            scale=scale, interpret=ctx.pallas_interpret())
    else:
        import numpy as np
        import jax
        qh, kh, vh = split(q), split(k), split(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * (
            scale or 1.0 / np.sqrt(d))
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        w = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
    out = o.transpose(0, 2, 1, 3).reshape(B, T, E).astype(orig_dtype)
    return {"Out": [out]}


@register_op("fused_mha")
def _fused_mha(ctx, ins, attrs):
    """Projection-fused multi-head attention — ONE op owning the q/k/v
    and output projection weights, lowered transpose-free.

    X [B, T, D] (+ XKV [B, Tk, Dk] for cross-attention); Wq/Wk/Wv
    [D, E], Wo [E, D_out]; attrs n_head, causal.  The projections run
    with the WEIGHTS as the dot_general lhs, so q/k/v come out in the
    head-major [H, d_head, B*T] layout the Pallas HDT kernel consumes
    directly, and o's (h, d) dims are adjacent so the output projection
    collapses to a plain matmul: the whole sublayer has ZERO XLA
    transposes, forward and backward (the [B,T,H,d] <-> [B,H,T,d]
    layout churn of the split-heads composition cost ~24% of the
    flagship step, docs/profile_r03).  No reference equivalent (2018
    codebase: unfused matmul+softmax, fluid/nets.py)."""
    from .math_ops import amp_inputs, amp_result, _acc_type
    x = ins["X"][0]
    wq, wk, wv = ins["Wq"][0], ins["Wk"][0], ins["Wv"][0]
    wo = ins["Wo"][0]
    n_head = int(attrs["n_head"])
    causal = bool(attrs.get("causal", False))
    orig_dtype = x.dtype
    B, T, D = x.shape
    E = int(wo.shape[0])
    dh = E // n_head
    if causal and ins.get("XKV"):
        raise EnforceNotMet(
            "fused_mha: causal masking is only defined for "
            "self-attention (positions of XKV and X differ)")
    xkv = ins["XKV"][0] if ins.get("XKV") else x
    Tk = xkv.shape[1]
    xb, xkvb, wqb, wkb, wvb, wob = amp_inputs(x, xkv, wq, wk, wv, wo)

    def pad_tokens(a, t, tp):
        return jnp.pad(a, ((0, 0), (0, tp - t), (0, 0))) if tp != t else a

    cp_axis = getattr(ctx, "cp_axis", None)
    use_pallas = cp_axis is None and flags.get_flag("use_pallas_kernels")
    if use_pallas:
        # only the Pallas kernel needs tile-granule padding; the ring
        # (cp) and unfused paths take any T
        granule = 128
        Tp = -(-T // granule) * granule
        Tkp = -(-Tk // granule) * granule
    else:
        Tp, Tkp = T, Tk
    if cp_axis is not None:
        # context-parallel plane: q/k/v still project head-major, then
        # ring attention rotates K/V around the axis (local T chunk)
        from ..parallel.ring_attention import ring_attention
    # project with weights as lhs: head-major [E, B*T], no transpose.
    # NOTE a single stacked [3,D,E] qkv dot was measured SLOWER on v5e
    # (0.445 -> 0.432 MFU) than q separate + stacked [2,D,E] k/v — the
    # weight-stack copy sits on the critical path each step
    xq2 = pad_tokens(xb, T, Tp).reshape(B * Tp, D)
    xk2 = pad_tokens(xkvb, Tk, Tkp).reshape(B * Tkp, -1)
    w2 = jnp.stack([wkb, wvb])                      # [2, Dk, E]
    q = lax.dot_general(wqb, xq2, (((0,), (1,)), ((), ())))   # [E, BTp]
    kv = lax.dot_general(w2, xk2, (((1,), (1,)), ((), ())))   # [2,E,BTkp]
    q = q.reshape(n_head, dh, B * Tp)
    k = kv[0].reshape(n_head, dh, B * Tkp)
    v = kv[1].reshape(n_head, dh, B * Tkp)

    if cp_axis is not None:
        def to_bthd(a, t):
            return a.reshape(n_head, dh, B, t).transpose(2, 3, 0, 1)
        o = ring_attention(to_bthd(q, Tp), to_bthd(k, Tkp),
                           to_bthd(v, Tkp), cp_axis,
                           causal=causal)              # [B, T, H, dh]
        o = o.transpose(2, 3, 0, 1).reshape(E, B * Tp)
    elif flags.get_flag("use_pallas_kernels"):
        from ..kernels.flash_attention import flash_attention_hdt
        o = flash_attention_hdt(
            q, k, v, batch=B, causal=causal,
            kv_len=Tk if Tkp != Tk else None,
            interpret=ctx.pallas_interpret())          # [H, dh, BTp]
        o = o.reshape(E, B * Tp)
    else:
        # unfused composition from the same head-major tensors
        # (correctness/debug path; layout cost irrelevant off-TPU)
        q4 = q.reshape(n_head, dh, B, Tp)
        k4 = k.reshape(n_head, dh, B, Tkp)
        v4 = v.reshape(n_head, dh, B, Tkp)
        s = jnp.einsum("hdbq,hdbk->bhqk", q4, k4) * (dh ** -0.5)
        if causal:
            mask = jnp.tril(jnp.ones((Tp, Tkp), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        w_att = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        o = jnp.einsum("bhqk,hdbk->hdbq", w_att, v4).reshape(E, B * Tp)

    # 2-byte o -> 2-byte out directly (MXU still accumulates f32); an
    # f32 surface + the amp_result cast below left an unfused
    # convert_element_type pass over [B, T, D] (see math_ops.amp_matmul)
    pet = None if jnp.dtype(o.dtype).itemsize == 2 else _acc_type(o)
    out = lax.dot_general(o, wob, (((0,), (0,)), ((), ())),
                          preferred_element_type=pet)
    out = out.reshape(B, Tp, -1)
    if Tp != T:
        out = out[:, :T]
    return {"Out": [amp_result(out, orig_dtype)]}


@register_op("fused_lm_head_loss")
def _fused_lm_head_loss(ctx, ins, attrs):
    """Chunked, rematerialized LM-head + softmax-CE (TPU-first fusion;
    the reference computes fc -> softmax_with_cross_entropy materializing
    the full [N, V] logits — operators/softmax_with_cross_entropy_op.cc).

    X [N, D] activations, W [D, V] head weights, Label [N] int ->
    Loss [1] = mean_n (logsumexp(x_n W) - (x_n W)[y_n]).

    lax.scan over token chunks with jax.checkpoint: HBM holds only one
    [chunk, V] logits block at a time, forward AND backward (backward
    recomputes each block), instead of full [N, V] fwd plus its
    gradient — the dominant HBM cost of big-vocab LM training.  Matmul
    runs in bf16 under FLAGS_amp_bf16 with f32 accumulation.
    """
    from .math_ops import amp_inputs
    x = single_input(ins, "X")
    w = single_input(ins, "W")
    label = single_input(ins, "Label").reshape(-1).astype(jnp.int32)
    n, d = x.shape
    chunk = int(attrs.get("chunk_size", 2048))
    chunk = min(chunk, n)
    n_chunks = (n + chunk - 1) // chunk
    pad = n_chunks * chunk - n
    if (flags.get_flag("use_pallas_kernels") and n % 256 == 0
            and d <= 2048):
        # vocab-streamed Pallas head (kernels/lm_head.py): logits never
        # hit HBM, 1 fwd + 3 bwd matmul passes — the [N,V] HBM round
        # trips of the scan path below were the top cost of the v5e
        # flagship step (docs/profile_r03)
        from ..kernels.lm_head import lm_head_xent
        xb, wb = amp_inputs(x, w)
        losses = lm_head_xent(xb, wb, label, chunk=chunk,
                              interpret=ctx.pallas_interpret())
        return {"Loss": [(jnp.sum(losses) / n).reshape(1)]}
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        label = jnp.pad(label, (0, pad), constant_values=-1)
    xs = x.reshape(n_chunks, chunk, d)
    ys = label.reshape(n_chunks, chunk)

    def chunk_loss(w, x_c, y_c):
        xb, wb = amp_inputs(x_c, w)
        logits = jnp.matmul(xb, wb,
                            preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, jnp.maximum(y_c, 0)[:, None], axis=1)[:, 0]
        valid = (y_c >= 0).astype(jnp.float32)
        return jnp.sum((lse - gold) * valid)

    remat_loss = jax.checkpoint(chunk_loss)

    if bool(attrs.get("unroll", False)):
        # unrolled chunks: XLA can overlap/schedule across chunks at the
        # cost of code size (attr for A/B; scan is the default)
        total = jnp.zeros((), jnp.float32)
        for ci in range(n_chunks):
            total = total + remat_loss(w, xs[ci], ys[ci])
    else:
        def body(acc, xy):
            x_c, y_c = xy
            return acc + remat_loss(w, x_c, y_c), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (xs, ys))
    return {"Loss": [(total / n).reshape(1)]}
