"""Fused attention program ops backed by the Pallas flash kernel.

No reference equivalent exists (2018 codebase computes attention as
unfused matmul+softmax ops, e.g. nets.scaled_dot_product_attention in
python/paddle/fluid/nets.py) — this op is the TPU-native upgrade: one
program op that lowers to kernels/flash_attention.py, O(T) memory.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import flags
from ..framework.registry import register_op


@register_op("fused_attention")
def _fused_attention(ctx, ins, attrs):
    """Q,K,V: [B, T, n_head*d].  Out: [B, T, n_head*d].
    attrs: n_head, causal, scale (0 => 1/sqrt(d))."""
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    n_head = int(attrs["n_head"])
    causal = bool(attrs.get("causal", False))
    B, T, E = q.shape
    d = E // n_head
    orig_dtype = q.dtype
    from .math_ops import amp_inputs
    q, k, v = amp_inputs(q, k, v)

    def split(x):
        return x.reshape(B, T, n_head, d).transpose(0, 2, 1, 3)

    scale = float(attrs.get("scale", 0.0)) or None
    if flags.get_flag("use_pallas_kernels"):
        from ..kernels.flash_attention import flash_attention
        o = flash_attention(split(q), split(k), split(v), causal=causal,
                            scale=scale, interpret=ctx.pallas_interpret())
    else:
        import numpy as np
        import jax
        qh, kh, vh = split(q), split(k), split(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * (
            scale or 1.0 / np.sqrt(d))
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        w = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
    out = o.transpose(0, 2, 1, 3).reshape(B, T, E).astype(orig_dtype)
    return {"Out": [out]}
