"""Sampled / structured classification losses.

Capability parity with /root/reference/paddle/fluid/operators/nce_op.cc,
hierarchical_sigmoid_op.cc, teacher_student_sigmoid_loss_op.cc,
positive_negative_pair_op.cc — TPU-first: negative sampling draws from
the functional RNG (ctx.rng()), the hsigmoid default tree is the
reference's complete binary tree over classes, and everything is dense
batched math (no SelectedRows side outputs; grads are XLA scatter-adds).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op, single_input


@register_op("nce")
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation (ref nce_op.cc, uniform sampler).

    Input [B,D], Weight [N,D], optional Bias [N], Label [B] (or [B,1]).
    attrs: num_total_classes N, num_neg_samples (default 10).
    Output: Cost [B,1]; SampleLogits/SampleLabels for parity."""
    x = single_input(ins, "Input").astype(jnp.float32)
    w = single_input(ins, "Weight").astype(jnp.float32)
    label = single_input(ins, "Label")
    if label.ndim == 2:
        label = label[:, 0]
    label = label.astype(jnp.int32)
    bias = ins["Bias"][0].astype(jnp.float32) if ins.get("Bias") else None
    B, D = x.shape
    N = int(attrs.get("num_total_classes", w.shape[0]))
    k = int(attrs.get("num_neg_samples", 10))

    neg = jax.random.randint(ctx.rng(), (B, k), 0, N)       # uniform noise
    samples = jnp.concatenate([label[:, None], neg], axis=1)  # [B, 1+k]
    sw = w[samples]                                          # [B,1+k,D]
    logits = jnp.einsum("bd,bkd->bk", x, sw)
    if bias is not None:
        logits = logits + bias[samples]
    # NCE objective with uniform noise q = 1/N:  P(data|u) =
    # sigmoid(logit - log(k*q))
    log_kq = np.log(k / N)
    adj = logits - log_kq
    lbl = jnp.zeros((B, 1 + k), jnp.float32).at[:, 0].set(1.0)
    # stable sigmoid cross entropy
    loss = jnp.maximum(adj, 0) - adj * lbl + jnp.log1p(jnp.exp(-jnp.abs(adj)))
    cost = jnp.sum(loss, axis=1, keepdims=True)
    return {"Cost": [cost], "SampleLogits": [logits],
            "SampleLabels": [samples]}


@register_op("hierarchical_sigmoid")
def _hierarchical_sigmoid(ctx, ins, attrs):
    """Hierarchical softmax over the reference's default complete binary
    tree (ref hierarchical_sigmoid_op.cc + operators/math/matrix_bit_code.h:
    internal node for class c at each step = path of (c + num_classes) in
    a heap layout; code bit = child direction).

    Input X [B,D], W [num_classes-1, D], Label [B], optional Bias
    [num_classes-1].  Output Cost [B,1], PreOut [B, max_code_length]."""
    x = single_input(ins, "X").astype(jnp.float32)
    w = single_input(ins, "W").astype(jnp.float32)
    label = single_input(ins, "Label")
    if label.ndim == 2:
        label = label[:, 0]
    label = label.astype(jnp.int32)
    bias = ins["Bias"][0].astype(jnp.float32) if ins.get("Bias") else None
    num_classes = int(attrs["num_classes"])
    B, D = x.shape
    # heap path: node ids of (label + num_classes) up to the root (id 1);
    # matrix_bit_code.h: calc_index = path node - num_classes ... the
    # reference uses SimpleCode: code(d) = (c + num_classes) >> (L-d) ...
    L = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
    node = label + num_classes
    # step j (from leaf up): parent nodes; weight row = node//2 - 1
    costs = jnp.zeros((B,), jnp.float32)
    preouts = []
    for _ in range(L):
        parent = node // 2
        bit = (node % 2).astype(jnp.float32)     # 1 = right child
        row = parent - 1                          # internal node index
        valid = parent >= 1
        row_c = jnp.clip(row, 0, w.shape[0] - 1)
        z = jnp.einsum("bd,bd->b", x, w[row_c])
        if bias is not None:
            z = z + bias[row_c]
        # sigmoid xent against the bit
        step_cost = jnp.maximum(z, 0) - z * bit + jnp.log1p(
            jnp.exp(-jnp.abs(z)))
        costs = costs + jnp.where(valid & (row >= 0), step_cost, 0.0)
        preouts.append(z)
        node = parent
    pre = jnp.stack(preouts, axis=1)
    return {"Out": [costs[:, None]], "PreOut": [pre]}


@register_op("teacher_student_sigmoid_loss")
def _ts_sigmoid_loss(ctx, ins, attrs):
    """ref teacher_student_sigmoid_loss_op.cc: distillation loss mixing
    hard 0/1 CTR label with a soft teacher score."""
    x = single_input(ins, "X").astype(jnp.float32)
    label = single_input(ins, "Label").astype(jnp.float32)
    soft_max_up = float(attrs.get("soft_max_up_bound", 15.0))
    soft_max_lo = float(attrs.get("soft_max_lower_bound", -15.0))
    z = x.reshape(label.shape)
    hard = (label > 0.5).astype(jnp.float32)
    ce = jnp.maximum(z, 0) - z * hard + jnp.log1p(jnp.exp(-jnp.abs(z)))
    zc = jnp.clip(z, soft_max_lo, soft_max_up)
    soft = jnp.log1p(jnp.exp(zc)) - label * zc
    use_soft = (label > 0.0) & (label < 1.0)
    return {"Y": [jnp.where(use_soft, soft, ce)]}


@register_op("positive_negative_pair", stop_gradient=True)
def _positive_negative_pair(ctx, ins, attrs):
    """ref positive_negative_pair_op.cc: within each query id, count
    (pos, neg, neutral) score-ordering pairs between items of different
    labels.  Score [N,1], Label [N,1], QueryID [N,1]."""
    score = single_input(ins, "Score").reshape(-1).astype(jnp.float32)
    label = single_input(ins, "Label").reshape(-1).astype(jnp.float32)
    qid = single_input(ins, "QueryID").reshape(-1).astype(jnp.int32)
    same_q = qid[:, None] == qid[None, :]
    li, lj = label[:, None], label[None, :]
    si, sj = score[:, None], score[None, :]
    upper = jnp.triu(jnp.ones_like(same_q), k=1)
    pairs = same_q & (li != lj) & upper.astype(bool)
    hi_right = jnp.where(li > lj, si - sj, sj - si)     # margin of the
    pos = jnp.sum((pairs & (hi_right > 0)).astype(jnp.float32))
    neg = jnp.sum((pairs & (hi_right < 0)).astype(jnp.float32))
    neu = jnp.sum((pairs & (hi_right == 0)).astype(jnp.float32))
    return {"PositivePair": [pos.reshape(1)],
            "NegativePair": [neg.reshape(1)],
            "NeutralPair": [neu.reshape(1)]}
