"""Linear-chain CRF + CTC op family.

Capability parity with /root/reference/paddle/fluid/operators/
linear_chain_crf_op.cc, crf_decoding_op.cc, warpctc_op.cc,
ctc_align_op.cc, chunk_eval_op.cc — redesigned TPU-first: dense [B, T]
batches with float masks instead of LoD, and every recurrence is a
log-semiring lax.scan, so the losses are differentiable by the
whole-program jax.vjp (no hand-written grad kernels; the reference's
warpctc vendored library becomes ~40 lines of scan).

Transition layout follows the reference (linear_chain_crf_op.h):
Transition [N+2, N]: row 0 = start weights, row 1 = stop weights,
rows 2.. = [N, N] transition matrix w[i, j] = score(tag i -> tag j).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dtypes import index_dtype
from ..framework.registry import register_op, single_input

NEG = -1e9


def _crf_terms(trans):
    start, stop, w = trans[0], trans[1], trans[2:]
    return start, stop, w


def _seq_lens(mask, B, T):
    if mask is None:
        return jnp.full((B,), T, jnp.int32)
    return jnp.sum(mask, axis=1).astype(jnp.int32)


@register_op("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    """Emission [B,T,N], Transition [N+2,N], Label [B,T] int, optional
    Mask [B,T] (1=token).  Outputs LogLikelihood [B,1] (ref outputs the
    log-likelihood; loss = -mean(llh)), Alpha [B,T,N],
    EmissionExps/TransitionExps kept for API parity (exp of inputs)."""
    em = single_input(ins, "Emission").astype(jnp.float32)
    trans = single_input(ins, "Transition").astype(jnp.float32)
    label = single_input(ins, "Label")
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    mask = ins["Mask"][0].astype(jnp.float32) if ins.get("Mask") else None
    B, T, N = em.shape
    start, stop, w = _crf_terms(trans)
    lens = _seq_lens(mask, B, T)

    # ---- partition function: alpha recursion in log space -------------
    a0 = start[None, :] + em[:, 0]                       # [B, N]

    def fwd(a, t):
        # a[b, i] -> logsumexp_i(a + w[i, j]) + em[t, j]
        nxt = jax.scipy.special.logsumexp(
            a[:, :, None] + w[None, :, :], axis=1) + em[:, t]
        live = (t < lens)[:, None]
        a = jnp.where(live, nxt, a)
        return a, a

    aT, alphas = lax.scan(fwd, a0, jnp.arange(1, T))
    alpha = jnp.concatenate([a0[:, None], jnp.swapaxes(alphas, 0, 1)], 1)
    last_tag_bonus = stop[None, :]
    log_z = jax.scipy.special.logsumexp(aT + last_tag_bonus, axis=1)

    # ---- gold path score ---------------------------------------------
    brange = jnp.arange(B)
    gold0 = start[label[:, 0]] + em[brange, 0, label[:, 0]]

    def gold_step(g, t):
        step = (w[label[:, t - 1], label[:, t]]
                + em[brange, t, label[:, t]])
        live = (t < lens).astype(jnp.float32)
        return g + live * step, None

    gold, _ = lax.scan(gold_step, gold0, jnp.arange(1, T))
    last_idx = jnp.clip(lens - 1, 0, T - 1)
    gold = gold + stop[label[brange, last_idx]]

    llh = (gold - log_z)[:, None]                        # [B, 1]
    return {"LogLikelihood": [llh], "Alpha": [alpha],
            "EmissionExps": [jnp.exp(em)],
            "TransitionExps": [jnp.exp(trans)]}


@register_op("crf_decoding", stop_gradient=True)
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode (ref crf_decoding_op.cc).  Emission [B,T,N],
    Transition [N+2,N], optional Mask.  Output ViterbiPath [B,T] int32
    (padded steps emit 0); with Label given, outputs 0/1 correctness per
    step instead (the reference's behavior under Label)."""
    em = single_input(ins, "Emission").astype(jnp.float32)
    trans = single_input(ins, "Transition").astype(jnp.float32)
    mask = ins["Mask"][0].astype(jnp.float32) if ins.get("Mask") else None
    B, T, N = em.shape
    start, stop, w = _crf_terms(trans)
    lens = _seq_lens(mask, B, T)

    v0 = start[None, :] + em[:, 0]

    def step(v, t):
        cand = v[:, :, None] + w[None, :, :]             # [B, i, j]
        best = jnp.max(cand, axis=1) + em[:, t]
        ptr = jnp.argmax(cand, axis=1).astype(jnp.int32)
        live = (t < lens)[:, None]
        v = jnp.where(live, best, v)
        return v, ptr

    vT, ptrs = lax.scan(step, v0, jnp.arange(1, T))      # ptrs [T-1,B,N]
    # ending tag: add stop at each sequence's true last position
    last = jnp.argmax(vT + stop[None, :], axis=1).astype(jnp.int32)

    def back(tag, t):
        prev = ptrs[t - 1][jnp.arange(B), tag]
        live = (t <= lens - 1)
        # beyond the end the pointer chain is frozen at `last`
        tag_prev = jnp.where(live, prev, tag)
        return tag_prev, tag

    first_tag, path_rev = lax.scan(back, last, jnp.arange(T - 1, 0, -1))
    rest = jnp.swapaxes(jnp.flip(path_rev, 0), 0, 1)     # tags 1..T-1
    path = jnp.concatenate([first_tag[:, None], rest], axis=1)
    if mask is not None:
        path = path * (mask > 0).astype(jnp.int32)
    if ins.get("Label"):
        label = ins["Label"][0]
        if label.ndim == 3:
            label = label[..., 0]
        correct = (path == label.astype(jnp.int32)).astype(jnp.int32)
        if mask is not None:
            correct = correct * (mask > 0).astype(jnp.int32)
        return {"ViterbiPath": [correct]}
    return {"ViterbiPath": [path]}


@register_op("warpctc")
def _warpctc(ctx, ins, attrs):
    """CTC loss (ref warpctc_op.cc, the vendored warp-ctc library) as a
    log-semiring scan over the blank-extended label sequence.

    Logits [B,T,C] unnormalized, Label [B,S] int (padded with -1 or
    blank beyond each label's length), optional LogitsLength [B],
    LabelLength [B].  attrs: blank (default 0), norm_by_times.
    Output Loss [B,1] = -log p(label | logits); WarpCTCGrad omitted —
    jax.vjp differentiates the scan exactly."""
    logits = single_input(ins, "Logits").astype(jnp.float32)
    label = single_input(ins, "Label")
    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    B, T, C = logits.shape
    S = label.shape[1]
    blank = int(attrs.get("blank", 0))
    lp = jax.nn.log_softmax(logits, axis=-1)
    t_lens = (ins["LogitsLength"][0].astype(jnp.int32).reshape(B)
              if ins.get("LogitsLength") else jnp.full((B,), T, jnp.int32))
    l_lens = (ins["LabelLength"][0].astype(jnp.int32).reshape(B)
              if ins.get("LabelLength")
              else jnp.sum((label >= 0) & (label != blank), 1)
              .astype(jnp.int32))

    # extended sequence: blank l1 blank l2 ... lS blank  (len 2S+1)
    E = 2 * S + 1
    lab = jnp.where(label < 0, blank, label)
    ext = jnp.full((B, E), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    pos = jnp.arange(E)[None, :]
    valid = pos < (2 * l_lens + 1)[:, None]
    # can-skip: ext[e] != blank and ext[e] != ext[e-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :E]
    can_skip = (ext != blank) & (ext != ext_m2)

    a0 = jnp.full((B, E), NEG)
    a0 = a0.at[:, 0].set(lp[:, 0, blank])
    a0 = a0.at[:, 1].set(
        jnp.where(l_lens > 0, lp[jnp.arange(B), 0, ext[:, 1]], NEG))

    def step(a, t):
        stay = a
        prev1 = jnp.pad(a, ((0, 0), (1, 0)), constant_values=NEG)[:, :E]
        prev2 = jnp.pad(a, ((0, 0), (2, 0)), constant_values=NEG)[:, :E]
        prev2 = jnp.where(can_skip, prev2, NEG)
        m = jnp.maximum(stay, jnp.maximum(prev1, prev2))
        m_safe = jnp.maximum(m, NEG)
        summed = (jnp.exp(stay - m_safe) + jnp.exp(prev1 - m_safe)
                  + jnp.exp(prev2 - m_safe))
        new = m_safe + jnp.log(summed) + lp[:, t][
            jnp.arange(B)[:, None], ext]
        new = jnp.where(valid, new, NEG)
        live = (t < t_lens)[:, None]
        a = jnp.where(live, new, a)
        return a, None

    aT, _ = lax.scan(step, a0, jnp.arange(1, T))
    brange = jnp.arange(B)
    end1 = aT[brange, 2 * l_lens]          # final blank
    end2 = jnp.where(l_lens > 0,
                     aT[brange, jnp.clip(2 * l_lens - 1, 0, E - 1)], NEG)
    m = jnp.maximum(end1, end2)
    ll = m + jnp.log(jnp.exp(end1 - m) + jnp.exp(end2 - m))
    loss = -ll
    if attrs.get("norm_by_times"):
        loss = loss / t_lens.astype(jnp.float32)
    return {"Loss": [loss[:, None]]}


@register_op("ctc_align", stop_gradient=True)
def _ctc_align(ctx, ins, attrs):
    """Collapse repeats then drop blanks (ref ctc_align_op.cc).  Input
    [B,T] int token ids; output [B,T] with kept tokens left-packed and
    `padding_value` elsewhere (dense replacement for the LoD shrink)."""
    x = single_input(ins, "Input")
    if x.ndim == 3:
        x = x[..., 0]
    x = x.astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    pad = int(attrs.get("padding_value", 0))
    B, T = x.shape
    prev = jnp.pad(x, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    keep = (x != blank) & (x != prev)
    # left-pack via stable argsort on (not keep)
    order = jnp.argsort(~keep, axis=1, stable=True)
    packed = jnp.take_along_axis(x, order, axis=1)
    kept_sorted = jnp.take_along_axis(keep, order, axis=1)
    out = jnp.where(kept_sorted, packed, pad)
    return {"Output": [out]}


@register_op("chunk_eval", stop_gradient=True)
def _chunk_eval(ctx, ins, attrs):
    """Chunk-level precision/recall/F1 for IOB tagging (ref
    chunk_eval_op.cc, plain IOB scheme).  Inference/Label [B,T] int tag
    ids laid out as the reference's IOB: tag = chunk_type * 2 (+0 for B,
    +1 for I); num_chunk_types attr; `excluded_chunk_types` chunk types
    are remapped to Outside before counting.  Optional Mask [B,T]."""
    inf = single_input(ins, "Inference")
    lab = single_input(ins, "Label")
    if inf.ndim == 3:
        inf = inf[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    inf = inf.astype(jnp.int32)
    lab = lab.astype(jnp.int32)
    mask = (ins["Mask"][0].astype(jnp.bool_) if ins.get("Mask")
            else jnp.ones(inf.shape, jnp.bool_))
    n_types = int(attrs["num_chunk_types"])
    outside = 2 * n_types     # ids >= 2*num_chunk_types are Outside
    for ex in attrs.get("excluded_chunk_types", []) or []:
        inf = jnp.where(inf // 2 == int(ex), outside, inf)
        lab = jnp.where(lab // 2 == int(ex), outside, lab)

    def chunk_starts(tags):
        typ = tags // 2
        is_b = (tags % 2 == 0) & (tags < outside)
        prev = jnp.pad(tags, ((0, 0), (1, 0)),
                       constant_values=outside)[:, :tags.shape[1]]
        prev_typ = prev // 2
        is_i = (tags % 2 == 1) & (tags < outside)
        # I- starting a chunk (after O or different type) counts as start
        i_start = is_i & ((prev >= outside) | (prev_typ != typ))
        return (is_b | i_start) & mask

    def members(tags):
        return (tags < outside) & mask

    inf_starts = chunk_starts(inf)
    lab_starts = chunk_starts(lab)
    inf_in, lab_in = members(inf), members(lab)
    T = inf.shape[1]
    nxt_inf = jnp.pad(inf_starts | ~inf_in, ((0, 0), (0, 1)),
                      constant_values=True)[:, 1:]
    nxt_lab = jnp.pad(lab_starts | ~lab_in, ((0, 0), (0, 1)),
                      constant_values=True)[:, 1:]
    inf_end = inf_in & nxt_inf           # chunk's last position
    lab_end = lab_in & nxt_lab
    type_eq = (inf // 2) == (lab // 2)

    # one scan: track whether the currently-open chunk pair still matches
    def step(carry, t):
        in_ok, count = carry
        both_start = inf_starts[:, t] & lab_starts[:, t] & type_eq[:, t]
        cont_ok = (in_ok & inf_in[:, t] & lab_in[:, t]
                   & ~inf_starts[:, t] & ~lab_starts[:, t]
                   & type_eq[:, t])
        in_ok = both_start | cont_ok
        close = in_ok & inf_end[:, t] & lab_end[:, t]
        count = count + close.astype(index_dtype())
        in_ok = in_ok & ~close
        return (in_ok, count), None

    init = (jnp.zeros((inf.shape[0],), jnp.bool_),
            jnp.zeros((inf.shape[0],), index_dtype()))
    (_, counts), _ = lax.scan(step, init, jnp.arange(T))
    correct = jnp.sum(counts)
    num_inf = jnp.sum(inf_starts.astype(index_dtype()))
    num_lab = jnp.sum(lab_starts.astype(index_dtype()))
    precision = correct / jnp.maximum(num_inf, 1)
    recall = correct / jnp.maximum(num_lab, 1)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    return {"Precision": [precision.astype(jnp.float32).reshape(1)],
            "Recall": [recall.astype(jnp.float32).reshape(1)],
            "F1-Score": [f1.astype(jnp.float32).reshape(1)],
            "NumInferChunks": [num_inf.reshape(1)],
            "NumLabelChunks": [num_lab.reshape(1)],
            "NumCorrectChunks": [correct.reshape(1)]}
