"""Op library: every module registers its ops on import.

Capability parity target: the reference's op census
(/root/reference/paddle/fluid/operators/, ~330 ops — see SURVEY.md §2.3).
Each op here is a single `lower` function emitting jax/XLA (or Pallas); see
framework/registry.py for why that replaces per-device kernel registration.
"""
from . import structural  # feed/fetch/autodiff pseudo-ops
from . import creation
from . import elementwise
from . import activation
from . import math_ops
from . import reduce_ops
from . import tensor_manip
from . import nn_ops
from . import loss_ops
from . import metric_ops
from . import optimizer_ops
from . import control_flow
from . import rnn_ops
from . import sequence_ops
from . import beam_search_ops
from . import crf_ops
from . import sampling_ops
from . import misc_ops
from . import detection_ops
from . import collective_ops
from . import attention_ops
from . import quantize_ops
from . import fused_ops
