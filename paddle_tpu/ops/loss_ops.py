"""Loss ops.

Parity: cross_entropy (operators/cross_entropy_op.cc),
softmax_with_cross_entropy (softmax_with_cross_entropy_op.cc — fused,
numerically-stable path; the TPU version is exactly the log-softmax fusion
XLA produces), sigmoid_cross_entropy_with_logits, square_error_cost,
smooth_l1, huber_loss, log_loss, hinge_loss, modified_huber_loss, bpr_loss,
margin_rank_loss, rank_loss, mse_loss, kldiv_loss, npair/center etc. later.
Label convention follows the reference: integer labels have a trailing dim
of 1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op, single_input


def _squeeze_label(label):
    if label.ndim >= 2 and label.shape[-1] == 1:
        return label.squeeze(-1)
    return label


@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    """X is a probability distribution (post-softmax)."""
    x = single_input(ins)
    label = single_input(ins, "Label")
    ignore_index = int(attrs.get("ignore_index", -100))
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + 1e-20), axis=-1, keepdims=True)
    else:
        lab = _squeeze_label(label).astype(jnp.int32)
        picked = jnp.take_along_axis(x, lab[..., None], axis=-1)
        loss = -jnp.log(picked + 1e-20)
        loss = jnp.where(lab[..., None] == ignore_index, 0.0, loss)
    return {"Y": [loss]}


@register_op("softmax_with_cross_entropy")
def _softmax_xent(ctx, ins, attrs):
    logits = single_input(ins, "Logits")
    label = single_input(ins, "Label")
    ignore_index = int(attrs.get("ignore_index", -100))
    log_p = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    softmax = jnp.exp(log_p)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * log_p, axis=-1, keepdims=True)
    else:
        lab = _squeeze_label(label).astype(jnp.int32)
        picked = jnp.take_along_axis(log_p, lab[..., None], axis=-1)
        loss = -picked
        loss = jnp.where(lab[..., None] == ignore_index, 0.0, loss)
    return {"Loss": [loss.astype(logits.dtype)],
            "Softmax": [softmax.astype(logits.dtype)]}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_xent(ctx, ins, attrs):
    x = single_input(ins)
    label = single_input(ins, "Label")
    ignore_index = int(attrs.get("ignore_index", -100))
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    loss = jnp.where(label == ignore_index, 0.0, loss)
    if attrs.get("normalize", False):
        norm = jnp.maximum(jnp.sum((label != ignore_index)
                                   .astype(loss.dtype)), 1.0)
        loss = loss / norm
    return {"Out": [loss]}


@register_op("square_error_cost")
def _square_error_cost(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    return {"Out": [jnp.square(x - label)]}


@register_op("mse_loss")
def _mse_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    return {"Out": [jnp.mean(jnp.square(x - label))]}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    """ref smooth_l1_loss_op.cc; sigma2-weighted huber on (X - Y)."""
    x, y = ins["X"][0], ins["Y"][0]
    sigma = float(attrs.get("sigma", 1.0))
    s2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight"):
        diff = diff * ins["InsideWeight"][0]
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / s2, 0.5 * s2 * jnp.square(diff),
                    ad - 0.5 / s2)
    if ins.get("OutsideWeight"):
        val = val * ins["OutsideWeight"][0]
    out = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [out], "Diff": [diff]}


@register_op("huber_loss")
def _huber(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = float(attrs.get("delta", 1.0))
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * jnp.square(r),
                    delta * (ar - 0.5 * delta))
    return {"Out": [out], "Residual": [r]}


@register_op("log_loss")
def _log_loss(ctx, ins, attrs):
    p = single_input(ins, "Predicted")
    label = single_input(ins, "Labels")
    eps = float(attrs.get("epsilon", 1e-4))
    out = (-label * jnp.log(p + eps)
           - (1 - label) * jnp.log(1 - p + eps))
    return {"Loss": [out]}


@register_op("hinge_loss")
def _hinge(ctx, ins, attrs):
    logits = single_input(ins, "Logits")
    label = single_input(ins, "Labels")
    signed = 2.0 * label - 1.0
    return {"Loss": [jax.nn.relu(1.0 - signed * logits)]}


@register_op("modified_huber_loss")
def _modified_huber(ctx, ins, attrs):
    x = single_input(ins)
    y = single_input(ins, "Y")
    signed = 2.0 * y - 1.0
    z = x * signed
    out = jnp.where(z >= -1.0, jnp.square(jax.nn.relu(1.0 - z)), -4.0 * z)
    return {"Out": [out], "IntermediateVal": [z]}


@register_op("bpr_loss")
def _bpr(ctx, ins, attrs):
    """Bayesian personalized ranking (ref bpr_loss_op.cc)."""
    x = single_input(ins)
    label = _squeeze_label(single_input(ins, "Label")).astype(jnp.int32)
    pos = jnp.take_along_axis(x, label[..., None], axis=-1)
    diff = x - pos
    loss = jnp.mean(jnp.log1p(jnp.exp(diff)), axis=-1, keepdims=True)
    return {"Y": [loss]}


@register_op("margin_rank_loss")
def _margin_rank(ctx, ins, attrs):
    x1, x2 = ins["X1"][0], ins["X2"][0]
    label = single_input(ins, "Label")
    margin = float(attrs.get("margin", 0.0))
    out = jax.nn.relu(-label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    label = single_input(ins, "Label")
    left, right = ins["Left"][0], ins["Right"][0]
    d = left - right
    out = jnp.log1p(jnp.exp(d)) - label * d
    return {"Out": [out]}


@register_op("kldiv_loss")
def _kldiv(ctx, ins, attrs):
    x = single_input(ins)
    target = single_input(ins, "Target")
    loss = target * (jnp.log(target + 1e-20) - x)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    return {"Loss": [loss]}


@register_op("npair_loss")
def _npair(ctx, ins, attrs):
    anchor = single_input(ins, "Anchor")
    positive = single_input(ins, "Positive")
    labels = single_input(ins, "Labels").astype(jnp.float32)
    l2 = float(attrs.get("l2_reg", 0.002))
    sim = anchor @ positive.T
    lab = labels.reshape(-1, 1)
    same = (lab == lab.T).astype(jnp.float32)
    same = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    xent = -jnp.mean(jnp.sum(same * logp, axis=1))
    reg = l2 * (jnp.mean(jnp.sum(jnp.square(anchor), 1))
                + jnp.mean(jnp.sum(jnp.square(positive), 1))) / 2.0
    return {"Out": [xent + reg]}
