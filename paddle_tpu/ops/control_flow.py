"""Control-flow ops: structured, compiler-friendly loops/branches.

Parity: operators/controlflow/ (while_op.cc, conditional_block_op.cc,
compare/logical ops live in ops/elementwise.py) and the RNN substrate
(recurrent_op.cc).

TPU-first design: the reference's while/conditional run a sub-block through
a nested Executor with per-iteration scopes.  Here sub-blocks lower into
lax.while_loop / lax.cond / lax.scan with an explicit carry — the set of
vars the sub-block writes.  Shapes must be loop-invariant (XLA requirement),
which the reference's TensorArray-style dynamic shapes violate; the
DynamicRNN capability is covered by `scan` over padded/packed sequences
(see layers/control_flow.py StaticRNN).
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from ..framework.registry import register_op, single_input


def _lower_block(ctx, env: Dict, block) -> Dict:
    """Run a sub-block's ops against an env copy; returns the final env."""
    from ..framework.executor import run_ops_in_env  # shared lowering loop
    return run_ops_in_env(ctx, env, block.ops)


def _block_written_vars(block) -> List[str]:
    written = []
    for op in block.ops:
        for names in op.outputs.values():
            for n in names:
                if n and n not in written:
                    written.append(n)
    return written


@register_op("while")
def _while(ctx, ins, attrs):
    """attrs: sub_block (block idx), condition (var name).
    Carry = condition var + every var written in the sub-block that already
    exists outside (loop-carried state)."""
    program = ctx.program
    block = program.blocks[int(attrs["sub_block"])]
    cond_name = attrs["condition"]
    env = ctx.env  # the executor exposes the live env to control-flow ops
    written = _block_written_vars(block)
    carried = [n for n in written if n in env]
    if cond_name not in carried and cond_name in env:
        carried.append(cond_name)

    def cond_fn(carry):
        return carry[cond_name].reshape(())

    def body_fn(carry):
        benv = dict(env)
        benv.update(carry)
        benv = _lower_block(ctx, benv, block)
        return {n: benv[n] for n in carried}

    init = {n: env[n] for n in carried}
    final = jax.lax.while_loop(cond_fn, body_fn, init)
    # one produced value per out_vars entry (run_ops_in_env zips them in
    # order); vars that could not be carried pass through unchanged
    out_vars = attrs.get("out_vars", carried)
    outs = []
    for n in out_vars:
        if n in final:
            outs.append(final[n])
        elif n in env:
            outs.append(env[n])
        else:
            from ..core.enforce import EnforceNotMet
            raise EnforceNotMet(
                f"while loop output {n!r} has no value before the loop; "
                f"initialise it (e.g. fill_constant) so it can be carried")
    return {"Out": outs}


@register_op("conditional_block")
def _conditional_block(ctx, ins, attrs):
    """attrs: sub_block; Cond input scalar bool.  Vars written by the block
    are emitted through 'Out' (attrs out_vars order); when the condition is
    false the pre-existing values (or zeros) pass through."""
    program = ctx.program
    block = program.blocks[int(attrs["sub_block"])]
    cond = single_input(ins, "Cond").reshape(())
    env = ctx.env
    out_vars = attrs["out_vars"]

    def then_fn(_):
        benv = _lower_block(ctx, dict(env), block)
        return tuple(benv[n] for n in out_vars)

    # else-branch shapes come from abstract-evaluating the then-branch —
    # robust for sub-block-local temps that exist nowhere else
    out_abs = jax.eval_shape(then_fn, None)

    def else_fn(_):
        return tuple(env[n] if n in env else jnp.zeros(a.shape, a.dtype)
                     for n, a in zip(out_vars, out_abs))

    outs = jax.lax.cond(cond, then_fn, else_fn, operand=None)
    return {"Out": list(outs)}


@register_op("scan")
def _scan(ctx, ins, attrs):
    """TPU-native sequence loop: lax.scan over the leading time axis.
    attrs: sub_block, carry_vars (names), x_vars (scanned inputs -> block
    var names), y_vars (per-step outputs collected).
    This is the engine under StaticRNN/DynamicRNN-capability
    (ref operators/recurrent_op.cc — per-timestep scopes become the carry)."""
    program = ctx.program
    block = program.blocks[int(attrs["sub_block"])]
    env = ctx.env
    carry_names = list(attrs["carry_vars"])
    x_names = list(attrs.get("x_vars", []))
    y_names = list(attrs.get("y_vars", []))
    xs = {n: env[n] for n in x_names}

    def body(carry, x_t):
        benv = dict(env)
        benv.update(carry)
        benv.update(x_t)
        benv = _lower_block(ctx, benv, block)
        new_carry = {n: benv[n] for n in carry_names}
        ys = tuple(benv[n] for n in y_names)
        return new_carry, ys

    init = {n: env[n] for n in carry_names}
    final_carry, ys = jax.lax.scan(body, init, xs)
    return {"CarryOut": [final_carry[n] for n in carry_names],
            "Ys": list(ys)}


@register_op("static_rnn_scan")
def _static_rnn_scan(ctx, ins, attrs):
    """The engine under layers.StaticRNN: lax.scan with explicit init
    values and scanned inputs (ref operators/recurrent_op.cc — per-timestep
    scopes become the carry).

    Inputs: Init (one value per memory), X (scanned [T, B, ...] arrays).
    attrs: sub_block, carry_vars (inner memory var names), x_inner_vars
    (inner per-step var names, aligned with X), y_vars (per-step outputs)."""
    program = ctx.program
    block = program.blocks[int(attrs["sub_block"])]
    env = ctx.env
    carry_names = list(attrs["carry_vars"])
    x_inner = list(attrs.get("x_inner_vars", []))
    y_names = list(attrs.get("y_vars", []))
    inits = tuple(ins.get("Init", []))
    xs = tuple(ins.get("X", []))

    def body(carry, x_t):
        benv = dict(env)
        benv.update(dict(zip(carry_names, carry)))
        benv.update(dict(zip(x_inner, x_t)))
        benv = _lower_block(ctx, benv, block)
        new_carry = tuple(benv[n] for n in carry_names)
        return new_carry, tuple(benv[n] for n in y_names)

    final_carry, ys = jax.lax.scan(body, inits, xs)
    return {"Ys": list(ys), "CarryOut": list(final_carry)}


@register_op("increment_loop_counter")
def _increment_counter(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [x + attrs.get("step", 1)]}
