"""Shape/layout/indexing manipulation ops.

Parity: reshape, transpose, concat, split, stack, unstack, squeeze,
unsqueeze, flatten, expand, expand_as, slice, gather, gather_nd, scatter,
scatter_nd_add, pad, pad2d, pad_constant_like, crop, reverse, flip,
multiplex, space_to_depth, unbind, tile, roll, where, masked_select-era
is_empty, shard_index (/root/reference/paddle/fluid/operators/*.cc).
All are pure layout ops — XLA folds most of them into surrounding fusions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import index_dtype
from ..framework.registry import register_op, single_input


@register_op("reshape")
def _reshape(ctx, ins, attrs):
    x = single_input(ins)
    shape = list(attrs["shape"])
    # ref reshape semantics: 0 means copy input dim at that position
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": [x.reshape(shape)]}


@register_op("reshape2")
def _reshape2(ctx, ins, attrs):
    x = single_input(ins)
    shape = list(attrs["shape"])
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": [x.reshape(shape)],
            "XShape": [jnp.asarray(x.shape, index_dtype())]}


@register_op("transpose")
def _transpose(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.transpose(x, attrs["axis"])]}


@register_op("transpose2")
def _transpose2(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.transpose(x, attrs["axis"])],
            "XShape": [jnp.asarray(x.shape, index_dtype())]}


@register_op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=int(attrs.get("axis", 0)))]}


@register_op("split")
def _split(ctx, ins, attrs):
    x = single_input(ins)
    axis = int(attrs.get("axis", 0))
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, int(num), axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=int(attrs.get("axis", 0)))]}


@register_op("unstack")
def _unstack(ctx, ins, attrs):
    x = single_input(ins)
    axis = int(attrs.get("axis", 0))
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis)]}


@register_op("unbind")
def _unbind(ctx, ins, attrs):
    return _unstack(ctx, ins, attrs)


@register_op("squeeze")
def _squeeze(ctx, ins, attrs):
    x = single_input(ins)
    axes = attrs.get("axes", [])
    if axes:
        for ax in sorted((a % x.ndim for a in axes), reverse=True):
            if x.shape[ax] == 1:
                x = jnp.squeeze(x, ax)
    else:
        x = jnp.squeeze(x)
    return {"Out": [x]}


@register_op("squeeze2")
def _squeeze2(ctx, ins, attrs):
    orig = single_input(ins)
    out = _squeeze(ctx, ins, attrs)["Out"]
    return {"Out": out, "XShape": [jnp.asarray(orig.shape, index_dtype())]}


@register_op("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    x = single_input(ins)
    for ax in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, ax)
    return {"Out": [x]}


@register_op("unsqueeze2")
def _unsqueeze2(ctx, ins, attrs):
    orig = single_input(ins)
    out = _unsqueeze(ctx, ins, attrs)["Out"]
    return {"Out": out, "XShape": [jnp.asarray(orig.shape, index_dtype())]}


@register_op("flatten")
def _flatten(ctx, ins, attrs):
    x = single_input(ins)
    axis = int(attrs.get("axis", 1))
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return {"Out": [x.reshape(lead, -1)]}


@register_op("flatten2")
def _flatten2(ctx, ins, attrs):
    orig = single_input(ins)
    out = _flatten(ctx, ins, attrs)["Out"]
    return {"Out": out, "XShape": [jnp.asarray(orig.shape, index_dtype())]}


@register_op("flatten_contiguous_range")
def _flatten_range(ctx, ins, attrs):
    x = single_input(ins)
    start = int(attrs.get("start_axis", 1)) % x.ndim
    stop = int(attrs.get("stop_axis", -1)) % x.ndim
    shape = (x.shape[:start] + (int(np.prod(x.shape[start:stop + 1])),)
             + x.shape[stop + 1:])
    return {"Out": [x.reshape(shape)]}


@register_op("expand")
def _expand(ctx, ins, attrs):
    """ref expand_op.cc: tile each dim by expand_times."""
    x = single_input(ins)
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register_op("expand_as")
def _expand_as(ctx, ins, attrs):
    x = single_input(ins)
    target = single_input(ins, "target_tensor" if "target_tensor" in ins
                          else "Y")
    return {"Out": [jnp.broadcast_to(x, target.shape)]}


@register_op("tile")
def _tile(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.tile(x, attrs["repeat_times"])]}


@register_op("slice")
def _slice(ctx, ins, attrs):
    """ref slice_op.cc: static begin/end per listed axis."""
    x = single_input(ins, "Input")
    axes = attrs["axes"]
    starts, ends = attrs["starts"], attrs["ends"]
    idx = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        dim = x.shape[ax]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[ax] = slice(s, e)
    out = x[tuple(idx)]
    for ax in sorted(attrs.get("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, ax)
    return {"Out": [out]}


@register_op("strided_slice")
def _strided_slice(ctx, ins, attrs):
    x = single_input(ins, "Input")
    idx = [slice(None)] * x.ndim
    for ax, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                            attrs["strides"]):
        idx[ax] = slice(s, e, st)
    return {"Out": [x[tuple(idx)]]}


@register_op("gather")
def _gather(ctx, ins, attrs):
    x = single_input(ins)
    idx = single_input(ins, "Index")
    axis = int(attrs.get("axis", 0))
    if idx.ndim == 2 and idx.shape[-1] == 1:
        idx = idx.squeeze(-1)
    return {"Out": [jnp.take(x, idx.astype(jnp.int32), axis=axis)]}


@register_op("gather_nd")
def _gather_nd(ctx, ins, attrs):
    x = single_input(ins)
    idx = single_input(ins, "Index").astype(jnp.int32)
    return {"Out": [x[tuple(jnp.moveaxis(idx, -1, 0))]]}


@register_op("scatter")
def _scatter(ctx, ins, attrs):
    """ref scatter_op.cc: overwrite (default) or add rows of X at Ids."""
    x = single_input(ins)
    ids = single_input(ins, "Ids").astype(jnp.int32)
    upd = single_input(ins, "Updates")
    if ids.ndim == 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    if attrs.get("overwrite", True):
        out = x.at[ids].set(upd)
    else:
        out = x.at[ids].add(upd)
    return {"Out": [out]}


@register_op("scatter_nd_add")
def _scatter_nd_add(ctx, ins, attrs):
    x = single_input(ins)
    idx = single_input(ins, "Index").astype(jnp.int32)
    upd = single_input(ins, "Updates")
    return {"Out": [x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)]}


@register_op("pad")
def _pad(ctx, ins, attrs):
    x = single_input(ins)
    p = attrs["paddings"]  # [d0_lo, d0_hi, d1_lo, d1_hi, ...]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pads, constant_values=attrs.get(
        "pad_value", 0.0))]}


@register_op("pad2d")
def _pad2d(ctx, ins, attrs):
    """NCHW spatial pad, modes constant/reflect/edge (ref pad2d_op.cc)."""
    x = single_input(ins)
    t, b, l, r = attrs["paddings"]
    mode = attrs.get("mode", "constant")
    pads = [(0, 0), (0, 0), (t, b), (l, r)]
    if mode == "constant":
        return {"Out": [jnp.pad(x, pads,
                                constant_values=attrs.get("pad_value", 0.0))]}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": [jnp.pad(x, pads, mode=jmode)]}


@register_op("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pads,
                            constant_values=attrs.get("pad_value", 0.0))]}


@register_op("crop")
def _crop(ctx, ins, attrs):
    x = single_input(ins)
    offsets = attrs["offsets"]
    shape = attrs["shape"]
    # -1 in shape keeps the full dimension (the batch-dim idiom, ref
    # crop_op.cc shape semantics)
    idx = tuple(slice(o, None if s == -1 else o + s)
                for o, s in zip(offsets, shape))
    return {"Out": [x[idx]]}


@register_op("reverse")
def _reverse(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.flip(x, axis=tuple(attrs["axis"]))]}


@register_op("flip")
def _flip(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.flip(x, axis=tuple(attrs["axis"]))]}


@register_op("roll")
def _roll(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.roll(x, attrs["shifts"],
                             axis=tuple(attrs.get("axis", [0])))]}


@register_op("multiplex")
def _multiplex(ctx, ins, attrs):
    """Row-wise select among candidate tensors by Ids (ref multiplex_op.cc)."""
    ids = single_input(ins, "Ids").astype(jnp.int32).reshape(-1)
    stacked = jnp.stack(ins["X"], axis=0)  # (K, N, ...)
    rows = jnp.arange(stacked.shape[1])
    return {"Out": [stacked[ids, rows]]}


@register_op("where")
def _where(ctx, ins, attrs):
    c = single_input(ins, "Condition")
    return {"Out": [jnp.where(c, ins["X"][0], ins["Y"][0])]}


@register_op("where_index", stop_gradient=True)
def _where_index(ctx, ins, attrs):
    """Nonzero indices — needs static size; gated for in-jit use."""
    c = single_input(ins, "Condition")
    n = int(np.prod(c.shape))
    idx = jnp.nonzero(c, size=n, fill_value=-1)
    return {"Out": [jnp.stack(idx, axis=-1).astype(index_dtype())]}


@register_op("space_to_depth")
def _space_to_depth(ctx, ins, attrs):
    x = single_input(ins)  # NCHW
    bs = int(attrs["blocksize"])
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": [x.reshape(n, c * bs * bs, h // bs, w // bs)]}


@register_op("pixel_shuffle")
def _pixel_shuffle(ctx, ins, attrs):
    x = single_input(ins)  # NCHW
    r = int(attrs["upscale_factor"])
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return {"Out": [x.reshape(n, c // (r * r), h * r, w * r)]}


@register_op("is_empty", stop_gradient=True)
def _is_empty(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jnp.asarray(int(np.prod(x.shape)) == 0)]}


@register_op("shard_index", stop_gradient=True)
def _shard_index(ctx, ins, attrs):
    """Map global ids to shard-local ids (ref shard_index_op.cc) — the
    building block for sharded embedding lookups."""
    x = single_input(ins)
    index_num = int(attrs["index_num"])
    nshards = int(attrs["nshards"])
    shard_id = int(attrs["shard_id"])
    ignore = int(attrs.get("ignore_value", -1))
    size = (index_num + nshards - 1) // nshards
    in_shard = (x // size) == shard_id
    return {"Out": [jnp.where(in_shard, x % size, ignore)]}
