"""The reference's fused-op family (operators/fused/) + remaining
census stragglers.

Parity targets: fused_elemwise_activation_op.cc, conv_fusion_op.cc
(conv2d_fusion), fusion_gru_op.cc, fusion_lstm_op.cc,
fusion_seqconv_eltadd_relu_op.cc, fusion_seqexpand_concat_fc_op.cc,
fusion_transpose_flatten_concat_op.cc, fused_embedding_fc_lstm_op.cc,
attention_lstm_op.cc, fc_op.cc (the mkldnn fused fc),
conv_transpose_op.cc (depthwise_conv2d_transpose),
fake_quantize_op.cc (range_abs_max variant), fake_init_op.cc,
rnn_memory_helper_op.cc, tensor_array_read_write_op.cc
(read_from_array / write_to_array), save_op.cc / load_op.cc /
save_combine_op.cc / load_combine_op.cc.

TPU-first note: on GPU these exist because kernel-launch overhead and
cuDNN coverage made hand-fusion pay; under XLA most of them would fuse
anyway.  They are still real ops here — programs serialized by the
reference-style frontend name them — each lowering COMPOSES the
already-registered primitive lowerings, so there is exactly one
implementation of every primitive (one lstm scan, one conv, ...).
Save/load are host-side io_callbacks so checkpoint-inside-program
(the reference's save/load-as-ops contract) works under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import get_op_def, register_op, single_input

_ACTS = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
         "tanh": jnp.tanh, "identity": lambda x: x, "": lambda x: x}


def _sub(op_type, ctx, ins, attrs):
    """Invoke another registered op's lowering (composition helper)."""
    return get_op_def(op_type).lower(ctx, ins, attrs)


@register_op("fc")
def _fc(ctx, ins, attrs):
    """ref fc_op.cc (the fused mul+bias(+act) op the mkldnn path used;
    the layers DSL normally emits mul+elementwise_add instead)."""
    x = single_input(ins, "Input")
    w = single_input(ins, "W")
    out = _sub("mul", ctx, {"X": [x], "Y": [w]},
               {"x_num_col_dims": int(attrs.get("in_num_col_dims", 1)),
                "y_num_col_dims": 1})["Out"][0]
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [_ACTS[attrs.get("activation_type", "")](out)]}


@register_op("fused_elemwise_activation")
def _fused_elemwise_activation(ctx, ins, attrs):
    """ref fused/fused_elemwise_activation_op.cc — functor_list[0] is
    the OUTER function: ['elementwise_add', 'relu'] -> x + relu(y);
    ['relu', 'elementwise_add'] -> relu(x + y)."""
    x, y = ins["X"][0], ins["Y"][0]
    functors = list(attrs.get("functor_list", ["elementwise_add", "relu"]))
    binary = next((f for f in functors if f.startswith("elementwise")),
                  None)
    if binary is None:
        from ..core.enforce import EnforceNotMet
        raise EnforceNotMet(
            f"fused_elemwise_activation needs one elementwise_* functor, "
            f"got {functors}")
    unary = next((f for f in functors if not f.startswith("elementwise")),
                 "identity")
    # attrs pass through to BOTH functors (scale's `scale`, leaky_relu's
    # `alpha`, the broadcast `axis`, ...).  Reference order contract
    # (fused_elemwise_activation_op.h IsUnaryCompound): functor_list[0]
    # is the OUTER function.
    sub_attrs = dict(attrs)
    if functors[0] == binary:          # binop(x, act(y))
        ya = _sub(unary, ctx, {"X": [y]}, sub_attrs)["Out"][0]
        out = _sub(binary, ctx, {"X": [x], "Y": [ya]},
                   sub_attrs)["Out"][0]
    else:                              # act(binop(x, y))
        out = _sub(binary, ctx, {"X": [x], "Y": [y]}, sub_attrs)["Out"][0]
        out = _sub(unary, ctx, {"X": [out]}, sub_attrs)["Out"][0]
    return {"Out": [out]}


@register_op("conv2d_fusion")
def _conv2d_fusion(ctx, ins, attrs):
    """ref conv_fusion_op.cc: conv + bias + activation (+ residual)."""
    out = _sub("conv2d", ctx,
               {"Input": ins["Input"], "Filter": ins["Filter"]},
               attrs)["Output"][0]
    if ins.get("Bias"):
        b = ins["Bias"][0]
        out = out + b.reshape(1, -1, *([1] * (out.ndim - 2)))
    if ins.get("ResidualData"):
        out = out + ins["ResidualData"][0]
    return {"Output": [_ACTS[attrs.get("activation", "relu")](out)]}


@register_op("fusion_lstm")
def _fusion_lstm(ctx, ins, attrs):
    """ref fused/fusion_lstm_op.cc: x-projection fc fused with the lstm
    scan.  X [B,T,D], WeightX [D,4H], WeightH [H,4H], Bias [4H]."""
    x = single_input(ins, "X")
    wx = single_input(ins, "WeightX")
    xp = _sub("mul", ctx, {"X": [x], "Y": [wx]},
              {"x_num_col_dims": 2, "y_num_col_dims": 1})["Out"][0]
    if ins.get("Bias"):
        xp = xp + ins["Bias"][0]
    sub_ins = {"Input": [xp], "Weight": ins["WeightH"]}
    for slot in ("H0", "C0", "Mask"):
        if ins.get(slot):
            sub_ins[slot] = ins[slot]
    r = _sub("lstm", ctx, sub_ins, attrs)
    return {"Hidden": r["Hidden"], "Cell": r["Cell"],
            "LastH": r["LastH"], "LastC": r["LastC"]}


@register_op("fusion_gru")
def _fusion_gru(ctx, ins, attrs):
    """ref fused/fusion_gru_op.cc: x-projection fc fused with the gru
    scan.  X [B,T,D], WeightX [D,3H], WeightH [H,3H], Bias [3H]."""
    x = single_input(ins, "X")
    wx = single_input(ins, "WeightX")
    xp = _sub("mul", ctx, {"X": [x], "Y": [wx]},
              {"x_num_col_dims": 2, "y_num_col_dims": 1})["Out"][0]
    if ins.get("Bias"):
        xp = xp + ins["Bias"][0]
    sub_ins = {"Input": [xp], "Weight": ins["WeightH"]}
    for slot in ("H0", "Mask"):
        if ins.get(slot):
            sub_ins[slot] = ins[slot]
    r = _sub("gru", ctx, sub_ins, attrs)
    return {"Hidden": r["Hidden"]}


@register_op("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm(ctx, ins, attrs):
    """ref fused/fused_embedding_fc_lstm_op.cc: embedding lookup of Ids
    fused with the x-projection and the lstm scan.  Embeddings slot
    holds the PRE-PROJECTED table (vocab, 4H) — the reference folds
    W_x into the table offline; Bias [4H], WeightH [H,4H]."""
    ids = single_input(ins, "Ids").astype(jnp.int32)
    table = single_input(ins, "Embeddings")
    if ids.ndim == 3:
        ids = ids[..., 0]
    xp = jnp.take(table, ids, axis=0)          # [B,T,4H]
    if ins.get("Bias"):
        xp = xp + ins["Bias"][0]
    sub_ins = {"Input": [xp], "Weight": ins["WeightH"]}
    for slot in ("H0", "C0", "Mask"):
        if ins.get(slot):
            sub_ins[slot] = ins[slot]
    r = _sub("lstm", ctx, sub_ins, attrs)
    return {"Hidden": r["Hidden"], "Cell": r["Cell"]}


@register_op("attention_lstm")
def _attention_lstm(ctx, ins, attrs):
    """ref fused/attention_lstm_op.cc (simplified dense): per step,
    softmax(fc([x_t; h])) over the memory X pools a context vector that
    feeds an LSTM cell.  X [B,T,D] (memory = the input sequence),
    AttentionWeight [D+H, 1], LSTMWeight [D+H, 4H], LSTMBias [4H]."""
    x = single_input(ins, "X")
    aw = single_input(ins, "AttentionWeight")
    lw = single_input(ins, "LSTMWeight")
    lb = (ins["LSTMBias"][0] if ins.get("LSTMBias") else 0.0)
    B, T, D = x.shape
    H = lw.shape[1] // 4
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, H), x.dtype)
    # the x-part of the score is loop-invariant: project once, add the
    # h-part per step (no per-step [B,T,D+H] concat)
    sx = jnp.einsum("btd,dk->btk", x, aw[:D])[..., 0]          # [B,T]

    def step(carry, _):
        h, c = carry
        score = sx + (h @ aw[D:])                              # [B,T]+[B,1]
        alpha = jax.nn.softmax(score, axis=1)
        ctx_vec = jnp.einsum("bt,btd->bd", alpha, x)           # [B,D]
        gates = jnp.concatenate([ctx_vec, h], axis=-1) @ lw + lb
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = jax.lax.scan(step, (h0, c0), None,
                                              length=T)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)],
            "LastH": [h_last], "LastC": [c_last]}


@register_op("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ctx, ins, attrs):
    """ref fused/fusion_seqconv_eltadd_relu_op.cc."""
    out = _sub("sequence_conv", ctx,
               {"X": ins["X"], "Filter": ins["Filter"]}, attrs)["Out"][0]
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [jax.nn.relu(out)]}


@register_op("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """ref fused/fusion_seqexpand_concat_fc_op.cc: broadcast the row
    inputs along X[0]'s time axis, concat features, one fc."""
    xs = ins["X"]
    ref_seq = xs[0]                                 # [B,T,D0]
    T = ref_seq.shape[1]
    feats = [ref_seq]
    for x in xs[1:]:
        feats.append(jnp.broadcast_to(
            x[:, None], (x.shape[0], T, x.shape[-1])))
    cat = jnp.concatenate(feats, axis=-1)
    w = single_input(ins, "FCWeight")
    out = jnp.einsum("btd,dk->btk", cat, w)
    if ins.get("FCBias"):
        out = out + ins["FCBias"][0]
    return {"Out": [_ACTS[attrs.get("fc_activation", "identity")](out)]}


@register_op("fusion_transpose_flatten_concat", stop_gradient=True)
def _fusion_transpose_flatten_concat(ctx, ins, attrs):
    """ref fused/fusion_transpose_flatten_concat_op.cc."""
    trans = list(attrs.get("trans_axis", []))
    flatten_axis = int(attrs.get("flatten_axis", 1))
    concat_axis = int(attrs.get("concat_axis", 1))
    outs = []
    for x in ins["X"]:
        if trans:
            x = jnp.transpose(x, trans)
        lead = int(np.prod(x.shape[:flatten_axis]))
        outs.append(x.reshape(lead, -1))
    return {"Out": [jnp.concatenate(outs, axis=concat_axis)]}


@register_op("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ctx, ins, attrs):
    """ref conv_transpose_op.cc depthwise variant: groups == channels."""
    x = single_input(ins, "Input")
    return _sub("conv2d_transpose", ctx, ins,
                dict(attrs, groups=int(x.shape[1])))


@register_op("fake_quantize_range_abs_max")
def _fake_quantize_range_abs_max(ctx, ins, attrs):
    """ref fake_quantize_op.cc range_abs_max: track |x|-max over the last
    `window_size` steps in a circular scale buffer and quantize against
    the window max.  Stateful form: feed Iter ([1] int step counter) and
    InScales ([window_size] history) — both are updated and re-emitted as
    OutScales/OutIter, matching the reference's Iter/OutScales contract.
    Stateless fallback (no Iter/InScales): monotone running max of
    InScale — a documented approximation that never decays (fine for
    inference-scale export, wrong for shrinking activations; see
    docs/PARITY.md)."""
    x = single_input(ins, "X")
    bits = int(attrs.get("bit_length", 8))
    window = int(attrs.get("window_size", 10000))
    qmax = float(2 ** (bits - 1) - 1)
    from .quantize_ops import _ste_round
    cur = jnp.max(jnp.abs(x))
    outs = {}
    if ins.get("Iter") and ins.get("InScales"):
        it = ins["Iter"][0].reshape(()).astype(jnp.int32)
        hist = ins["InScales"][0].reshape(-1)[:window]
        # The fed buffer's length is the effective window: indexing by the
        # attr when the buffer is shorter would silently drop the update.
        window = hist.shape[0]
        hist = hist.at[jnp.mod(it, window)].set(cur)
        seen = jnp.minimum(it + 1, window)
        valid = jnp.arange(hist.shape[0]) < seen
        scale = jnp.max(jnp.where(valid, hist, 0.0))
        outs["OutScales"] = [hist]
        outs["OutIter"] = [(it + 1).reshape(1)]
    else:
        in_scale = (ins["InScale"][0].reshape(()) if ins.get("InScale")
                    else cur)
        scale = jnp.maximum(cur, in_scale)
    q = jnp.clip(_ste_round(x / jnp.maximum(scale, 1e-8) * qmax),
                 -qmax, qmax)
    outs.update({"Out": [q * scale / qmax], "OutScale": [scale.reshape(1)]})
    return outs


@register_op("fake_init", stop_gradient=True)
def _fake_init(ctx, ins, attrs):
    """ref fake_init_op.cc: declare-without-filling (pserver startup);
    here it materializes zeros so the var exists."""
    from ..core.dtypes import to_jnp_dtype
    shape = tuple(attrs.get("shape", [1]))
    return {"Out": [jnp.zeros(shape,
                              to_jnp_dtype(attrs.get("dtype",
                                                     "float32")))]}


@register_op("rnn_memory_helper")
def _rnn_memory_helper(ctx, ins, attrs):
    """ref rnn_memory_helper_op.cc: identity used to thread RNN state
    across steps (grad is identity too, via jax.vjp)."""
    return {"Out": [single_input(ins, "X")]}


@register_op("write_to_array", stop_gradient=True)
def _write_to_array(ctx, ins, attrs):
    """ref tensor_array_read_write_op.cc, dense redesign: the 'array'
    var holds [N, ...] with I selecting the row, and consecutive writes
    THREAD the array explicitly — wire the previous write's Out into the
    next write's Array input (static shapes make the array a normal
    tensor, so there is no hidden mutable state to alias).  The first
    write of a fresh array instead passes the static `array_len` attr."""
    x = single_input(ins, "X")
    i = single_input(ins, "I").reshape(()).astype(jnp.int32)
    if ins.get("Array"):
        arr = ins["Array"][0]
    else:
        if "array_len" not in attrs:
            from ..core.enforce import EnforceNotMet
            raise EnforceNotMet(
                "write_to_array without an Array input needs the "
                "array_len attr (the fresh array's length); chained "
                "writes must thread the previous Out into Array")
        n = int(attrs["array_len"])
        arr = jnp.zeros((n,) + x.shape, x.dtype)
    return {"Out": [jax.lax.dynamic_update_index_in_dim(arr, x, i,
                                                        axis=0)]}


@register_op("read_from_array", stop_gradient=True)
def _read_from_array(ctx, ins, attrs):
    x = single_input(ins, "X")          # the [N, ...] array var
    i = single_input(ins, "I").reshape(()).astype(jnp.int32)
    return {"Out": [jax.lax.dynamic_index_in_dim(x, i, axis=0,
                                                 keepdims=False)]}


# -- save/load as ops (ref save_op.cc / load_op.cc) ------------------------

def _host_save(path, arr):
    import os
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.save(path, np.asarray(arr))
    return np.zeros((1,), np.int32)


@register_op("save", stop_gradient=True)
def _save(ctx, ins, attrs):
    """ref save_op.cc: persist one var during program execution (the
    checkpoint-as-ops contract).  Concrete values write directly;
    traced values go through io_callback (supported on the CPU backend
    and standard TPU runtimes; PJRT plugins without host callbacks must
    use pt.io.save_persistables instead)."""
    x = single_input(ins, "X")
    path = str(attrs["file_path"])
    if not isinstance(x, jax.core.Tracer):
        return {"Out": [jnp.asarray(_host_save(path, x))]}
    done = jax.experimental.io_callback(
        lambda a: _host_save(path, a), jax.ShapeDtypeStruct((1,),
                                                            jnp.int32), x,
        ordered=True)
    return {"Out": [done]}


@register_op("load", stop_gradient=True)
def _load(ctx, ins, attrs):
    """ref load_op.cc: requires static out shape/dtype attrs on TPU
    (XLA needs shapes at trace time)."""
    from ..core.dtypes import to_jnp_dtype
    path = str(attrs["file_path"])
    shape = tuple(attrs["shape"])
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    out = jax.experimental.io_callback(
        lambda: np.load(path + (".npy" if not path.endswith(".npy")
                                else "")).astype(dtype),
        jax.ShapeDtypeStruct(shape, dtype), ordered=True)
    return {"Out": [out]}


@register_op("save_combine", stop_gradient=True)
def _save_combine(ctx, ins, attrs):
    """ref save_combine_op.cc: many vars -> one file (.npz)."""
    xs = ins["X"]
    names = list(attrs.get("var_names",
                           [f"v{i}" for i in range(len(xs))]))
    path = str(attrs["file_path"])

    def host(*arrs):
        import os
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, **{n: np.asarray(a) for n, a in zip(names, arrs)})
        return np.zeros((1,), np.int32)

    done = jax.experimental.io_callback(
        host, jax.ShapeDtypeStruct((1,), jnp.int32), *xs, ordered=True)
    return {"Out": [done]}


@register_op("load_combine", stop_gradient=True)
def _load_combine(ctx, ins, attrs):
    """ref load_combine_op.cc: one .npz -> many vars (static shapes/
    dtypes from attrs)."""
    from ..core.dtypes import to_jnp_dtype
    path = str(attrs["file_path"])
    names = list(attrs["var_names"])
    shapes = [tuple(s) for s in attrs["shapes"]]
    dtypes = [to_jnp_dtype(d) for d in attrs.get(
        "dtypes", ["float32"] * len(names))]

    def host():
        z = np.load(path if path.endswith(".npz") else path + ".npz")
        return tuple(z[n].astype(d) for n, d in zip(names, dtypes))

    outs = jax.experimental.io_callback(
        host,
        tuple(jax.ShapeDtypeStruct(sh, d)
              for sh, d in zip(shapes, dtypes)),
        ordered=True)
    return {"Out": list(outs)}


@register_op("get_places", stop_gradient=True)
def _get_places(ctx, ins, attrs):
    """ref operators/get_places_op.cc: enumerate available devices (the
    v1 ParallelDo substrate).  Dense analogue: the local device count
    (capped by device_count attr), as an int32 scalar — placement itself
    is the mesh's job on TPU."""
    n = jax.local_device_count()
    cap = int(attrs.get("device_count", 0))
    if cap:
        n = min(n, cap)
    return {"Out": [jnp.asarray([n], jnp.int32)]}


@register_op("moe_ffn")
def _moe_ffn(ctx, ins, attrs):
    """Switch (top-1) mixture-of-experts FFN (TPU-native capability;
    the 2018 reference has no MoE).  X [B, T, D] or [N, D];
    Gate [D, E]; W1 [E(l), D, F]; W2 [E(l), F, D].  Outputs Out (X's
    shape) and AuxLoss [1] (load-balance loss, ALREADY scaled by
    aux_weight — add it to the training cost).

    Under ExpertParallelTranspiler the executor runs this inside
    shard_map with `ctx.ep_axis` in scope and W1/W2 sharded over the
    expert axis; dispatch/combine then ride all_to_all
    (parallel/moe.py).
    """
    from ..parallel.moe import switch_moe
    x = single_input(ins, "X")
    gate_w = single_input(ins, "Gate")
    w1 = single_input(ins, "W1")
    w2 = single_input(ins, "W2")
    cf = float(attrs.get("capacity_factor", 1.25))
    aw = float(attrs.get("aux_weight", 1e-2))
    shape = x.shape
    xf = x.reshape(-1, shape[-1])
    from .math_ops import amp_inputs
    xf, gate_w, w1, w2 = amp_inputs(xf, gate_w, w1, w2)
    out, aux = switch_moe(xf, gate_w, w1, w2, cf,
                          ep_axis=getattr(ctx, "ep_axis", None))
    return {"Out": [out.reshape(shape).astype(x.dtype)],
            "AuxLoss": [(aux * aw).reshape(1).astype(jnp.float32)]}


@register_op("fused_transformer_block")
def _fused_transformer_block(ctx, ins, attrs):
    """One whole pre-norm transformer block (LN -> MHA -> residual ->
    LN -> MLP -> residual) as a single op, emitted by
    transpiler/fused_block.py pattern matching (FLAGS_fuse_block).

    X [B, T, D]; Wq/Wk/Wv [D, E], Wo [E, D], W1 [D, F], W2 [F, D],
    LN scales/biases [D], B1 [F], B2 [D].  attrs: n_head, causal,
    eps1, eps2.  Lowers to the VMEM-resident Pallas block kernel
    (kernels/fused_block.py) on TPU; elsewhere to the numerically
    matching XLA composition, so CPU tests and the interpret path stay
    green.  No reference equivalent (2018 codebase has no fusion past
    single ops)."""
    from ..core import flags
    from ..kernels.fused_block import transformer_block
    from .math_ops import amp_inputs, amp_result
    x = ins["X"][0]
    ln1g, ln1b = ins["Ln1Scale"][0], ins["Ln1Bias"][0]
    ln2g, ln2b = ins["Ln2Scale"][0], ins["Ln2Bias"][0]
    b1, b2 = ins["B1"][0], ins["B2"][0]
    orig = x.dtype
    # amp casts the MATMUL operands only; LN affine params and biases
    # stay f32 (matching the unfused program, where LN math is f32 and
    # bias adds promote)
    xb, wq, wk, wv, wo, w1, w2 = amp_inputs(
        x, ins["Wq"][0], ins["Wk"][0], ins["Wv"][0], ins["Wo"][0],
        ins["W1"][0], ins["W2"][0])
    interpret = ctx.pallas_interpret()
    use_pallas = bool(flags.get_flag("use_pallas_kernels")) \
        and not interpret
    out = transformer_block(
        xb, (ln1g, ln1b, wq, wk, wv, wo, ln2g, ln2b, w1, b1, w2, b2),
        n_head=int(attrs["n_head"]),
        causal=bool(attrs.get("causal", False)),
        eps1=float(attrs.get("eps1", 1e-5)),
        eps2=float(attrs.get("eps2", 1e-5)),
        interpret=interpret, use_pallas=use_pallas)
    return {"Out": [amp_result(out, orig)]}
