"""Sequence ops over dense padded batches with explicit lengths/masks.

Parity target: the reference's LoD-aware sequence family
(/root/reference/paddle/fluid/operators/sequence_ops/ — 16 ops) and LoD
plumbing (lod_reset, sequence_mask, ...).

TPU-first design (SURVEY.md §7 hard part (a)): LoD ragged batches are
replaced by dense (batch, max_len, ...) tensors + a Length vector (or
sequence mask).  Each op takes X (+ optionally Length) and honours padding
via masking — static shapes, so everything stays jittable and
MXU-friendly.  This is the documented design decision, not an omission:
the *capability bar* (train attention/RNN models on variable-length
sequences) is met by mask-aware ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtypes import index_dtype
from ..framework.registry import register_op, single_input


def _mask(x, ins, time_axis=1):
    """(batch, T) float mask from an optional Mask ([B,T] 0/1) or Length
    ([B]) input — layers/sequence.py passes either spelling."""
    if ins.get("Mask"):
        return ins["Mask"][0].reshape(x.shape[:2]).astype(jnp.float32)
    if not ins.get("Length"):
        return jnp.ones(x.shape[:2], dtype=jnp.float32)
    length = ins["Length"][0].reshape(-1)
    t = x.shape[time_axis]
    return (jnp.arange(t)[None, :] < length[:, None]).astype(jnp.float32)


@register_op("sequence_mask", stop_gradient=True)
def _sequence_mask(ctx, ins, attrs):
    length = single_input(ins)
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen <= 0:
        raise ValueError("sequence_mask needs a static maxlen attr on TPU")
    out = (jnp.arange(maxlen)[None, :] <
           length.reshape(-1, 1)).astype(jnp.int32)
    # to_jnp_dtype lowers int64 on the x32 plane itself (core/dtypes.py)
    from ..core.dtypes import to_jnp_dtype
    dt = to_jnp_dtype(attrs.get("out_dtype", "int64"))
    return {"Y": [out.astype(dt)]}


@register_op("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    """average|sum|sqrt|max|last|first over the time axis with padding
    masked out (ref sequence_ops/sequence_pool_op.cc)."""
    x = single_input(ins)
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    m = _mask(x, ins)
    m_exp = m.reshape(m.shape + (1,) * (x.ndim - 2))
    cnt = jnp.maximum(jnp.sum(m, axis=1), 1.0).reshape(
        (-1,) + (1,) * (x.ndim - 2))
    if ptype == "AVERAGE":
        out = jnp.sum(x * m_exp, axis=1) / cnt
    elif ptype == "SUM":
        out = jnp.sum(x * m_exp, axis=1)
    elif ptype == "SQRT":
        out = jnp.sum(x * m_exp, axis=1) / jnp.sqrt(cnt)
    elif ptype == "MAX":
        big_neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(m_exp > 0, x, big_neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(jnp.sum(m, axis=1).astype(jnp.int32) - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": [out]}


@register_op("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    """Masked softmax over the time axis ([B,T] or [B,T,...])."""
    x = single_input(ins)
    m = _mask(x, ins)
    m_exp = m.reshape(m.shape + (1,) * (x.ndim - 2))
    logits = jnp.where(m_exp > 0, x, -1e9)
    return {"Out": [jax.nn.softmax(logits, axis=1) * m_exp]}


@register_op("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    """Broadcast each row along a new time axis sized like Y's
    (dense analogue of sequence_expand_op.cc)."""
    x = single_input(ins)
    y = single_input(ins, "Y")
    t = y.shape[1]
    return {"Out": [jnp.repeat(x[:, None], t, axis=1)]}


@register_op("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    """Reverse valid timesteps only, keeping padding in place."""
    x = single_input(ins)
    if not ins.get("Length"):
        return {"Y": [jnp.flip(x, axis=1)]}
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    rev = jnp.where(idx < length[:, None], length[:, None] - 1 - idx, idx)
    out = jnp.take_along_axis(
        x, rev.reshape(rev.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=1)
    return {"Y": [out]}


@register_op("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=1)]}


@register_op("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    x = single_input(ins)
    off = int(attrs["offset"])
    length = int(attrs["length"])
    return {"Out": [x[:, off:off + length]]}


@register_op("sequence_pad")
def _sequence_pad(ctx, ins, attrs):
    """Already-dense input: pad/trim time axis to padded_length."""
    x = single_input(ins)
    target = int(attrs["padded_length"])
    t = x.shape[1]
    if t >= target:
        out = x[:, :target]
    else:
        pads = [(0, 0), (0, target - t)] + [(0, 0)] * (x.ndim - 2)
        out = jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))
    length = (ins["Length"][0] if ins.get("Length")
              else jnp.full((x.shape[0],), t, index_dtype()))
    return {"Out": [out], "Length": [length]}


@register_op("sequence_unpad")
def _sequence_unpad(ctx, ins, attrs):
    """Dense world: masking stand-in — zero out positions past Length."""
    x = single_input(ins)
    m = _mask(x, ins)
    return {"Out": [x * m.reshape(m.shape + (1,) * (x.ndim - 2))]}


@register_op("sequence_enumerate", stop_gradient=True)
def _sequence_enumerate(ctx, ins, attrs):
    """Sliding n-gram windows of ids (ref sequence_enumerate_op.cc)."""
    x = single_input(ins)  # (batch, T)
    win = int(attrs["win_size"])
    pad = int(attrs.get("pad_value", 0))
    t = x.shape[1]
    padded = jnp.pad(x, [(0, 0), (0, win - 1)], constant_values=pad)
    cols = jnp.stack([padded[:, i:i + t] for i in range(win)], axis=-1)
    return {"Out": [cols]}


@register_op("sequence_erase", stop_gradient=True)
def _sequence_erase(ctx, ins, attrs):
    """Mask out tokens (replace with pad 0) — dense analogue of erase."""
    x = single_input(ins)
    tokens = jnp.asarray(attrs["tokens"])
    hit = jnp.isin(x, tokens)
    return {"Out": [jnp.where(hit, 0, x)]}


@register_op("sequence_expand_as")
def _sequence_expand_as(ctx, ins, attrs):
    x = single_input(ins)
    y = single_input(ins, "Y")
    return {"Out": [jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1])
                                     + x.shape[1:])]}


@register_op("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    x = single_input(ins)
    new_dim = int(attrs["new_dim"])
    b = x.shape[0]
    return {"Out": [x.reshape(b, -1, new_dim)]}


@register_op("sequence_scatter")
def _sequence_scatter(ctx, ins, attrs):
    x = single_input(ins)
    ids = single_input(ins, "Ids").astype(jnp.int32)
    upd = single_input(ins, "Updates")
    b = x.shape[0]
    rows = jnp.repeat(jnp.arange(b)[:, None], ids.shape[1], axis=1)
    return {"Out": [x.at[rows, ids].add(upd)]}


@register_op("lod_reset")
def _lod_reset(ctx, ins, attrs):
    """LoD is edge metadata only; dense passthrough."""
    return {"Out": [single_input(ins)]}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (ref row_conv_op.cc): (B, T, D) x
    (future_ctx+1, D) -> (B, T, D)."""
    x = single_input(ins)
    w = single_input(ins, "Filter")
    ctx_len = w.shape[0]
    outs = jnp.zeros_like(x)
    padded = jnp.pad(x, [(0, 0), (0, ctx_len - 1), (0, 0)])
    for i in range(ctx_len):
        outs = outs + padded[:, i:i + x.shape[1]] * w[i][None, None, :]
    return {"Out": [outs]}


@register_op("add_position_encoding")
def _add_position_encoding(ctx, ins, attrs):
    """Sinusoidal PE added in-graph (ref add_position_encoding_op.cc)."""
    x = single_input(ins)  # (B, T, D)
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    i = jnp.arange(d // 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, 2 * i / d)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=1)
    return {"Out": [alpha * x + beta * pe[None].astype(x.dtype)]}
