"""Collective communication ops — the ICI/DCN plane.

Parity: the reference's raw NCCL ops (/root/reference/paddle/fluid/operators/
nccl/nccl_op.cc — ncclAllReduce/Bcast/Reduce as program ops) and the
collective op-handles of ParallelExecutor (details/all_reduce_op_handle.cc,
broadcast_op_handle.cc, reduce_op_handle.cc).

TPU-first: these lower to jax.lax collectives over a *named mesh axis* and
are only meaningful when the program is executed under shard_map / pjit with
that axis in scope (parallel/ modules arrange this).  For ordinary
data-parallel training these ops are NOT needed — XLA inserts the gradient
psum automatically from sharding annotations; they exist for explicit
SPMD programs (context/expert parallelism, manual pipelines).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.jax_compat import axis_size
from ..framework.registry import register_op, single_input


@register_op("c_allreduce_sum")
def _c_allreduce_sum(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jax.lax.psum(x, axis_name=attrs.get("axis_name",
                                                        "data"))]}


@register_op("c_allreduce_max")
def _c_allreduce_max(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jax.lax.pmax(x, axis_name=attrs.get("axis_name",
                                                        "data"))]}


@register_op("c_allreduce_mean")
def _c_allreduce_mean(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jax.lax.pmean(x, axis_name=attrs.get("axis_name",
                                                         "data"))]}


@register_op("c_allgather")
def _c_allgather(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jax.lax.all_gather(
        x, axis_name=attrs.get("axis_name", "data"),
        axis=int(attrs.get("axis", 0)), tiled=True)]}


@register_op("c_reducescatter")
def _c_reducescatter(ctx, ins, attrs):
    x = single_input(ins)
    return {"Out": [jax.lax.psum_scatter(
        x, axis_name=attrs.get("axis_name", "data"),
        scatter_dimension=int(attrs.get("axis", 0)), tiled=True)]}


@register_op("c_broadcast")
def _c_broadcast(ctx, ins, attrs):
    """Broadcast from root: implemented as select + psum (XLA lowers this
    to an efficient collective)."""
    x = single_input(ins)
    axis_name = attrs.get("axis_name", "data")
    root = int(attrs.get("root", 0))
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": [jax.lax.psum(masked, axis_name=axis_name)]}


@register_op("c_ppermute")
def _c_ppermute(ctx, ins, attrs):
    """Ring permute — the building block of ring attention / pipeline
    parallelism (no reference analogue; TPU-native capability)."""
    x = single_input(ins)
    axis_name = attrs.get("axis_name", "data")
    shift = int(attrs.get("shift", 1))
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": [jax.lax.ppermute(x, axis_name, perm)]}


@register_op("c_alltoall")
def _c_alltoall(ctx, ins, attrs):
    x = single_input(ins)
    axis_name = attrs.get("axis_name", "data")
    split_axis = int(attrs.get("split_axis", 0))
    concat_axis = int(attrs.get("concat_axis", 0))
    return {"Out": [jax.lax.all_to_all(x, axis_name, split_axis,
                                       concat_axis, tiled=True)]}


@register_op("c_sync_calc_stream")
def _c_sync(ctx, ins, attrs):
    """No-op on TPU: XLA owns stream ordering (ref c_sync_*_stream ops)."""
    return {"Out": [single_input(ins)]}
