"""Detection toolbox ops (SSD / Faster-RCNN / YOLO family).

Parity target: /root/reference/paddle/fluid/operators/detection/ (~25 ops).
This module covers the core geometry ops densely and statically (TPU needs
static shapes — NMS returns fixed-size outputs with validity counts instead
of the reference's variable-length LoD outputs).
Initial set: prior_box, density_prior_box, box_coder, iou_similarity,
anchor_generator, yolo_box-era transforms, multiclass_nms (static),
bipartite_match, polygon_box_transform.  Remaining ops tracked in
docs/PARITY.md.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op, single_input


def _iou_matrix(a, b):
    """Pairwise IoU of xyxy boxes a [N,4] vs b [M,4] -> [N,M].

    The single implementation behind iou_similarity, rpn_target_assign,
    generate_proposal_labels and detection_map (degenerate boxes clamp
    to zero area; epsilon guards empty unions).
    """
    area = lambda v: jnp.maximum(v[:, 2] - v[:, 0], 0) * jnp.maximum(
        v[:, 3] - v[:, 1], 0)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area(a)[:, None] + area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op("iou_similarity", stop_gradient=True)
def _iou_similarity(ctx, ins, attrs):
    x = single_input(ins)          # (N, 4) xmin,ymin,xmax,ymax
    y = single_input(ins, "Y")     # (M, 4)
    return {"Out": [_iou_matrix(x, y)]}


@register_op("box_coder", stop_gradient=True)
def _box_coder(ctx, ins, attrs):
    """encode_center_size / decode_center_size (ref detection/box_coder_op)."""
    prior = single_input(ins, "PriorBox")        # (M, 4)
    tb = single_input(ins, "TargetBox")
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    code_type = attrs.get("code_type", "encode_center_size")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, None, 2] - tb[:, None, 0]
        th = tb[:, None, 3] - tb[:, None, 1]
        tcx = tb[:, None, 0] + tw / 2
        tcy = tb[:, None, 1] + th / 2
        ox = (tcx - pcx[None]) / pw[None]
        oy = (tcy - pcy[None]) / ph[None]
        ow = jnp.log(jnp.abs(tw / pw[None]) + 1e-10)
        oh = jnp.log(jnp.abs(th / ph[None]) + 1e-10)
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if var is not None:
            out = out / var[None]
    else:  # decode_center_size
        if var is not None:
            tb = tb * var[None]
        dcx = tb[..., 0] * pw + pcx
        dcy = tb[..., 1] * ph + pcy
        dw = jnp.exp(tb[..., 2]) * pw
        dh = jnp.exp(tb[..., 3]) * ph
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2, dcy + dh / 2], axis=-1)
    return {"OutputBox": [out]}


@register_op("prior_box", stop_gradient=True)
def _prior_box(ctx, ins, attrs):
    """SSD prior boxes per feature-map cell (ref detection/prior_box_op.cc)."""
    feat = single_input(ins, "Input")   # (N, C, H, W)
    image = single_input(ins, "Image")  # (N, C, IH, IW)
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if attrs.get("flip", False):
                ars.append(1.0 / float(ar))
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w", 0) or iw / w)
    step_h = float(attrs.get("step_h", 0) or ih / h)
    offset = float(attrs.get("offset", 0.5))
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * math.sqrt(ar) / 2
            bh = ms / math.sqrt(ar) / 2
            boxes.append((bw, bh))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            s = math.sqrt(ms * mx) / 2
            boxes.append((s, s))
    nb = len(boxes)
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)           # (H, W)
    wh = jnp.asarray(boxes)                   # (nb, 2)
    out = jnp.stack([
        (cxg[..., None] - wh[None, None, :, 0]) / iw,
        (cyg[..., None] - wh[None, None, :, 1]) / ih,
        (cxg[..., None] + wh[None, None, :, 0]) / iw,
        (cyg[..., None] + wh[None, None, :, 1]) / ih,
    ], axis=-1)                               # (H, W, nb, 4)
    if attrs.get("clip", False):
        out = jnp.clip(out, 0.0, 1.0)
    variances = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]))
    var = jnp.broadcast_to(variances, out.shape)
    return {"Boxes": [out], "Variances": [var]}


@register_op("density_prior_box", stop_gradient=True)
def _density_prior_box(ctx, ins, attrs):
    """ref detection/density_prior_box_op.cc."""
    feat = single_input(ins, "Input")
    image = single_input(ins, "Image")
    fixed_sizes = [float(s) for s in attrs.get("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in attrs.get("fixed_ratios", [])]
    densities = [int(d) for d in attrs.get("densities", [])]
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w", 0) or iw / w)
    step_h = float(attrs.get("step_h", 0) or ih / h)
    offset = float(attrs.get("offset", 0.5))
    boxes = []  # per-cell (dx, dy, bw, bh) offsets
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * math.sqrt(ratio)
            bh = size / math.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    dx = -size / 2.0 + step / 2.0 + dj * step
                    dy = -size / 2.0 + step / 2.0 + di * step
                    boxes.append((dx, dy, bw, bh))
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    b = jnp.asarray(boxes)  # (nb, 4)
    ctrx = cxg[..., None] + b[None, None, :, 0]
    ctry = cyg[..., None] + b[None, None, :, 1]
    out = jnp.stack([
        (ctrx - b[None, None, :, 2] / 2) / iw,
        (ctry - b[None, None, :, 3] / 2) / ih,
        (ctrx + b[None, None, :, 2] / 2) / iw,
        (ctry + b[None, None, :, 3] / 2) / ih,
    ], axis=-1)
    if attrs.get("clip", False):
        out = jnp.clip(out, 0.0, 1.0)
    variances = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]))
    return {"Boxes": [out],
            "Variances": [jnp.broadcast_to(variances, out.shape)]}


@register_op("anchor_generator", stop_gradient=True)
def _anchor_generator(ctx, ins, attrs):
    """RPN anchors (ref detection/anchor_generator_op.cc)."""
    feat = single_input(ins, "Input")
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    stride = [float(s) for s in attrs["stride"]]
    offset = float(attrs.get("offset", 0.5))
    h, w = feat.shape[2], feat.shape[3]
    anchors = []
    for r in ratios:
        for s in sizes:
            aw = s * math.sqrt(1.0 / r)
            ah = s * math.sqrt(r)
            anchors.append((aw / 2, ah / 2))
    a = jnp.asarray(anchors)
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    out = jnp.stack([
        cxg[..., None] - a[None, None, :, 0],
        cyg[..., None] - a[None, None, :, 1],
        cxg[..., None] + a[None, None, :, 0],
        cyg[..., None] + a[None, None, :, 1],
    ], axis=-1)
    variances = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]))
    return {"Anchors": [out],
            "Variances": [jnp.broadcast_to(variances, out.shape)]}


def _nms_single_class(boxes, scores, iou_thr, score_thr, max_out):
    """Static-shape greedy NMS: returns (max_out,) indices (-1 pad) — the
    TPU-friendly replacement for variable-length NMS outputs."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    scores_s = scores[order]
    area = jnp.maximum(boxes_s[:, 2] - boxes_s[:, 0], 0) * jnp.maximum(
        boxes_s[:, 3] - boxes_s[:, 1], 0)

    def iou_with(i, j_boxes):
        b = boxes_s[i]
        ix1 = jnp.maximum(b[0], j_boxes[:, 0])
        iy1 = jnp.maximum(b[1], j_boxes[:, 1])
        ix2 = jnp.minimum(b[2], j_boxes[:, 2])
        iy2 = jnp.minimum(b[3], j_boxes[:, 3])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        ab = jnp.maximum(b[2] - b[0], 0) * jnp.maximum(b[3] - b[1], 0)
        return inter / jnp.maximum(ab + area - inter, 1e-10)

    def body(i, keep):
        ious = iou_with(i, boxes_s)
        suppress = (ious > iou_thr) & (jnp.arange(n) > i) & keep[i]
        return jnp.where(suppress, False, keep)

    keep = scores_s > score_thr
    keep = jax.lax.fori_loop(0, n, body, keep)
    kept_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    sel = jnp.full((max_out,), -1, jnp.int32)
    sel = sel.at[jnp.where(keep, kept_rank, max_out)
                 .clip(0, max_out)].set(
        jnp.where(keep, order, -1).astype(jnp.int32), mode="drop")
    return sel


@register_op("multiclass_nms", stop_gradient=True)
def _multiclass_nms(ctx, ins, attrs):
    """Static-shape multiclass NMS (ref detection/multiclass_nms_op.cc).
    Output: (N, keep_top_k, 6) [class, score, x1, y1, x2, y2], score==-1
    marks padding rows; plus a per-image valid count."""
    boxes = single_input(ins, "BBoxes")    # (N, M, 4)
    scores = single_input(ins, "Scores")   # (N, C, M)
    score_thr = float(attrs.get("score_threshold", 0.0))
    iou_thr = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 200))
    background = int(attrs.get("background_label", 0))
    n, c, m = scores.shape
    per_cls = min(nms_top_k if nms_top_k > 0 else m, m)

    def one_image(bxs, scs):
        rows = []
        for cls in range(c):
            if cls == background:
                continue
            sel = _nms_single_class(bxs, scs[cls], iou_thr, score_thr,
                                    per_cls)
            valid = sel >= 0
            cls_scores = jnp.where(valid, scs[cls][sel.clip(0)], -1.0)
            cls_boxes = bxs[sel.clip(0)]
            rows.append(jnp.concatenate([
                jnp.full((per_cls, 1), float(cls)),
                cls_scores[:, None],
                jnp.where(valid[:, None], cls_boxes, 0.0)], axis=1))
        allrows = jnp.concatenate(rows, axis=0)
        top = min(keep_top_k, allrows.shape[0])
        _, idx = jax.lax.top_k(allrows[:, 1], top)
        out = allrows[idx]
        if top < keep_top_k:
            out = jnp.pad(out, [(0, keep_top_k - top), (0, 0)],
                          constant_values=-1.0)
        count = jnp.sum((out[:, 1] > score_thr).astype(jnp.int32))
        return out, count

    outs, counts = jax.vmap(one_image)(boxes, scores)
    return {"Out": [outs], "NmsRoisNum": [counts]}


@register_op("bipartite_match", stop_gradient=True)
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching on a similarity matrix
    (ref detection/bipartite_match_op.cc), static-shape greedy variant."""
    dist = single_input(ins, "DistMat")  # (N, M) rows=gt cols=pred
    n, m = dist.shape

    def body(_, carry):
        d, match_idx, match_dist = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        best = d[i, j]
        do = best > -1e9
        match_idx = jnp.where(do, match_idx.at[j].set(i), match_idx)
        match_dist = jnp.where(do, match_dist.at[j].set(best), match_dist)
        d = jnp.where(do, d.at[i, :].set(-1e10).at[:, j].set(-1e10), d)
        return d, match_idx, match_dist

    init = (jnp.where(dist > 0, dist, -1e10),
            jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), dist.dtype))
    _, match_idx, match_dist = jax.lax.fori_loop(0, min(n, m), body, init)
    return {"ColToRowMatchIndices": [match_idx[None]],
            "ColToRowMatchDist": [match_dist[None]]}


@register_op("polygon_box_transform", stop_gradient=True)
def _polygon_box_transform(ctx, ins, attrs):
    """ref detection/polygon_box_transform_op.cc: offset channels to
    absolute coords on activated cells."""
    x = single_input(ins)  # (N, geo_channels, H, W)
    n, c, h, w = x.shape
    xg = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4.0
    yg = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4.0
    even = jnp.arange(c) % 2 == 0
    base = jnp.where(even[None, :, None, None], xg, yg)
    return {"Output": [base - x]}


@register_op("yolo_box", stop_gradient=True)
def _yolo_box(ctx, ins, attrs):
    """Decode YOLOv3 head to boxes (ref operators/detection/yolo_box-era;
    yolov3_loss's inference twin)."""
    x = single_input(ins)          # (N, A*(5+C), H, W)
    img_size = single_input(ins, "ImgSize")  # (N, 2) h, w
    anchors = attrs["anchors"]
    class_num = int(attrs["class_num"])
    conf_thresh = float(attrs.get("conf_thresh", 0.01))
    downsample = int(attrs.get("downsample_ratio", 32))
    na = len(anchors) // 2
    n, _, h, w = x.shape
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = (jax.nn.sigmoid(x[:, :, 0]) +
          jnp.arange(w, dtype=jnp.float32)[None, None, None, :]) / w
    gy = (jax.nn.sigmoid(x[:, :, 1]) +
          jnp.arange(h, dtype=jnp.float32)[None, None, :, None]) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    gw = jnp.exp(x[:, :, 2]) * aw / (w * downsample)
    gh = jnp.exp(x[:, :, 3]) * ah / (h * downsample)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imgh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imgw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack([(gx - gw / 2) * imgw, (gy - gh / 2) * imgh,
                       (gx + gw / 2) * imgw, (gy + gh / 2) * imgh], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    scores = jnp.where(scores > conf_thresh, scores, 0.0)
    return {"Boxes": [boxes], "Scores": [scores]}


@register_op("box_clip", stop_gradient=True)
def _box_clip(ctx, ins, attrs):
    boxes = single_input(ins, "Input")
    im_info = single_input(ins, "ImInfo")  # (N, 3) h, w, scale
    h = im_info[:, 0][:, None, None] - 1
    w = im_info[:, 1][:, None, None] - 1
    b = boxes.reshape(boxes.shape[0], -1, 4)
    out = jnp.stack([jnp.clip(b[..., 0], 0, w[..., 0]),
                     jnp.clip(b[..., 1], 0, h[..., 0]),
                     jnp.clip(b[..., 2], 0, w[..., 0]),
                     jnp.clip(b[..., 3], 0, h[..., 0])], axis=-1)
    return {"Output": [out.reshape(boxes.shape)]}


@register_op("affine_grid")
def _affine_grid(ctx, ins, attrs):
    """ref affine_grid_op.cc: Theta [N,2,3] -> sampling grid [N,H,W,2]
    in normalized [-1, 1] coords."""
    theta = single_input(ins, "Theta").astype(jnp.float32)
    if ins.get("OutputShape"):
        shp = ins["OutputShape"][0]
        if isinstance(shp, jax.core.Tracer):
            from ..core.enforce import EnforceNotMet
            raise EnforceNotMet(
                "affine_grid: OutputShape must be a trace-time constant "
                "under the jitted executor (grid dims set the output "
                "shape); pass the static `output_shape` attr instead")
        n, _, h, w = [int(v) for v in np.asarray(shp)]
    else:
        n, _, h, w = attrs["output_shape"]
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)                      # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)          # [H, W, 3]
    grid = jnp.einsum("hwk,nck->nhwc", base, theta)    # [N, H, W, 2]
    return {"Output": [grid]}


def _roi_batch_ids(ins, R):
    if ins.get("RoisBatchId"):
        return ins["RoisBatchId"][0].reshape(-1).astype(jnp.int32)
    if ins.get("RoisNum"):
        num = ins["RoisNum"][0].reshape(-1).astype(jnp.int32)
        return jnp.repeat(jnp.arange(num.shape[0]), num,
                          total_repeat_length=R)
    return jnp.zeros((R,), jnp.int32)


@register_op("roi_align")
def _roi_align(ctx, ins, attrs):
    """ref detection-era roi_align_op.cc: bilinear-sampled average over
    each bin.  X [N,C,H,W], ROIs [R,4] (x1,y1,x2,y2 image coords);
    roi->image mapping via RoisNum (dense) or RoisBatchId (LoD
    replacement).  attrs: pooled_height/width, spatial_scale,
    sampling_ratio."""
    x = single_input(ins, "X").astype(jnp.float32)
    rois = single_input(ins, "ROIs").astype(jnp.float32)
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    ratio = int(attrs.get("sampling_ratio", -1))
    ratio = ratio if ratio > 0 else 2
    N, C, H, W = x.shape
    R = rois.shape[0]
    bids = _roi_batch_ids(ins, R)

    def one_roi(roi, bid):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample points: ph*ratio x pw*ratio bilinear taps
        sy = y1 + (jnp.arange(ph * ratio) + 0.5) * bin_h / ratio
        sx = x1 + (jnp.arange(pw * ratio) + 0.5) * bin_w / ratio
        sy = jnp.clip(sy, 0.0, H - 1.0)
        sx = jnp.clip(sx, 0.0, W - 1.0)
        y0 = jnp.floor(sy).astype(jnp.int32)
        x0 = jnp.floor(sx).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = sy - y0
        wx = sx - x0
        img = x[bid]                                   # [C, H, W]
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        val = (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
               + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
               + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
               + v11 * wy[None, :, None] * wx[None, None, :])
        val = val.reshape(C, ph, ratio, pw, ratio)
        return jnp.mean(val, axis=(2, 4))              # [C, ph, pw]

    out = jax.vmap(one_roi)(rois, bids)
    return {"Out": [out]}


@register_op("roi_pool")
def _roi_pool(ctx, ins, attrs):
    """ref roi_pool_op.cc: max pool per bin (quantized boundaries)."""
    x = single_input(ins, "X").astype(jnp.float32)
    rois = single_input(ins, "ROIs").astype(jnp.float32)
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    R = rois.shape[0]
    bids = _roi_batch_ids(ins, R)
    yy = jnp.arange(H)
    xx = jnp.arange(W)

    def one_roi(roi, bid):
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = x[bid]
        def one_bin(i, j):
            hs = jnp.floor(y1 + i * rh / ph)
            he = jnp.ceil(y1 + (i + 1) * rh / ph)
            ws = jnp.floor(x1 + j * rw / pw)
            we = jnp.ceil(x1 + (j + 1) * rw / pw)
            inside = ((yy[:, None] >= hs) & (yy[:, None] < he)
                      & (xx[None, :] >= ws) & (xx[None, :] < we))
            masked = jnp.where(inside[None], img, -jnp.inf)
            m = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(m), m, 0.0)
        rows = []
        for i in range(ph):
            cols = [one_bin(i, j) for j in range(pw)]
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)                # [C, ph, pw]

    out = jax.vmap(one_roi)(rois, bids)
    return {"Out": [out]}


@register_op("generate_proposals", stop_gradient=True)
def _generate_proposals(ctx, ins, attrs):
    """ref detection/generate_proposals_op.cc, dense static shapes:
    Scores [N,A,H,W], BboxDeltas [N,4A,H,W], ImInfo [N,3] (h,w,scale),
    Anchors [H,W,A,4], Variances same shape.  Output RpnRois
    [N, post_nms_topN, 4] (-1-padded) + RpnRoiProbs [N, post_nms_topN]."""
    scores = single_input(ins, "Scores").astype(jnp.float32)
    deltas = single_input(ins, "BboxDeltas").astype(jnp.float32)
    im_info = single_input(ins, "ImInfo").astype(jnp.float32)
    anchors = single_input(ins, "Anchors").astype(jnp.float32)
    variances = (ins["Variances"][0].astype(jnp.float32)
                 if ins.get("Variances") else jnp.ones_like(anchors))
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))
    N, A, H, W = scores.shape
    total = A * H * W
    pre_n = min(pre_n, total)
    anc = anchors.reshape(-1, 4)                        # [H*W*A, 4]
    var = variances.reshape(-1, 4)

    def one_image(sc, dl, info):
        s = sc.transpose(1, 2, 0).reshape(-1)           # [H*W*A]
        d = dl.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        # decode (anchor + variance-scaled deltas, ref box_coder math)
        aw = anc[:, 2] - anc[:, 0] + 1
        ah = anc[:, 3] - anc[:, 1] + 1
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        cx = var[:, 0] * d[:, 0] * aw + acx
        cy = var[:, 1] * d[:, 1] * ah + acy
        bw = jnp.exp(jnp.clip(var[:, 2] * d[:, 2], -10, 10)) * aw
        bh = jnp.exp(jnp.clip(var[:, 3] * d[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - 1, cy + bh / 2 - 1], axis=1)
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, info[1] - 1),
                           jnp.clip(boxes[:, 1], 0, info[0] - 1),
                           jnp.clip(boxes[:, 2], 0, info[1] - 1),
                           jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=1)
        ms = min_size * info[2]
        keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= ms)
                & (boxes[:, 3] - boxes[:, 1] + 1 >= ms))
        s = jnp.where(keep, s, -1e9)
        top_s, top_i = jax.lax.top_k(s, pre_n)
        cand = boxes[top_i]
        sel = _nms_single_class(cand, top_s, nms_thresh, -1e9 + 1, post_n)
        rois = jnp.where(sel[:, None] >= 0,
                         cand[jnp.clip(sel, 0, pre_n - 1)], -1.0)
        probs = jnp.where(sel >= 0, top_s[jnp.clip(sel, 0, pre_n - 1)],
                          0.0)
        return rois, probs

    rois, probs = jax.vmap(one_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs]}


@register_op("rpn_target_assign", stop_gradient=True)
def _rpn_target_assign(ctx, ins, attrs):
    """ref rpn_target_assign_op.cc, dense redesign: instead of
    variable-length index lists, emit per-anchor labels (1 pos / 0 neg /
    -1 ignore) and regression targets + a sampling mask drawn with the
    functional RNG.  Anchor [A,4], GtBoxes [N,G,4] (-1 pads)."""
    anchor = single_input(ins, "Anchor").astype(jnp.float32)
    gt = single_input(ins, "GtBoxes").astype(jnp.float32)
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    A = anchor.shape[0]
    N, G, _ = gt.shape

    def one_image(key, gtb):
        valid_gt = gtb[:, 2] > gtb[:, 0]
        ax1, ay1, ax2, ay2 = anchor.T
        iou = jnp.where(valid_gt[None, :], _iou_matrix(anchor, gtb), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        labels = jnp.full((A,), -1, jnp.int32)
        labels = jnp.where(best_iou >= pos_thr, 1, labels)
        labels = jnp.where((best_iou < neg_thr) & (best_iou >= 0), 0,
                           labels)
        # each gt's best anchor is positive (ref behavior)
        best_anchor = jnp.argmax(jnp.where(valid_gt[None, :], iou, -2.0),
                                 axis=0)
        labels = labels.at[best_anchor].set(
            jnp.where(valid_gt, 1, labels[best_anchor]))
        matched = gtb[best_gt]
        aw = jnp.maximum(ax2 - ax1, 1.0)
        ah = jnp.maximum(ay2 - ay1, 1.0)
        gw = jnp.maximum(matched[:, 2] - matched[:, 0], 1.0)
        gh = jnp.maximum(matched[:, 3] - matched[:, 1], 1.0)
        tx = ((matched[:, 0] + matched[:, 2]) / 2
              - (ax1 + ax2) / 2) / aw
        ty = ((matched[:, 1] + matched[:, 3]) / 2
              - (ay1 + ay2) / 2) / ah
        tw = jnp.log(gw / aw)
        th = jnp.log(gh / ah)
        targets = jnp.stack([tx, ty, tw, th], axis=1)
        # subsample to rpn_batch_size_per_im at rpn_fg_fraction positives
        # (ref behavior); excess anchors are set back to -1 (ignored).
        # Static shapes: rank anchors by a random draw and keep the first
        # fg_cap / bg_cap of each class.
        batch = int(attrs.get("rpn_batch_size_per_im", 256))
        fg_cap = int(batch * float(attrs.get("rpn_fg_fraction", 0.5)))
        kpos, kneg = jax.random.split(key)
        pos = labels == 1
        r = jax.random.uniform(kpos, (A,))
        pos_rank = jnp.argsort(jnp.argsort(jnp.where(pos, r, 2.0)))
        labels = jnp.where(pos & (pos_rank >= fg_cap), -1, labels)
        n_fg = jnp.minimum(jnp.sum(pos), fg_cap)
        neg = labels == 0
        r2 = jax.random.uniform(kneg, (A,))
        neg_rank = jnp.argsort(jnp.argsort(jnp.where(neg, r2, 2.0)))
        labels = jnp.where(neg & (neg_rank >= batch - n_fg), -1, labels)
        return labels, targets

    keys = jax.random.split(ctx.rng(), N)
    labels, targets = jax.vmap(one_image)(keys, gt)
    return {"Labels": [labels], "BboxTargets": [targets],
            "LocationIndex": [jnp.argsort(-labels, axis=1)],
            "ScoreIndex": [jnp.argsort(labels == -1, axis=1)]}


@register_op("detection_map", stop_gradient=True)
def _detection_map(ctx, ins, attrs):
    """ref detection_map_op.cc, integral mAP over dense inputs:
    Detection [M,6] rows (label, score, x1, y1, x2, y2; label<0 pads);
    ground truth either as Label [G,5] rows (label, x1, y1, x2, y2) or
    as Label [G,1] + separate GtBox [G,4] (dense single-image or
    pre-flattened batch with -1 pads).  Output MAP [1]."""
    det = single_input(ins, "DetectRes").astype(jnp.float32)
    gt_label = single_input(ins, "Label").astype(jnp.float32)
    overlap = float(attrs.get("overlap_threshold", 0.5))
    # gt rows: (label, x1, y1, x2, y2)
    g_lbl = gt_label[:, 0]
    if gt_label.shape[1] >= 5:
        g_box = gt_label[:, 1:5]
    elif ins.get("GtBox"):
        g_box = ins["GtBox"][0].astype(jnp.float32).reshape(-1, 4)
    else:
        from ..core.enforce import EnforceNotMet
        raise EnforceNotMet(
            "detection_map needs boxes: pass Label as [G,5] "
            "(label,x1,y1,x2,y2) or provide a GtBox [G,4] input")
    valid_gt = g_lbl >= 0
    d_lbl, d_score, d_box = det[:, 0], det[:, 1], det[:, 2:6]
    valid_d = d_lbl >= 0
    order = jnp.argsort(-jnp.where(valid_d, d_score, -jnp.inf))
    d_lbl, d_box = d_lbl[order], d_box[order]
    valid_d = valid_d[order]
    M = det.shape[0]
    G = gt_label.shape[0]

    def iou_row(b):
        return _iou_matrix(b[None, :], g_box)[0]

    def body(carry, i):
        used, tp, fp = carry
        ious = iou_row(d_box[i])
        same = (g_lbl == d_lbl[i]) & valid_gt & ~used
        ious = jnp.where(same, ious, -1.0)
        j = jnp.argmax(ious)
        hit = (ious[j] >= overlap) & valid_d[i]
        used = used.at[j].set(used[j] | hit)
        tp = tp.at[i].set(jnp.where(valid_d[i] & hit, 1.0, 0.0))
        fp = fp.at[i].set(jnp.where(valid_d[i] & ~hit, 1.0, 0.0))
        return (used, tp, fp), None

    init = (jnp.zeros((G,), bool), jnp.zeros((M,)), jnp.zeros((M,)))
    (used, tp, fp), _ = jax.lax.scan(body, init, jnp.arange(M))
    # Per-class integral AP, averaged over classes that have ground truth
    # (VOC mAP).  Detections are globally score-sorted, so the same-class
    # prefix sums below walk each class's PR curve in score order.
    same_cls = (d_lbl[None, :] == d_lbl[:, None]) & (
        jnp.arange(M)[None, :] <= jnp.arange(M)[:, None])
    ctp = jnp.sum(jnp.where(same_cls, tp[None, :], 0.0), axis=1)
    cfp = jnp.sum(jnp.where(same_cls, fp[None, :], 0.0), axis=1)
    precision = ctp / jnp.maximum(ctp + cfp, 1e-10)
    # gt count for the class of detection i
    n_gt_of = jnp.sum((g_lbl[None, :] == d_lbl[:, None])
                      & valid_gt[None, :], axis=1).astype(jnp.float32)
    terms = jnp.where(tp > 0, precision / jnp.maximum(n_gt_of, 1.0), 0.0)
    # number of distinct classes present in the ground truth
    gs = jnp.sort(jnp.where(valid_gt, g_lbl, jnp.inf))
    first = jnp.concatenate([jnp.ones((1,), bool), gs[1:] != gs[:-1]])
    n_classes = jnp.sum(jnp.where(jnp.isfinite(gs), first, False)
                        .astype(jnp.float32))
    ap = jnp.sum(terms) / jnp.maximum(n_classes, 1.0)
    return {"MAP": [ap.reshape(1)], "AccumPosCount": [jnp.cumsum(tp)],
            "AccumTruePos": [tp], "AccumFalsePos": [fp]}


@register_op("target_assign", stop_gradient=True)
def _target_assign(ctx, ins, attrs):
    """ref detection/target_assign_op.cc: scatter per-prior matched gt
    rows (dense: MatchIndices [N, Np] with -1 for unmatched).
    X [N, G, K] gt attributes -> Out [N, Np, K] + OutWeight [N, Np, 1]."""
    x = single_input(ins, "X")
    match = single_input(ins, "MatchIndices").astype(jnp.int32)
    mismatch_value = attrs.get("mismatch_value", 0)
    n, np_, = match.shape
    gat = jnp.take_along_axis(
        x, jnp.clip(match, 0, x.shape[1] - 1)[..., None], axis=1)
    ok = (match >= 0)[..., None]
    out = jnp.where(ok, gat, mismatch_value)
    return {"Out": [out], "OutWeight": [ok.astype(x.dtype)]}


@register_op("mine_hard_examples", stop_gradient=True)
def _mine_hard_examples(ctx, ins, attrs):
    """ref detection/mine_hard_examples_op.cc (max_negative mode):
    keep the hardest negatives up to neg_pos_ratio * #pos per image.
    ClsLoss [N, Np], MatchIndices [N, Np] (-1 = negative).  Returns an
    updated NegIndices mask (dense 0/1) instead of LoD index lists."""
    loss = single_input(ins, "ClsLoss")
    match = single_input(ins, "MatchIndices").astype(jnp.int32)
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    n, np_ = loss.shape
    is_neg = match < 0
    n_pos = jnp.sum((~is_neg).astype(jnp.int32), axis=1)
    n_neg = jnp.minimum((n_pos * ratio).astype(jnp.int32),
                        jnp.sum(is_neg.astype(jnp.int32), axis=1))
    neg_loss = jnp.where(is_neg, loss, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.zeros_like(match).at[
        jnp.arange(n)[:, None], order].set(
        jnp.broadcast_to(jnp.arange(np_), (n, np_)))
    keep = is_neg & (rank < n_neg[:, None])
    return {"NegIndices": [keep.astype(jnp.int32)],
            "UpdatedMatchIndices": [jnp.where(keep, -1, match)]}


@register_op("psroi_pool")
def _psroi_pool(ctx, ins, attrs):
    """ref psroi_pool_op.cc: position-sensitive RoI average pooling.
    X [N, C=out_c*ph*pw, H, W], ROIs [R, 4]."""
    x = single_input(ins, "X").astype(jnp.float32)
    rois = single_input(ins, "ROIs").astype(jnp.float32)
    out_c = int(attrs["output_channels"])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    R = rois.shape[0]
    bids = _roi_batch_ids(ins, R)
    yy = jnp.arange(H)
    xx = jnp.arange(W)

    def one_roi(roi, bid):
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        img = x[bid].reshape(out_c, ph, pw, H, W)
        rows = []
        for i in range(ph):
            cols = []
            for j in range(pw):
                hs = jnp.floor(y1 + i * rh / ph)
                he = jnp.ceil(y1 + (i + 1) * rh / ph)
                ws = jnp.floor(x1 + j * rw / pw)
                we = jnp.ceil(x1 + (j + 1) * rw / pw)
                inside = ((yy[:, None] >= hs) & (yy[:, None] < he)
                          & (xx[None, :] >= ws) & (xx[None, :] < we))
                cnt = jnp.maximum(jnp.sum(inside), 1)
                v = jnp.sum(jnp.where(inside[None], img[:, i, j], 0.0),
                            axis=(1, 2)) / cnt
                cols.append(v)
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)               # [out_c, ph, pw]

    out = jax.vmap(one_roi)(rois, bids)
    return {"Out": [out]}


@register_op("generate_proposal_labels", stop_gradient=True)
def _generate_proposal_labels(ctx, ins, attrs):
    """ref detection/generate_proposal_labels_op.cc, dense redesign:
    sample a fixed batch_size_per_im of rois per image, label them by
    IoU vs gt, and emit box-regression targets (fixed shapes + weights
    instead of LoD)."""
    rois = single_input(ins, "RpnRois").astype(jnp.float32)   # [N,R,4]
    gt_boxes = single_input(ins, "GtBoxes").astype(jnp.float32)  # [N,G,4]
    gt_classes = single_input(ins, "GtClasses").astype(jnp.int32)  # [N,G]
    per_im = int(attrs.get("batch_size_per_im", 256))
    fg_thr = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    N, R, _ = rois.shape

    def one(roi, gtb, gtc):
        valid_gt = gtb[:, 2] > gtb[:, 0]
        iou = jnp.where(valid_gt[None], _iou_matrix(roi, gtb), -1.0)
        best = jnp.max(iou, axis=1)
        bgt = jnp.argmax(iou, axis=1)
        fg = best >= fg_thr
        bg = (best < bg_hi) & (best >= 0)
        labels = jnp.where(fg, gtc[bgt], 0)
        labels = jnp.where(fg | bg, labels, -1)
        # take top per_im by (fg first, then score=iou)
        pri = jnp.where(fg, 2.0 + best, jnp.where(bg, 1.0 - best, -1.0))
        k = min(per_im, R)
        _, sel = jax.lax.top_k(pri, k)
        m = gtb[bgt[sel]]
        r = roi[sel]
        rw = jnp.maximum(r[:, 2] - r[:, 0], 1.0)
        rh = jnp.maximum(r[:, 3] - r[:, 1], 1.0)
        mw = jnp.maximum(m[:, 2] - m[:, 0], 1.0)
        mh = jnp.maximum(m[:, 3] - m[:, 1], 1.0)
        t = jnp.stack([
            ((m[:, 0] + m[:, 2]) - (r[:, 0] + r[:, 2])) / 2 / rw,
            ((m[:, 1] + m[:, 3]) - (r[:, 1] + r[:, 3])) / 2 / rh,
            jnp.log(mw / rw), jnp.log(mh / rh)], axis=1)
        lab_s = labels[sel]
        w = (lab_s > 0).astype(jnp.float32)[:, None]
        return r, lab_s, t * w, w

    out = jax.vmap(one)(rois, gt_boxes, gt_classes)
    r, labels, targets, weights = out
    return {"Rois": [r], "LabelsInt32": [labels],
            "BboxTargets": [targets], "BboxInsideWeights": [weights],
            "BboxOutsideWeights": [weights]}


@register_op("yolov3_loss")
def _yolov3_loss(ctx, ins, attrs):
    """ref yolov3_loss_op.cc, simplified dense: objectness + box + class
    losses against assigned anchors.  X [N, A*(5+C), H, W],
    GtBox [N, G, 4] (cx, cy, w, h normalized), GtLabel [N, G]."""
    x = single_input(ins, "X").astype(jnp.float32)
    gt_box = single_input(ins, "GTBox").astype(jnp.float32)
    gt_label = single_input(ins, "GTLabel").astype(jnp.int32)
    anchors = list(attrs["anchors"])
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    N, CC, H, W = x.shape
    A = len(anchors) // 2
    x = x.reshape(N, A, 5 + class_num, H, W)
    pred_xy = jax.nn.sigmoid(x[:, :, 0:2])
    pred_wh = x[:, :, 2:4]
    pred_obj = x[:, :, 4]
    pred_cls = x[:, :, 5:]

    def one(px, pw, pobj, pcls, gtb, gtl):
        valid = gtb[:, 2] > 0
        # assign each gt to the cell containing its center + best anchor
        gi = jnp.clip((gtb[:, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[:, 1] * H).astype(jnp.int32), 0, H - 1)
        aw = jnp.asarray(anchors[0::2], jnp.float32) / W
        ah = jnp.asarray(anchors[1::2], jnp.float32) / H
        inter = (jnp.minimum(gtb[:, 2:3], aw[None]) *
                 jnp.minimum(gtb[:, 3:4], ah[None]))
        union = (gtb[:, 2:3] * gtb[:, 3:4] + aw[None] * ah[None] - inter)
        best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=1)
        obj_t = jnp.zeros((A, H, W))
        obj_t = obj_t.at[best_a, gj, gi].max(
            jnp.where(valid, 1.0, 0.0))
        # box loss at assigned cells
        tx = gtb[:, 0] * W - gi
        ty = gtb[:, 1] * H - gj
        tw = jnp.log(jnp.maximum(gtb[:, 2] / aw[best_a], 1e-9))
        th = jnp.log(jnp.maximum(gtb[:, 3] / ah[best_a], 1e-9))
        px_g = px[best_a, :, gj, gi]
        pw_g = pw[best_a, :, gj, gi]
        box_l = (jnp.square(px_g[:, 0] - tx) + jnp.square(px_g[:, 1] - ty)
                 + jnp.square(pw_g[:, 0] - tw)
                 + jnp.square(pw_g[:, 1] - th))
        box_loss = jnp.sum(jnp.where(valid, box_l, 0.0))
        # objectness BCE everywhere, except cells whose predicted box
        # overlaps some gt above ignore_thresh (ref semantics: such
        # duplicate-quality predictions are ignored, not pushed to 0)
        ci = (jnp.arange(W, dtype=jnp.float32))[None, None, :]
        cj = (jnp.arange(H, dtype=jnp.float32))[None, :, None]
        pcx = (px[:, 0] + ci) / W
        pcy = (px[:, 1] + cj) / H
        pbw = jnp.exp(jnp.clip(pw[:, 0], -10, 10)) * aw[:, None, None]
        pbh = jnp.exp(jnp.clip(pw[:, 1], -10, 10)) * ah[:, None, None]

        def iou_vs_gt(g):
            ix = (jnp.minimum(pcx + pbw / 2, g[0] + g[2] / 2)
                  - jnp.maximum(pcx - pbw / 2, g[0] - g[2] / 2))
            iy = (jnp.minimum(pcy + pbh / 2, g[1] + g[3] / 2)
                  - jnp.maximum(pcy - pbh / 2, g[1] - g[3] / 2))
            inter_g = jnp.maximum(ix, 0) * jnp.maximum(iy, 0)
            return inter_g / jnp.maximum(
                pbw * pbh + g[2] * g[3] - inter_g, 1e-10)

        ious = jax.vmap(iou_vs_gt)(gtb)          # [G, A, H, W]
        best = jnp.max(jnp.where(valid[:, None, None, None], ious, 0.0),
                       axis=0)
        obj_w = jnp.where((best > ignore_thresh) & (obj_t == 0.0), 0.0, 1.0)
        z = pobj
        obj_bce = jnp.maximum(z, 0) - z * obj_t + jnp.log1p(
            jnp.exp(-jnp.abs(z)))
        obj_loss = jnp.sum(obj_bce * obj_w)
        # class BCE at assigned cells
        pc = pcls[best_a, :, gj, gi]
        onehot = jax.nn.one_hot(gtl, class_num)
        cls_bce = jnp.maximum(pc, 0) - pc * onehot + jnp.log1p(
            jnp.exp(-jnp.abs(pc)))
        cls_loss = jnp.sum(jnp.where(valid[:, None], cls_bce, 0.0))
        return box_loss + obj_loss + cls_loss

    losses = jax.vmap(one)(pred_xy, pred_wh, pred_obj, pred_cls,
                           gt_box, gt_label)
    return {"Loss": [losses]}


@register_op("roi_perspective_transform")
def _roi_perspective_transform(ctx, ins, attrs):
    """ref detection/roi_perspective_transform_op.cc: warp a quadrilateral
    RoI (8 coords: x1,y1,...,x4,y4 clockwise from top-left) into a
    transformed_height x transformed_width rectangle with bilinear
    sampling.  Homography solved per RoI via an 8x8 linear system (the
    classic getPerspectiveTransform), vmapped over RoIs — no scalar loops,
    so XLA batches the solves and the gathers tile onto the VPU."""
    x = single_input(ins, "X")
    rois = single_input(ins, "ROIs").reshape(-1, 8)
    batch_idx = (ins["BatchIdx"][0].reshape(-1).astype(jnp.int32)
                 if ins.get("BatchIdx")
                 else jnp.zeros((rois.shape[0],), jnp.int32))
    th = int(attrs.get("transformed_height", 8))
    tw = int(attrs.get("transformed_width", 8))
    scale = float(attrs.get("spatial_scale", 1.0))
    _, C, H, W = x.shape

    def one(quad, b):
        # src quad corners (feature-map coords), dst rect corners
        sx = quad[0::2] * scale
        sy = quad[1::2] * scale
        dx = jnp.asarray([0.0, tw - 1.0, tw - 1.0, 0.0])
        dy = jnp.asarray([0.0, 0.0, th - 1.0, th - 1.0])
        # solve for H mapping dst -> src: [x',y',1] ~ M @ [x,y,1]
        rows = []
        for i in range(4):
            rows.append(jnp.stack([dx[i], dy[i], 1.0, 0.0, 0.0, 0.0,
                                   -dx[i] * sx[i], -dy[i] * sx[i]]))
            rows.append(jnp.stack([0.0, 0.0, 0.0, dx[i], dy[i], 1.0,
                                   -dx[i] * sy[i], -dy[i] * sy[i]]))
        A = jnp.stack(rows)
        rhs = jnp.stack([sx[0], sy[0], sx[1], sy[1],
                         sx[2], sy[2], sx[3], sy[3]])
        h8 = jnp.linalg.solve(A + 1e-8 * jnp.eye(8), rhs)
        M = jnp.concatenate([h8, jnp.ones((1,))]).reshape(3, 3)
        gy, gx = jnp.meshgrid(jnp.arange(th, dtype=jnp.float32),
                              jnp.arange(tw, dtype=jnp.float32),
                              indexing="ij")
        ones = jnp.ones_like(gx)
        src = M @ jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])
        sxp = src[0] / (src[2] + 1e-8)
        syp = src[1] / (src[2] + 1e-8)
        # bilinear sample, zero outside
        x0 = jnp.floor(sxp)
        y0 = jnp.floor(syp)
        wx = sxp - x0
        wy = syp - y0
        valid = ((sxp >= 0) & (sxp <= W - 1) & (syp >= 0) & (syp <= H - 1))
        x0i = jnp.clip(x0, 0, W - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
        y0i = jnp.clip(y0, 0, H - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
        img = x[b]  # [C,H,W]
        v00 = img[:, y0i, x0i]
        v01 = img[:, y0i, x1i]
        v10 = img[:, y1i, x0i]
        v11 = img[:, y1i, x1i]
        val = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
               + v10 * (1 - wx) * wy + v11 * wx * wy)
        val = jnp.where(valid[None, :], val, 0.0)
        return val.reshape(C, th, tw), valid.reshape(th, tw), M

    outs, masks, mats = jax.vmap(one)(rois, batch_idx)
    return {"Out": [outs.astype(x.dtype)],
            "Mask": [masks.astype(jnp.int32)],
            "TransformMatrix": [mats]}
