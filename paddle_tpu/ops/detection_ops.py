"""Detection toolbox ops (SSD / Faster-RCNN / YOLO family).

Parity target: /root/reference/paddle/fluid/operators/detection/ (~25 ops).
This module covers the core geometry ops densely and statically (TPU needs
static shapes — NMS returns fixed-size outputs with validity counts instead
of the reference's variable-length LoD outputs).
Initial set: prior_box, density_prior_box, box_coder, iou_similarity,
anchor_generator, yolo_box-era transforms, multiclass_nms (static),
bipartite_match, polygon_box_transform.  Remaining ops tracked in
docs/PARITY.md.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op, single_input


@register_op("iou_similarity", stop_gradient=True)
def _iou_similarity(ctx, ins, attrs):
    x = single_input(ins)          # (N, 4) xmin,ymin,xmax,ymax
    y = single_input(ins, "Y")     # (M, 4)
    area = lambda b: jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0)
    ix1 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy1 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix2 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy2 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area(x)[:, None] + area(y)[None, :] - inter
    return {"Out": [inter / jnp.maximum(union, 1e-10)]}


@register_op("box_coder", stop_gradient=True)
def _box_coder(ctx, ins, attrs):
    """encode_center_size / decode_center_size (ref detection/box_coder_op)."""
    prior = single_input(ins, "PriorBox")        # (M, 4)
    tb = single_input(ins, "TargetBox")
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    code_type = attrs.get("code_type", "encode_center_size")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, None, 2] - tb[:, None, 0]
        th = tb[:, None, 3] - tb[:, None, 1]
        tcx = tb[:, None, 0] + tw / 2
        tcy = tb[:, None, 1] + th / 2
        ox = (tcx - pcx[None]) / pw[None]
        oy = (tcy - pcy[None]) / ph[None]
        ow = jnp.log(jnp.abs(tw / pw[None]) + 1e-10)
        oh = jnp.log(jnp.abs(th / ph[None]) + 1e-10)
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if var is not None:
            out = out / var[None]
    else:  # decode_center_size
        if var is not None:
            tb = tb * var[None]
        dcx = tb[..., 0] * pw + pcx
        dcy = tb[..., 1] * ph + pcy
        dw = jnp.exp(tb[..., 2]) * pw
        dh = jnp.exp(tb[..., 3]) * ph
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2, dcy + dh / 2], axis=-1)
    return {"OutputBox": [out]}


@register_op("prior_box", stop_gradient=True)
def _prior_box(ctx, ins, attrs):
    """SSD prior boxes per feature-map cell (ref detection/prior_box_op.cc)."""
    feat = single_input(ins, "Input")   # (N, C, H, W)
    image = single_input(ins, "Image")  # (N, C, IH, IW)
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if attrs.get("flip", False):
                ars.append(1.0 / float(ar))
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w", 0) or iw / w)
    step_h = float(attrs.get("step_h", 0) or ih / h)
    offset = float(attrs.get("offset", 0.5))
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * math.sqrt(ar) / 2
            bh = ms / math.sqrt(ar) / 2
            boxes.append((bw, bh))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            s = math.sqrt(ms * mx) / 2
            boxes.append((s, s))
    nb = len(boxes)
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)           # (H, W)
    wh = jnp.asarray(boxes)                   # (nb, 2)
    out = jnp.stack([
        (cxg[..., None] - wh[None, None, :, 0]) / iw,
        (cyg[..., None] - wh[None, None, :, 1]) / ih,
        (cxg[..., None] + wh[None, None, :, 0]) / iw,
        (cyg[..., None] + wh[None, None, :, 1]) / ih,
    ], axis=-1)                               # (H, W, nb, 4)
    if attrs.get("clip", False):
        out = jnp.clip(out, 0.0, 1.0)
    variances = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]))
    var = jnp.broadcast_to(variances, out.shape)
    return {"Boxes": [out], "Variances": [var]}


@register_op("density_prior_box", stop_gradient=True)
def _density_prior_box(ctx, ins, attrs):
    """ref detection/density_prior_box_op.cc."""
    feat = single_input(ins, "Input")
    image = single_input(ins, "Image")
    fixed_sizes = [float(s) for s in attrs.get("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in attrs.get("fixed_ratios", [])]
    densities = [int(d) for d in attrs.get("densities", [])]
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w", 0) or iw / w)
    step_h = float(attrs.get("step_h", 0) or ih / h)
    offset = float(attrs.get("offset", 0.5))
    boxes = []  # per-cell (dx, dy, bw, bh) offsets
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * math.sqrt(ratio)
            bh = size / math.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    dx = -size / 2.0 + step / 2.0 + dj * step
                    dy = -size / 2.0 + step / 2.0 + di * step
                    boxes.append((dx, dy, bw, bh))
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    b = jnp.asarray(boxes)  # (nb, 4)
    ctrx = cxg[..., None] + b[None, None, :, 0]
    ctry = cyg[..., None] + b[None, None, :, 1]
    out = jnp.stack([
        (ctrx - b[None, None, :, 2] / 2) / iw,
        (ctry - b[None, None, :, 3] / 2) / ih,
        (ctrx + b[None, None, :, 2] / 2) / iw,
        (ctry + b[None, None, :, 3] / 2) / ih,
    ], axis=-1)
    if attrs.get("clip", False):
        out = jnp.clip(out, 0.0, 1.0)
    variances = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]))
    return {"Boxes": [out],
            "Variances": [jnp.broadcast_to(variances, out.shape)]}


@register_op("anchor_generator", stop_gradient=True)
def _anchor_generator(ctx, ins, attrs):
    """RPN anchors (ref detection/anchor_generator_op.cc)."""
    feat = single_input(ins, "Input")
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    stride = [float(s) for s in attrs["stride"]]
    offset = float(attrs.get("offset", 0.5))
    h, w = feat.shape[2], feat.shape[3]
    anchors = []
    for r in ratios:
        for s in sizes:
            aw = s * math.sqrt(1.0 / r)
            ah = s * math.sqrt(r)
            anchors.append((aw / 2, ah / 2))
    a = jnp.asarray(anchors)
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    out = jnp.stack([
        cxg[..., None] - a[None, None, :, 0],
        cyg[..., None] - a[None, None, :, 1],
        cxg[..., None] + a[None, None, :, 0],
        cyg[..., None] + a[None, None, :, 1],
    ], axis=-1)
    variances = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]))
    return {"Anchors": [out],
            "Variances": [jnp.broadcast_to(variances, out.shape)]}


def _nms_single_class(boxes, scores, iou_thr, score_thr, max_out):
    """Static-shape greedy NMS: returns (max_out,) indices (-1 pad) — the
    TPU-friendly replacement for variable-length NMS outputs."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    scores_s = scores[order]
    area = jnp.maximum(boxes_s[:, 2] - boxes_s[:, 0], 0) * jnp.maximum(
        boxes_s[:, 3] - boxes_s[:, 1], 0)

    def iou_with(i, j_boxes):
        b = boxes_s[i]
        ix1 = jnp.maximum(b[0], j_boxes[:, 0])
        iy1 = jnp.maximum(b[1], j_boxes[:, 1])
        ix2 = jnp.minimum(b[2], j_boxes[:, 2])
        iy2 = jnp.minimum(b[3], j_boxes[:, 3])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        ab = jnp.maximum(b[2] - b[0], 0) * jnp.maximum(b[3] - b[1], 0)
        return inter / jnp.maximum(ab + area - inter, 1e-10)

    def body(i, keep):
        ious = iou_with(i, boxes_s)
        suppress = (ious > iou_thr) & (jnp.arange(n) > i) & keep[i]
        return jnp.where(suppress, False, keep)

    keep = scores_s > score_thr
    keep = jax.lax.fori_loop(0, n, body, keep)
    kept_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    sel = jnp.full((max_out,), -1, jnp.int32)
    sel = sel.at[jnp.where(keep, kept_rank, max_out)
                 .clip(0, max_out)].set(
        jnp.where(keep, order, -1).astype(jnp.int32), mode="drop")
    return sel


@register_op("multiclass_nms", stop_gradient=True)
def _multiclass_nms(ctx, ins, attrs):
    """Static-shape multiclass NMS (ref detection/multiclass_nms_op.cc).
    Output: (N, keep_top_k, 6) [class, score, x1, y1, x2, y2], score==-1
    marks padding rows; plus a per-image valid count."""
    boxes = single_input(ins, "BBoxes")    # (N, M, 4)
    scores = single_input(ins, "Scores")   # (N, C, M)
    score_thr = float(attrs.get("score_threshold", 0.0))
    iou_thr = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 200))
    background = int(attrs.get("background_label", 0))
    n, c, m = scores.shape
    per_cls = min(nms_top_k if nms_top_k > 0 else m, m)

    def one_image(bxs, scs):
        rows = []
        for cls in range(c):
            if cls == background:
                continue
            sel = _nms_single_class(bxs, scs[cls], iou_thr, score_thr,
                                    per_cls)
            valid = sel >= 0
            cls_scores = jnp.where(valid, scs[cls][sel.clip(0)], -1.0)
            cls_boxes = bxs[sel.clip(0)]
            rows.append(jnp.concatenate([
                jnp.full((per_cls, 1), float(cls)),
                cls_scores[:, None],
                jnp.where(valid[:, None], cls_boxes, 0.0)], axis=1))
        allrows = jnp.concatenate(rows, axis=0)
        top = min(keep_top_k, allrows.shape[0])
        _, idx = jax.lax.top_k(allrows[:, 1], top)
        out = allrows[idx]
        if top < keep_top_k:
            out = jnp.pad(out, [(0, keep_top_k - top), (0, 0)],
                          constant_values=-1.0)
        count = jnp.sum((out[:, 1] > score_thr).astype(jnp.int32))
        return out, count

    outs, counts = jax.vmap(one_image)(boxes, scores)
    return {"Out": [outs], "NmsRoisNum": [counts]}


@register_op("bipartite_match", stop_gradient=True)
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching on a similarity matrix
    (ref detection/bipartite_match_op.cc), static-shape greedy variant."""
    dist = single_input(ins, "DistMat")  # (N, M) rows=gt cols=pred
    n, m = dist.shape

    def body(_, carry):
        d, match_idx, match_dist = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        best = d[i, j]
        do = best > -1e9
        match_idx = jnp.where(do, match_idx.at[j].set(i), match_idx)
        match_dist = jnp.where(do, match_dist.at[j].set(best), match_dist)
        d = jnp.where(do, d.at[i, :].set(-1e10).at[:, j].set(-1e10), d)
        return d, match_idx, match_dist

    init = (jnp.where(dist > 0, dist, -1e10),
            jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), dist.dtype))
    _, match_idx, match_dist = jax.lax.fori_loop(0, min(n, m), body, init)
    return {"ColToRowMatchIndices": [match_idx[None]],
            "ColToRowMatchDist": [match_dist[None]]}


@register_op("polygon_box_transform", stop_gradient=True)
def _polygon_box_transform(ctx, ins, attrs):
    """ref detection/polygon_box_transform_op.cc: offset channels to
    absolute coords on activated cells."""
    x = single_input(ins)  # (N, geo_channels, H, W)
    n, c, h, w = x.shape
    xg = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4.0
    yg = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4.0
    even = jnp.arange(c) % 2 == 0
    base = jnp.where(even[None, :, None, None], xg, yg)
    return {"Output": [base - x]}


@register_op("yolo_box", stop_gradient=True)
def _yolo_box(ctx, ins, attrs):
    """Decode YOLOv3 head to boxes (ref operators/detection/yolo_box-era;
    yolov3_loss's inference twin)."""
    x = single_input(ins)          # (N, A*(5+C), H, W)
    img_size = single_input(ins, "ImgSize")  # (N, 2) h, w
    anchors = attrs["anchors"]
    class_num = int(attrs["class_num"])
    conf_thresh = float(attrs.get("conf_thresh", 0.01))
    downsample = int(attrs.get("downsample_ratio", 32))
    na = len(anchors) // 2
    n, _, h, w = x.shape
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = (jax.nn.sigmoid(x[:, :, 0]) +
          jnp.arange(w, dtype=jnp.float32)[None, None, None, :]) / w
    gy = (jax.nn.sigmoid(x[:, :, 1]) +
          jnp.arange(h, dtype=jnp.float32)[None, None, :, None]) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    gw = jnp.exp(x[:, :, 2]) * aw / (w * downsample)
    gh = jnp.exp(x[:, :, 3]) * ah / (h * downsample)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imgh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imgw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack([(gx - gw / 2) * imgw, (gy - gh / 2) * imgh,
                       (gx + gw / 2) * imgw, (gy + gh / 2) * imgh], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    scores = jnp.where(scores > conf_thresh, scores, 0.0)
    return {"Boxes": [boxes], "Scores": [scores]}


@register_op("box_clip", stop_gradient=True)
def _box_clip(ctx, ins, attrs):
    boxes = single_input(ins, "Input")
    im_info = single_input(ins, "ImInfo")  # (N, 3) h, w, scale
    h = im_info[:, 0][:, None, None] - 1
    w = im_info[:, 1][:, None, None] - 1
    b = boxes.reshape(boxes.shape[0], -1, 4)
    out = jnp.stack([jnp.clip(b[..., 0], 0, w[..., 0]),
                     jnp.clip(b[..., 1], 0, h[..., 0]),
                     jnp.clip(b[..., 2], 0, w[..., 0]),
                     jnp.clip(b[..., 3], 0, h[..., 0])], axis=-1)
    return {"Output": [out.reshape(boxes.shape)]}
