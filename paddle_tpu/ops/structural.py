"""Structural pseudo-ops the Executor itself interprets.

`feed`/`fetch` mirror the reference's feed/fetch ops (operators/controlflow/
feed_op.cc, fetch_op.cc) — here they are program-level markers only; the
Executor passes feeds/fetches as function inputs/outputs.  `autodiff` is the
marker appended by framework/backward.py and expanded by the Executor via
jax.vjp.
"""
from ..framework.registry import register_op


@register_op("feed", doc="structural: executor input marker")
def _feed(ctx, ins, attrs):
    return {"Out": ins.get("X", [])}


@register_op("fetch", doc="structural: executor output marker")
def _fetch(ctx, ins, attrs):
    return {"Out": ins.get("X", [])}


@register_op("autodiff", doc="structural: vjp boundary (framework/backward.py)")
def _autodiff(ctx, ins, attrs):
    raise RuntimeError("autodiff op is expanded by the Executor; "
                       "it must not be lowered directly")
