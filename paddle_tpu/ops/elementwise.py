"""Elementwise binary ops with the reference's axis-broadcast semantics,
plus comparison and logical ops.

Parity: operators/elementwise/ (elementwise_op_function.h broadcast
machinery; add/sub/mul/div/min/max/pow/mod/floordiv), operators/controlflow/
compare_op.cc, logical_op.cc.

Reference broadcast rule: Y's dims align with X starting at `axis`
(axis == -1 -> trailing alignment), then NumPy-style broadcast.  XLA fuses
the resulting broadcast+op into surrounding computation, so this costs
nothing at run time.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.registry import register_op


def _align(x, y, axis):
    if x.ndim == y.ndim:
        return x, y
    if y.ndim > x.ndim:  # allow either operand to be the smaller one
        y_al, x_al = _align(y, x, axis)
        return x_al, y_al
    axis = int(axis)
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return x, y.reshape(new_shape)


def _binary(name, fn, out_slot="Out"):
    @register_op(name)
    def _lower(ctx, ins, attrs, _fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _align(x, y, attrs.get("axis", -1))
        # AMP: a bf16 activation meeting an f32 operand (bias/residual
        # master copy) computes in bf16 — numpy promotion would silently
        # lift the whole activation plane back to f32, doubling the HBM
        # traffic of every residual saved for backward (measured ~2ms of
        # the flagship step in docs/profile_r03)
        from ..core import flags
        if (flags.get_flag("amp_bf16")
                and {jnp.dtype(x.dtype), jnp.dtype(y.dtype)}
                == {jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32)}):
            x = x.astype(jnp.bfloat16)
            y = y.astype(jnp.bfloat16)
        return {out_slot: [_fn(x, y)]}
    return _lower


_binary("elementwise_add", jnp.add)
_binary("elementwise_sub", jnp.subtract)
_binary("elementwise_mul", jnp.multiply)
_binary("elementwise_div", jnp.divide)
_binary("elementwise_min", jnp.minimum)
_binary("elementwise_max", jnp.maximum)
_binary("elementwise_pow", jnp.power)
_binary("elementwise_mod", jnp.mod)
_binary("elementwise_floordiv", jnp.floor_divide)


def _compare(name, fn):
    @register_op(name, stop_gradient=True)
    def _lower(ctx, ins, attrs, _fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _align(x, y, attrs.get("axis", -1))
        return {"Out": [_fn(x, y)]}
    return _lower


_compare("equal", jnp.equal)
_compare("not_equal", jnp.not_equal)
_compare("less_than", jnp.less)
_compare("less_equal", jnp.less_equal)
_compare("greater_than", jnp.greater)
_compare("greater_equal", jnp.greater_equal)


@register_op("logical_and", stop_gradient=True)
def _land(ctx, ins, attrs):
    return {"Out": [jnp.logical_and(ins["X"][0], ins["Y"][0])]}


@register_op("logical_or", stop_gradient=True)
def _lor(ctx, ins, attrs):
    return {"Out": [jnp.logical_or(ins["X"][0], ins["Y"][0])]}


@register_op("logical_xor", stop_gradient=True)
def _lxor(ctx, ins, attrs):
    return {"Out": [jnp.logical_xor(ins["X"][0], ins["Y"][0])]}


@register_op("logical_not", stop_gradient=True)
def _lnot(ctx, ins, attrs):
    return {"Out": [jnp.logical_not(ins["X"][0])]}
