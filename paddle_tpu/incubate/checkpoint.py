"""Sharded, corruption-safe checkpointing (orbax-style, self-contained).

Capability parity with the reference's durable-checkpoint discipline:
  * /root/reference/go/pserver/service.go:346 — checkpoint() computes a
    CRC32 over the serialized state, writes to a temp file, then commits
    with an atomic rename; a torn write is detected at load;
  * contrib/trainer.py:663,763 — serial-numbered directories + rotation;
  * SURVEY.md §5 — the TPU equivalent must shard: every process saves
    only its addressable shards of each jax.Array, and load reassembles
    (or re-shards) them, so a multi-host mesh never funnels the whole
    model through one host.

Layout of one checkpoint:
    <root>/checkpoint_<serial>/
        shard_00000-of-00001.npz       per-process piece file
        manifest.json                  written LAST = commit point
The manifest records every array's global shape/dtype, each piece's
slice, and a CRC32 per shard file.  A checkpoint without a manifest, or
whose shard CRCs mismatch, is invalid and is skipped by
latest_checkpoint() — resume falls back to the newest valid serial.

Elastic resize (ISSUE 14): a checkpoint written as N-sharded resumes as
M-sharded for any N, M (including 1).  :func:`reshard` is the PURE
planner — given a manifest it maps every array onto ``n_to`` shard
files (contiguous axis-0 chunks by default; a ``layout`` override picks
a different split axis per array, e.g. the model axis of a
tensor-parallel weight).  :func:`reshard_checkpoint` is the IO driver:
it gathers the source pieces, re-splits, and commits the M-sharded copy
as a NEW serial under the same root, manifest written last — a crash or
torn write mid-reshard leaves an invalid serial that
``latest_checkpoint`` skips, so resume falls back to the pre-resize
checkpoint instead of bricking the start.
"""
from __future__ import annotations

import json
import os
import shutil
import warnings
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ..observability import journal as obs_journal
from ..resilience import chaos

MANIFEST = "manifest.json"

# process-0 wait for the other processes' sidecars (monkeypatchable in
# crash-consistency tests)
SIDECAR_TIMEOUT = 300.0


class CheckpointCorrupt(Exception):
    pass


def _npdtype(name):
    if name == "bfloat16":
        import jax.numpy as jnp
        return jnp.bfloat16
    return np.dtype(name)


def _pieces_of(name: str, value) -> list:
    """Split a value into (key, slices, np_array) pieces this process
    owns.  jax.Arrays contribute their addressable shards; host arrays
    contribute one full piece."""
    import jax
    pieces = []
    if isinstance(value, jax.Array):
        for i, sh in enumerate(value.addressable_shards):
            if sh.replica_id != 0:
                # replicated arrays expose one identical shard per device;
                # write the data once, not once per replica
                continue
            idx = []
            for d, sl in enumerate(sh.index):
                start = 0 if sl.start is None else int(sl.start)
                stop = (value.shape[d] if sl.stop is None else int(sl.stop))
                idx.append((start, stop))
            dat = np.asarray(sh.data)
            if dat.dtype.name == "bfloat16":
                dat = dat.astype(np.float32)
            pieces.append((f"{name}@{i}", idx, dat))
    else:
        arr = np.asarray(value)
        if arr.dtype.name == "bfloat16":
            arr = arr.astype(np.float32)
        pieces.append((f"{name}@0", [(0, s) for s in arr.shape], arr))
    return pieces


def save_state(dirname: str, state: Dict[str, Any],
               meta: Optional[dict] = None,
               process_index: Optional[int] = None,
               num_processes: Optional[int] = None):
    """Write this process's shard of `state` + (on process 0) the manifest.

    Single-process callers can ignore process arguments."""
    import jax
    # transient-failure site: a raise-kind fault here models the flaky
    # filesystem the retry policy in Trainer._save_checkpoint absorbs
    chaos.trigger("checkpoint.save", exc=OSError)
    p = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if num_processes is None else num_processes
    os.makedirs(dirname, exist_ok=True)
    shard_name = f"shard_{p:05d}-of-{n:05d}.npz"

    arrays, entries = {}, {}
    for name, value in state.items():
        dtype = np.asarray(value).dtype.name if not hasattr(value, "dtype") \
            else value.dtype.name
        shape = list(np.shape(value))
        pcs = []
        for key, idx, dat in _pieces_of(name, value):
            arrays[key] = dat
            pcs.append({"key": key, "index": idx, "shard": shard_name})
        entries[name] = {"shape": shape, "dtype": str(dtype),
                         "pieces": pcs}

    tmp = os.path.join(dirname, shard_name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    with open(tmp, "rb") as f:
        crc = zlib.crc32(f.read())
    shard_path = os.path.join(dirname, shard_name)
    os.replace(tmp, shard_path)               # atomic (ref :346)
    # torn-write site: truncates the committed shard so it no longer
    # matches the CRC the manifest is about to record — the exact state
    # a crash mid-flush leaves behind; load/is_valid must skip the serial
    chaos.corrupt_file("checkpoint.shard_write", shard_path)

    # every process contributes a sidecar; process 0 merges them into the
    # manifest, which is written last as the commit point
    side = {"entries": entries, "crc": {shard_name: crc}}
    side_path = os.path.join(dirname, f".side_{p:05d}.json")
    with open(side_path + ".tmp", "w") as f:
        json.dump(side, f)
    os.replace(side_path + ".tmp", side_path)

    if p == 0:
        # barrier via the shared filesystem: every process writes its
        # sidecar atomically; process 0 waits for all of them before
        # merging (multi-host saves share the checkpoint dir).  A reused
        # checkpoint dir may hold a sidecar from a PREVIOUS save (e.g. a
        # crash between shard write and manifest commit): merging it
        # would stamp stale CRCs into this manifest, so a sidecar only
        # counts once it is consistent with the current save — it names
        # this save's exact shard layout and is no older than the shard
        # file it describes (each process writes shard first, sidecar
        # second; a leftover sidecar predates a rewritten shard).
        import time
        deadline = time.time() + SIDECAR_TIMEOUT
        merged_entries: Dict[str, dict] = {}
        crcs: Dict[str, int] = {}
        for q in range(n):
            qp = os.path.join(dirname, f".side_{q:05d}.json")
            want_shard = f"shard_{q:05d}-of-{n:05d}.npz"
            while True:
                s = _load_sidecar_if_current(dirname, qp, want_shard)
                if s is not None:
                    break
                if time.time() > deadline:
                    raise CheckpointCorrupt(
                        f"timed out waiting for process {q}'s shard "
                        f"sidecar {qp} (missing or stale)")
                time.sleep(0.05)
            crcs.update(s["crc"])
            for name, e in s["entries"].items():
                if name in merged_entries:
                    merged_entries[name]["pieces"].extend(e["pieces"])
                else:
                    merged_entries[name] = e
        manifest = {"entries": merged_entries, "crc": crcs,
                    "meta": meta or {}, "num_processes": n}
        mtmp = os.path.join(dirname, MANIFEST + ".tmp")
        with open(mtmp, "w") as f:
            json.dump(manifest, f)
        os.replace(mtmp, os.path.join(dirname, MANIFEST))
        # the manifest is the commit point; consumed sidecars must not
        # outlive it, or the next save into a reused dir could merge them
        for q in range(n):
            try:
                os.remove(os.path.join(dirname, f".side_{q:05d}.json"))
            except OSError:
                pass


def _load_sidecar_if_current(dirname: str, side_path: str,
                             want_shard: str) -> Optional[dict]:
    """Load a per-process sidecar iff it belongs to the save in
    progress: it must describe exactly `want_shard` (a sidecar from a
    run with a different process count names a different file) and be
    at least as new as that shard file on disk.  Returns None (keep
    waiting) otherwise."""
    try:
        with open(side_path) as f:
            s = json.load(f)
        if set(s.get("crc", {})) != {want_shard}:
            return None
        shard_path = os.path.join(dirname, want_shard)
        if os.path.getmtime(side_path) < os.path.getmtime(shard_path):
            return None         # shard rewritten after this sidecar: stale
        return s
    except (OSError, ValueError):
        return None             # not there yet / torn mid-write


def is_valid(dirname: str) -> bool:
    """Manifest present and every shard file matches its CRC."""
    mpath = os.path.join(dirname, MANIFEST)
    if not os.path.exists(mpath):
        return False
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        for shard, crc in manifest["crc"].items():
            path = os.path.join(dirname, shard)
            with open(path, "rb") as f:
                if zlib.crc32(f.read()) != crc:
                    return False
    except (OSError, ValueError, KeyError):
        return False
    return True


def load_state(dirname: str, device=None) -> Tuple[Dict[str, Any], dict]:
    """Reassemble the full state from all shard files (CRC-checked).
    Returns (state, meta); arrays are host numpy (caller re-shards via
    device_put with its own shardings)."""
    mpath = os.path.join(dirname, MANIFEST)
    if not os.path.exists(mpath):
        raise CheckpointCorrupt(f"no manifest in {dirname}")
    with open(mpath) as f:
        manifest = json.load(f)
    shard_data = {}
    for shard, crc in manifest["crc"].items():
        path = os.path.join(dirname, shard)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointCorrupt(f"missing shard {shard}: {e}")
        if zlib.crc32(raw) != crc:
            raise CheckpointCorrupt(f"CRC mismatch in {shard}")
        import io as _io
        shard_data[shard] = np.load(_io.BytesIO(raw))
    state = {}
    for name, e in manifest["entries"].items():
        dt = _npdtype(e["dtype"])
        store_dt = np.float32 if e["dtype"] == "bfloat16" else dt
        out = np.zeros(e["shape"], dtype=store_dt)
        for pc in e["pieces"]:
            dat = shard_data[pc["shard"]][pc["key"]]
            sl = tuple(slice(a, b) for a, b in pc["index"])
            out[sl] = dat
        state[name] = out.astype(dt) if e["dtype"] == "bfloat16" else out
    return state, manifest.get("meta", {})


# -- elastic resharding (ISSUE 14) -----------------------------------------

Layout = Union[str, Dict[str, int], Callable[[str, Tuple[int, ...]], int]]


def _split_ranges(extent: int, n: int) -> List[Tuple[int, int]]:
    """Contiguous near-even split of [0, extent) into n ranges (first
    ``extent % n`` ranges get the extra element); deterministic, so an
    N→M→N round trip reproduces the original piece boundaries."""
    base, rem = divmod(int(extent), int(n))
    out, start = [], 0
    for i in range(n):
        size = base + (1 if i < rem else 0)
        out.append((start, start + size))
        start += size
    return out


def _split_axis(name: str, shape: Tuple[int, ...], layout: Layout) -> int:
    if callable(layout):
        return int(layout(name, tuple(shape)))
    if isinstance(layout, dict):
        return int(layout.get(name, 0))
    if layout == "axis0":
        return 0
    raise ValueError(f"unknown reshard layout {layout!r} (expected "
                     f"'axis0', a name->axis dict, or a callable)")


def _shard_file(q: int, n: int) -> str:
    return f"shard_{q:05d}-of-{n:05d}.npz"


def reshard(manifest: dict, n_to: int, layout: Layout = "axis0") -> dict:
    """PURE reshard plan: map every array of an N-sharded manifest onto
    ``n_to`` shard files.  Returns a new manifest skeleton (entries with
    piece assignments, ``num_processes``, carried-over meta) whose
    ``crc`` map is empty — the IO driver fills it as it writes each
    shard file.  Arrays split along ``layout``'s axis (axis 0 by
    default, the dp row convention) into contiguous chunks; an array
    too small to split (0-d, or extent < the shard index) simply
    contributes no piece to the tail shards and lands whole-or-partial
    on the head ones — ``load_state`` reassembles from pieces
    regardless of which file holds them."""
    n_to = int(n_to)
    if n_to < 1:
        raise ValueError(f"reshard: n_to must be >= 1, got {n_to}")
    entries: Dict[str, dict] = {}
    for name, e in manifest["entries"].items():
        shape = tuple(int(s) for s in e["shape"])
        pcs = []
        if not shape or shape[0] == 0 or n_to == 1:
            pcs.append({"key": f"{name}@0",
                        "index": [(0, s) for s in shape],
                        "shard": _shard_file(0, n_to)})
        else:
            ax = _split_axis(name, shape, layout)
            if not (0 <= ax < len(shape)):
                raise ValueError(
                    f"reshard: layout axis {ax} out of range for "
                    f"{name!r} with shape {shape}")
            for q, (a, b) in enumerate(_split_ranges(shape[ax], n_to)):
                if a == b:
                    continue          # more shards than rows: skip
                idx = [(0, s) for s in shape]
                idx[ax] = (a, b)
                pcs.append({"key": f"{name}@0", "index": idx,
                            "shard": _shard_file(q, n_to)})
        entries[name] = {"shape": list(shape), "dtype": e["dtype"],
                         "pieces": pcs}
    return {"entries": entries, "crc": {},
            "meta": dict(manifest.get("meta", {})),
            "num_processes": n_to}


def reshard_state(dirname: str, state: Dict[str, Any],
                  meta: Optional[dict], n_to: int,
                  layout: Layout = "axis0"):
    """Write ``state`` (full host arrays) as an ``n_to``-sharded
    checkpoint into ``dirname`` — shard files first, manifest LAST as
    the commit point (the save_state discipline), so a crash mid-write
    leaves an invalid directory, never a half-committed one."""
    src_entries = {}
    for name, value in state.items():
        arr = np.asarray(value)
        src_entries[name] = {"shape": list(arr.shape),
                             "dtype": arr.dtype.name, "pieces": []}
    plan = reshard({"entries": src_entries, "meta": meta or {}},
                   n_to, layout)
    os.makedirs(dirname, exist_ok=True)
    crcs: Dict[str, int] = {}
    # bucket pieces per destination shard file
    by_shard: Dict[str, list] = {}
    for name, e in plan["entries"].items():
        for pc in e["pieces"]:
            by_shard.setdefault(pc["shard"], []).append((name, pc))
    for q in range(n_to):
        shard_name = _shard_file(q, n_to)
        arrays = {}
        for name, pc in by_shard.get(shard_name, ()):
            arr = np.asarray(state[name])
            if arr.dtype.name == "bfloat16":
                arr = arr.astype(np.float32)
            sl = tuple(slice(a, b) for a, b in pc["index"])
            arrays[pc["key"]] = arr[sl]
        tmp = os.path.join(dirname, shard_name + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        with open(tmp, "rb") as f:
            crcs[shard_name] = zlib.crc32(f.read())
        shard_path = os.path.join(dirname, shard_name)
        os.replace(tmp, shard_path)
        # torn-write site (PR 2 idiom): truncate the committed shard so
        # it no longer matches the CRC the manifest is about to record
        # — resume must skip this serial and fall back to the source
        chaos.corrupt_file("checkpoint.reshard_write", shard_path)
    plan["crc"] = crcs
    mtmp = os.path.join(dirname, MANIFEST + ".tmp")
    with open(mtmp, "w") as f:
        json.dump(plan, f)
    os.replace(mtmp, os.path.join(dirname, MANIFEST))


def reshard_checkpoint(root: str, n_to: int,
                       serial: Optional[int] = None,
                       layout: Layout = "axis0") -> int:
    """Gather the newest valid checkpoint (or ``serial``) under
    ``root`` and re-commit it as an ``n_to``-sharded NEW serial; the
    source serial is never touched.  Returns the new serial.  If the
    reshard tears mid-commit, the new serial has no (or a mismatched)
    manifest — ``latest_checkpoint`` skips it with a warning and the
    fleet resumes from the pre-resize checkpoint."""
    src = latest_checkpoint(root) if serial is None else int(serial)
    if src < 0:
        raise CheckpointCorrupt(f"no valid checkpoint under {root} "
                                f"to reshard")
    state, meta = load_state(_serial_dir(root, src))
    meta = dict(meta)
    meta["resharded_from"] = src
    new_serial = latest_checkpoint(root, require_valid=False) + 1
    reshard_state(_serial_dir(root, new_serial), state, meta, n_to,
                  layout)
    obs_journal.emit("checkpoint", "reshard_commit", serial=new_serial,
                     source_serial=src, n_to=n_to, root=root)
    return new_serial


# -- serial-numbered rotation (ref contrib/trainer.py:663,763) -------------

def _serial_dir(root: str, serial: int) -> str:
    return os.path.join(root, f"checkpoint_{serial}")


def save_checkpoint(root: str, state: Dict[str, Any],
                    meta: Optional[dict] = None, max_keep: int = 3,
                    **proc_kw) -> int:
    serial = latest_checkpoint(root, require_valid=False) + 1
    save_state(_serial_dir(root, serial), state, meta, **proc_kw)
    serials = sorted(
        int(n.split("_")[-1]) for n in os.listdir(root)
        if n.startswith("checkpoint_") and n.split("_")[-1].isdigit())
    if max_keep > 0:
        for s in serials[:-max_keep]:
            shutil.rmtree(_serial_dir(root, s), ignore_errors=True)
    # the manifest landed: this serial is the fleet's newest durable
    # state — a timeline anchor for "what could that rank resume from"
    obs_journal.emit("checkpoint", "commit", serial=serial, root=root,
                     vars=len(state))
    return serial


def latest_checkpoint(root: str, require_valid: bool = True) -> int:
    """Newest serial; with require_valid, newest whose CRCs verify —
    a torn/corrupt checkpoint (e.g. a reshard that died mid-commit) is
    skipped with a loud warning so resume falls back instead of
    bricking the start (the PR 12 corrupt-entry idiom)."""
    if not os.path.isdir(root):
        return -1
    serials = sorted(
        (int(n.split("_")[-1]) for n in os.listdir(root)
         if n.startswith("checkpoint_") and n.split("_")[-1].isdigit()),
        reverse=True)
    for s in serials:
        if not require_valid or is_valid(_serial_dir(root, s)):
            return s
        warnings.warn(
            f"checkpoint {_serial_dir(root, s)} is torn or corrupt "
            f"(missing manifest or CRC mismatch); falling back to the "
            f"next older valid serial", RuntimeWarning, stacklevel=2)
    return -1


def load_checkpoint(root: str, serial: Optional[int] = None
                    ) -> Tuple[Dict[str, Any], dict, int]:
    s = latest_checkpoint(root) if serial is None else serial
    if s < 0:
        raise CheckpointCorrupt(f"no valid checkpoint under {root}")
    state, meta = load_state(_serial_dir(root, s))
    return state, meta, s
