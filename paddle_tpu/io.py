"""Save/load of variables and inference models.

Capability parity with /root/reference/python/paddle/fluid/io.py
(save_vars:89, save_persistables:270, load_vars:313, load_persistables:490,
save_inference_model:570, load_inference_model:704) and the save/load ops
(operators/save_op.cc, load_op.cc, save_combine_op.cc).

Format: one .npz per save (combine-style) + program JSON.  Durable
sharded checkpointing (per-process shard files, CRC32 + atomic rename,
rotation, corrupt-fallback resume) lives in paddle_tpu/incubate/
checkpoint.py and backs the Trainer's checkpoint cadence.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

import numpy as np

from .core.enforce import check_arg
from .framework.executor import Executor, Scope, global_scope
from .framework.program import Program, Variable, default_main_program

MODEL_FILENAME = "__model__"
PARAMS_FILENAME = "__params__.npz"


def _to_numpy(v):
    arr = np.asarray(v)
    if arr.dtype.name == "bfloat16":
        # npz has no bf16; store as f32 with a marker handled in load
        return arr.astype(np.float32), "bfloat16"
    return arr, arr.dtype.name


def save_vars(executor: Executor, dirname: str, var_names: Sequence[str],
              scope: Optional[Scope] = None,
              filename: str = PARAMS_FILENAME):
    scope = scope or executor.scope
    os.makedirs(dirname, exist_ok=True)
    arrays, dtypes = {}, {}
    for name in var_names:
        val = scope.find_var(name)
        check_arg(val is not None, f"var {name!r} not found in scope")
        arr, dt = _to_numpy(val)
        arrays[name] = arr
        dtypes[name] = dt
    # write through a file object so np.savez cannot append '.npz' to a
    # custom filename and break the load path
    with open(os.path.join(dirname, filename), "wb") as f:
        np.savez(f, **arrays)
    with open(os.path.join(dirname, filename + ".dtypes"), "w") as f:
        json.dump(dtypes, f)


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      filename: str = PARAMS_FILENAME):
    program = main_program or default_main_program()
    names = [v.name for v in program.list_vars() if v.persistable]
    names = [n for n in names if executor.scope.find_var(n) is not None]
    save_vars(executor, dirname, names, filename=filename)


def save_params(executor, dirname, main_program=None,
                filename=PARAMS_FILENAME):
    program = main_program or default_main_program()
    names = [p.name for p in program.all_parameters()]
    save_vars(executor, dirname, names, filename=filename)


def load_vars(executor: Executor, dirname: str,
              var_names: Optional[Sequence[str]] = None,
              scope: Optional[Scope] = None,
              filename: str = PARAMS_FILENAME):
    import jax
    scope = scope or executor.scope
    path = os.path.join(dirname, filename)
    data = np.load(path)
    dtypes = {}
    dt_path = path + ".dtypes"
    if os.path.exists(dt_path):
        with open(dt_path) as f:
            dtypes = json.load(f)
    device = executor.place.jax_device()
    names = var_names if var_names is not None else list(data.files)
    for name in names:
        check_arg(name in data.files, f"{name!r} missing in checkpoint")
        arr = data[name]
        if dtypes.get(name) == "bfloat16":
            import jax.numpy as jnp
            arr = arr.astype(jnp.bfloat16)
        scope.set_var(name, jax.device_put(arr, device))


def load_persistables(executor, dirname, main_program=None,
                      filename=PARAMS_FILENAME):
    program = main_program or default_main_program()
    names = [v.name for v in program.list_vars() if v.persistable]
    load_vars(executor, dirname, names, filename=filename)


load_params = load_persistables


def save_train_program(dirname: str,
                       main_program: Optional[Program] = None,
                       startup_program: Optional[Program] = None):
    """Save the TRAIN programs (startup + main with backward/optimizer
    ops) as JSON for the native C training entry — the reference's
    saved-ProgramDesc train path (train/demo/demo_trainer.cc:1 loads
    `startup_program` / `main_program` files and steps the Executor
    from pure C++)."""
    from .framework.program import default_startup_program
    main = main_program or default_main_program()
    startup = startup_program or default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "main_program.json"), "w") as f:
        json.dump(main.to_dict(), f)
    with open(os.path.join(dirname, "startup_program.json"), "w") as f:
        json.dump(startup.to_dict(), f)
    return dirname


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable],
                         executor: Executor,
                         main_program: Optional[Program] = None,
                         model_filename: str = MODEL_FILENAME,
                         params_filename: str = PARAMS_FILENAME):
    """Prune program to the inference slice + save params
    (ref io.py:570)."""
    program = main_program or default_main_program()
    program = program.clone(for_test=True)
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in target_vars]
    pruned = program.prune(feeded_var_names, fetch_names)
    os.makedirs(dirname, exist_ok=True)
    meta = {"program": pruned.to_dict(),
            "feed_names": list(feeded_var_names),
            "fetch_names": fetch_names}
    with open(os.path.join(dirname, model_filename), "w") as f:
        json.dump(meta, f)
    persist = [v.name for v in pruned.list_vars() if v.persistable]
    persist = [n for n in persist if executor.scope.find_var(n) is not None]
    save_vars(executor, dirname, persist, filename=params_filename)
    return fetch_names


def load_inference_model(dirname: str, executor: Executor,
                         model_filename: str = MODEL_FILENAME,
                         params_filename: str = PARAMS_FILENAME):
    """ref io.py:704 — returns (program, feed_names, fetch_names)."""
    with open(os.path.join(dirname, model_filename)) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    names = [v.name for v in program.list_vars() if v.persistable]
    if names:
        load_vars(executor, dirname, names, filename=params_filename)
    return program, meta["feed_names"], meta["fetch_names"]
