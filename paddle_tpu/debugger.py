"""Program debugging / visualization helpers.

Capability parity with the reference's
python/paddle/fluid/debugger.py:118 (draw_block_graphviz via the
graphviz.py DOT builder) and its pprint_program_codes program printer —
re-designed for the Program IR here: plain DOT text emission (no
external graphviz python package; render with `dot -Tpng`).
"""
from __future__ import annotations

from typing import Optional, Set

from .framework.program import Parameter, Program

__all__ = ["draw_block_graphviz", "pprint_program_codes"]


def _dot_escape(s: str) -> str:
    """Escape a name for use inside a double-quoted DOT label.

    Backslash must go first (else it re-escapes the escapes we add);
    quotes would end the label string; angle brackets / braces / pipe
    are record- and HTML-label metacharacters that several graphviz
    versions mis-lex even in plain labels (e.g. `fetch<0>`-style var
    names), so they are backslash-escaped too; literal newlines become
    the DOT `\\n` line break."""
    out = []
    for ch in s:
        if ch == "\\":
            out.append("\\\\")
        elif ch == '"':
            out.append('\\"')
        elif ch == "\n":
            out.append("\\n")
        elif ch in "<>{}|":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def draw_block_graphviz(block, highlights: Optional[Set[str]] = None,
                        path: str = "./temp.dot",
                        show_backward: bool = False,
                        highlight=None) -> str:
    """Write the block's dataflow graph as a DOT file (ref
    debugger.py:118).  Ops are boxes, vars are ellipses (Parameters
    shaded), edges follow input/output names; names in `highlights`
    are drawn red.  Returns the path.

    ``highlight`` renders verifier findings (paddle_tpu/analysis) onto
    the graph: an AnalysisResult, or an iterable of Finding records /
    their dicts.  Findings anchored to this block color their op node
    — dead ops (code ``dead_op``) fill grey, error-severity findings
    fill red, other warnings fill orange — and every var named by a
    finding gets a red outline.  Composes with ``highlights``."""
    highlights = set(highlights or set())
    finding_ops = {}        # op_index -> style category
    if highlight is not None:
        records = getattr(highlight, "findings", highlight)
        for f in records:
            d = f if isinstance(f, dict) else f.to_dict()
            if d.get("block_idx", 0) != block.idx:
                continue
            highlights |= set(d.get("var_names") or ())
            i = d.get("op_index", -1)
            if i is None or i < 0:
                continue
            cat = ("dead" if d.get("code") == "dead_op" else
                   "error" if d.get("severity") == "error" else "warn")
            # error beats warn beats dead when findings stack on one op
            rank = {"error": 0, "warn": 1, "dead": 2}
            if rank[cat] < rank.get(finding_ops.get(i), 9):
                finding_ops[i] = cat
    _OP_STYLE = {
        "dead": ' style="rounded,filled" fillcolor="grey80"',
        "warn": ' style="rounded,filled" fillcolor="orange"',
        "error": ' style="rounded,filled" fillcolor="red" '
                 'fontcolor="white"',
    }

    def is_grad(name: str) -> bool:
        return "@GRAD" in name

    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids: dict = {}        # name -> stable sequential id

    def var_node(name: str) -> str:
        if name not in var_ids:
            var_ids[name] = f"var_{len(var_ids)}"
            nid = var_ids[name]
            v = block.vars.get(name)
            shape = getattr(v, "shape", None) if v is not None else None
            # escape the name BEFORE appending the intentional \n line
            # break (escaping after would turn it into a literal
            # backslash-n)
            label = _dot_escape(name) + (
                f"\\n{list(shape)}" if shape is not None else "")
            style = []
            if isinstance(v, Parameter):
                style.append('style=filled fillcolor="lightgrey"')
            if name in highlights:
                style.append('color="red"')
            lines.append(f'  {nid} [label="{label}" shape=ellipse '
                         + " ".join(style) + "];")
        return var_ids[name]

    for i, op in enumerate(block.ops):
        names = [n for ns in list(op.inputs.values())
                 + list(op.outputs.values()) for n in ns]
        if not show_backward and (op.type.endswith("_grad")
                                  or any(is_grad(n) for n in names)):
            continue
        op_id = f"op_{i}"
        color = ' color="red"' if op.type in highlights else ""
        style = _OP_STYLE.get(finding_ops.get(i), " style=rounded")
        lines.append(f'  {op_id} [label="{_dot_escape(op.type)}" '
                     f'shape=box{style}{color}];')
        for ns in op.inputs.values():
            for n in ns:
                lines.append(f"  {var_node(n)} -> {op_id};")
        for ns in op.outputs.values():
            for n in ns:
                if n:
                    lines.append(f"  {op_id} -> {var_node(n)};")
    lines.append("}")
    dot = "\n".join(lines) + "\n"
    with open(path, "w") as f:
        f.write(dot)
    return path


def pprint_program_codes(program: Program,
                         show_backward: bool = False) -> str:
    """Human-readable pseudo-code of every block (ref debugger.py
    pprint_program_codes): one `out = op_type(in, ...) {attrs}` line per
    op."""
    reprs = []
    for block in program.blocks:
        lines = [f"// block {block.idx} (parent {block.parent_idx})"]
        for op in block.ops:
            names = [n for ns in list(op.inputs.values())
                     + list(op.outputs.values()) for n in ns]
            if not show_backward and (op.type.endswith("_grad")
                                      or any("@GRAD" in n
                                             for n in names)):
                continue
            outs = ", ".join(n for ns in op.outputs.values()
                             for n in ns if n) or "_"
            ins = ", ".join(f"{slot}={list(ns)}"
                            for slot, ns in op.inputs.items() if ns)
            attrs = {k: v for k, v in op.attrs.items()
                     if not k.startswith("_")}
            lines.append(f"{outs} = {op.type}({ins})"
                         + (f"  # {attrs}" if attrs else ""))
        reprs.append("\n".join(lines))
    return "\n\n".join(reprs)
