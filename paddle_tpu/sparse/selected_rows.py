"""SelectedRows: the sparse {row ids, row values} gradient carrier.

Capability parity with the reference's SelectedRows
(/root/reference/paddle/fluid/framework/selected_rows.h and the
merge_selected_rows / scale-ops family over it): the gradient of an
embedding lookup touches only the looked-up rows, so it travels as a
(rows, values) pair — never as a dense ``[vocab, dim]`` tensor.  In the
reference this representation flowed from ``lookup_table_grad`` through
the pserver ``send``/``recv`` ops; here it is the wire format of the
sparse-plane ``push_grads`` RPC (sparse/service.py) and the input of
the host-side table update (sparse/table.py).

The one semantic trap of the representation — and the reason
``merged()`` exists — is duplicate ids: a batch that looks up row 7
twice must contribute BOTH cotangents to row 7 (scatter-ADD), not let
the second overwrite the first.  ``merged()`` canonicalizes to unique,
sorted rows with summed values, which is also what keeps the push RPC
payload at "unique live rows" size.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["SelectedRows"]


class SelectedRows:
    """rows: [N] int64 global row ids; values: [N, dim] float32.

    ``height`` is the full table's row count (the dense shape this
    sparse view projects into) — kept for bounds checks and
    ``to_dense``, exactly the reference's ``SelectedRows::height_``."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows: Sequence[int], values, height: int):
        self.rows = np.asarray(rows, dtype=np.int64).reshape(-1)
        self.values = np.asarray(values, dtype=np.float32)
        if self.values.ndim == 1:
            self.values = self.values[:, None]
        if self.values.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"SelectedRows: {self.rows.shape[0]} rows but "
                f"{self.values.shape[0]} value rows")
        self.height = int(height)
        if self.rows.size and (self.rows.min() < 0
                               or self.rows.max() >= self.height):
            raise ValueError(
                f"SelectedRows: row ids outside [0, {self.height}): "
                f"min={self.rows.min()}, max={self.rows.max()}")

    @property
    def dim(self) -> int:
        return self.values.shape[1]

    def __len__(self) -> int:
        return int(self.rows.shape[0])

    def merged(self) -> "SelectedRows":
        """Canonical form: unique sorted rows, duplicate ids' values
        SUMMED (the scatter-add contract; ref merge_selected_rows_op).
        Idempotent; returns self when already canonical."""
        if self.rows.size == 0:
            return self
        uniq, inv = np.unique(self.rows, return_inverse=True)
        if uniq.shape[0] == self.rows.shape[0] \
                and np.array_equal(uniq, self.rows):
            return self
        out = np.zeros((uniq.shape[0], self.values.shape[1]),
                       dtype=np.float32)
        np.add.at(out, inv, self.values)
        return SelectedRows(uniq, out, self.height)

    def to_dense(self) -> np.ndarray:
        """Materialize the dense [height, dim] view (tests/debug only —
        production paths must never call this; the whole point of the
        representation is that they don't have to)."""
        out = np.zeros((self.height, self.values.shape[1]), np.float32)
        np.add.at(out, self.rows, self.values)
        return out

    @staticmethod
    def from_dense(grad: np.ndarray, rows=None) -> "SelectedRows":
        """Extract the nonzero (or explicitly named) rows of a dense
        gradient — the test-side bridge from a dense-reference run to
        the sparse wire format."""
        grad = np.asarray(grad, np.float32)
        if rows is None:
            rows = np.nonzero(np.abs(grad).sum(axis=1))[0]
        rows = np.asarray(rows, np.int64)
        return SelectedRows(rows, grad[rows], grad.shape[0])

    def to_wire(self) -> dict:
        """JSON-lines payload for the push_grads RPC."""
        return {"rows": self.rows.tolist(),
                "values": self.values.tolist(),
                "height": self.height}

    @staticmethod
    def from_wire(doc: dict) -> "SelectedRows":
        return SelectedRows(doc["rows"], np.asarray(doc["values"],
                                                    np.float32),
                            doc["height"])

    def __repr__(self):
        return (f"SelectedRows(n={len(self)}, dim={self.dim}, "
                f"height={self.height})")
