"""Streaming CTR worker — the sparse plane's supervised trainer body.

Run::

    python -m paddle_tpu.sparse.worker <endpoints> <rank> <out.json>

``endpoints`` is the task master's ``host:port[,host:port]`` failover
list; the parameter-shard service rides the same transport
(``serve_master(master, sparse=service)``) unless
``PTPU_SPARSE_SHARDS`` names separate per-shard endpoints
(';'-separated, shard-id order).  The job config is the
``PTPU_SPARSE_CFG`` env var (JSON, see :class:`CTRJobConfig`).

The worker is the whole ISSUE-13 story in one loop:

* registers + heartbeats under its rank (PR 5 membership — a
  supervisor-respawned incarnation rejoins under the same rank);
* leases criteo-shaped file shards from the task master and streams
  them through :class:`AsyncExecutor`'s multi-queue loop with a
  ``step_fn`` body — parsing overlaps compute, malformed lines raise
  named errors, the first failure stops the pool;
* per microbatch: **gather** (pull_rows for the batch's UNIQUE ids +
  the dense towers), **compute** (one jitted DeepFM grad step over the
  pulled rows — fixed shapes via id/sample padding, so the executable
  compiles once), **scatter** (push_grads SelectedRows; the shard
  applies adagrad/sgd row-wise).  A dense [vocab, dim] gradient never
  exists on either side, and every push's ``rows_applied`` is checked
  against the batch's unique live ids;
* passes the ``trainer.step`` chaos fault point per microbatch (where
  a ``PTPU_CHAOS_SPEC=trainer.step=exit:...`` schedule hard-kills it)
  and the sparse.pull/sparse.push fault points inside the RPC retry
  loops;
* a ``stale`` push (bounded-staleness window exceeded) re-pulls the
  table's rows to refresh the version window and re-pushes — counted,
  never silently dropped.

Exactly-once accounting: task completions are fenced-lease +
master-ledger exactly-once (a re-leased task's zombie ack fences);
gradient pushes are exactly-once per push_id under transport retries
(shard push ledger) and at-least-once across task RE-executions — a
worker killed mid-file re-runs that file's pushes under the new lease,
which plain async SGD absorbs (the parity test's tolerance covers it).

Exit code 0 = this rank saw the job through to ``complete``.
"""
from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

__all__ = ["CTRJobConfig", "table_specs", "init_host_params",
           "make_grad_fn", "CTRStepper", "evaluate_ctr",
           "reference_train", "auc_score"]


@dataclass
class CTRJobConfig:
    """Shared by every worker AND the reference/eval side — one JSON
    blob (PTPU_SPARSE_CFG) keeps the fleet and the single-process
    ground truth on identical shapes, seeds and learning rate."""

    num_field: int = 4
    vocab_size: int = 64
    embed_dim: int = 4
    fc_sizes: Tuple[int, ...] = (16,)
    learning_rate: float = 0.1
    batch_size: int = 16
    seed: int = 0
    table_optimizer: str = "sgd"    # "sgd" for reference parity
    int8_rows: bool = False

    def to_wire(self) -> dict:
        return {"num_field": self.num_field,
                "vocab_size": self.vocab_size,
                "embed_dim": self.embed_dim,
                "fc_sizes": list(self.fc_sizes),
                "learning_rate": self.learning_rate,
                "batch_size": self.batch_size, "seed": self.seed,
                "table_optimizer": self.table_optimizer,
                "int8_rows": self.int8_rows}

    @staticmethod
    def from_wire(doc: dict) -> "CTRJobConfig":
        doc = dict(doc)
        doc["fc_sizes"] = tuple(doc.get("fc_sizes", (16,)))
        return CTRJobConfig(**doc)


def _dense_names(cfg: CTRJobConfig) -> List[Tuple[str, int, int]]:
    """[(name, rows, dim)] of the dense tower, in init order."""
    sizes = [cfg.num_field * cfg.embed_dim] + list(cfg.fc_sizes) + [1]
    out = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        out.append((f"fc{i}_w", a, b))
        out.append((f"fc{i}_b", 1, b))
    return out


def table_specs(cfg: CTRJobConfig):
    """Every parameter as a shard-service table: the two big sparse
    tables plus the dense tower as tiny full-pull tables.  Seeds are
    derived per table so init_host_params reproduces them exactly."""
    from .table import TableConfig
    lr, opt = cfg.learning_rate, cfg.table_optimizer
    specs = [
        TableConfig("w1", cfg.vocab_size, 1, seed=cfg.seed,
                    init_std=0.0, learning_rate=lr, optimizer=opt,
                    int8_rows=cfg.int8_rows),
        TableConfig("emb", cfg.vocab_size, cfg.embed_dim,
                    seed=cfg.seed + 1, init_std=0.01,
                    learning_rate=lr, optimizer=opt,
                    int8_rows=cfg.int8_rows),
    ]
    for j, (name, rows, dim) in enumerate(_dense_names(cfg)):
        std = 0.0 if name.endswith("_b") else 1.0 / np.sqrt(rows)
        specs.append(TableConfig(name, rows, dim,
                                 seed=cfg.seed + 10 + j, init_std=std,
                                 learning_rate=lr, optimizer=opt))
    return specs


def init_host_params(cfg: CTRJobConfig) -> Dict[str, np.ndarray]:
    """The single-process reference's params — bit-identical to what
    the shard service initializes from the same specs."""
    from .table import EmbeddingShard
    out = {}
    for spec in table_specs(cfg):
        spec = type(spec)(**{**spec.to_wire(), "int8_rows": False})
        shard = EmbeddingShard(spec)
        arr = shard.dense()
        out[spec.name] = arr[0] if spec.name.endswith("_b") else arr
    return out


def _sharded_cfg(cfg: CTRJobConfig):
    from ..parallel.sharded_embedding import ShardedCTRConfig
    return ShardedCTRConfig(
        vocab_size=cfg.vocab_size, num_field=cfg.num_field,
        embed_dim=cfg.embed_dim, fc_sizes=tuple(cfg.fc_sizes),
        learning_rate=cfg.learning_rate)


def make_grad_fn(cfg: CTRJobConfig):
    """One jitted gather-side step: (padded unique rows, dense tower,
    inverse indices, vals, label, sample weights) -> (loss, row grads,
    dense grads).  Shapes are FIXED (ids padded to batch*num_field
    unique slots, samples padded to batch_size with weight 0), so the
    whole stream runs on a single executable."""
    import jax
    import jax.numpy as jnp

    from ..parallel.sharded_embedding import _ctr_forward
    scfg = _sharded_cfg(cfg)

    @jax.jit
    def f(w1_u, emb_u, dense, inv, vals, label, wgt):
        def loss_fn(w1_u, emb_u, dense):
            w1_rows = jnp.take(w1_u, inv, axis=0)      # [B, F, 1]
            emb_rows = jnp.take(emb_u, inv, axis=0)    # [B, F, K]
            logit = _ctr_forward(dense, w1_rows, emb_rows, vals, scfg)
            z = jnp.clip(logit, -30, 30)
            xent = jnp.maximum(z, 0) - z * label + jnp.log1p(
                jnp.exp(-jnp.abs(z)))
            return jnp.sum(xent * wgt) / jnp.maximum(jnp.sum(wgt), 1.0)

        loss, (g_w1, g_emb, g_dense) = jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2))(w1_u, emb_u, dense)
        return loss, g_w1, g_emb, g_dense
    return f


class CTRStepper:
    """The pull -> compute -> push body, shaped as an AsyncExecutor
    ``step_fn``.  One instance per worker process/thread (the sparse
    client is not thread-safe)."""

    def __init__(self, cfg: CTRJobConfig, client,
                 push_tag: str = "local"):
        self.cfg = cfg
        self.client = client
        self.push_tag = push_tag        # unique per lease/incarnation
        self.grad_fn = make_grad_fn(cfg)
        self.dense_shapes = _dense_names(cfg)
        self.steps = 0
        self.rows_applied = 0
        self.row_count_mismatches = 0
        self.stale_retries = 0
        self.max_staleness = 0

    def _pull_dense(self):
        dense, versions = {}, {}
        for name, rows, dim in self.dense_shapes:
            vals, vers = self.client.pull_rows(name, np.arange(rows))
            dense[name] = vals[0] if name.endswith("_b") else vals
            versions[name] = vers
        return dense, versions

    def _push(self, table, grad_sr, versions, push_id):
        """Push with bounded-staleness refresh: a 'stale' verdict
        re-pulls one row PER STALE SHARD (to learn each owner's
        current version) and re-pushes under the refreshed window."""
        from ..distributed.async_update import StalePushError
        from ..observability import flight as obs_flight
        versions = dict(versions)
        for attempt in range(16):
            out = self.client.push_grads(table, grad_sr, versions,
                                         push_id)
            self.max_staleness = max(self.max_staleness,
                                     out["staleness"])
            if not out["stale"]:
                return out
            self.stale_retries += 1
            obs_flight.record("sparse", "push_retry_stale",
                              table=table, attempt=attempt,
                              shards=out["stale"])
            # refresh the window for EXACTLY the stale shards: pull a
            # row each owns, and MERGE the fresh versions — replacing
            # the dict would zero the other shards' versions and make
            # every re-push maximally stale
            rows = grad_sr.merged().rows
            S = self.client.num_shards
            refresh = [int(rows[rows % S == s][0])
                       for s in out["stale"]
                       if (rows % S == s).any()]
            _, fresh = self.client.pull_rows(table,
                                             refresh or rows[:1])
            versions.update(fresh)
        raise StalePushError(
            f"sparse push to {table!r} stayed stale after refresh "
            f"retries — staleness bound too tight for this fleet")

    def __call__(self, feed: Dict[str, np.ndarray]) -> dict:
        from ..resilience import chaos
        from .selected_rows import SelectedRows
        cfg = self.cfg
        # the hard-death fault point: an armed exit schedule kills the
        # process HERE, mid-stream, lease held — the master requeues
        # the task, the supervisor respawns the rank
        chaos.trigger("trainer.step")
        ids = np.concatenate([feed[f"C{i}"]
                              for i in range(cfg.num_field)],
                             axis=1).astype("int64")        # [b, F]
        vals = feed["feat_vals"].astype("float32")
        label = feed["label"].astype("float32")
        b = ids.shape[0]
        B, F = cfg.batch_size, cfg.num_field
        if b < B:                       # pad the tail batch: one shape
            pad = B - b
            ids = np.pad(ids, ((0, pad), (0, 0)))
            vals = np.pad(vals, ((0, pad), (0, 0)))
            label = np.pad(label, ((0, pad), (0, 0)))
        wgt = np.zeros((B, 1), "float32")
        wgt[:b] = 1.0

        uniq, inv = np.unique(ids, return_inverse=True)
        n_unique = int(uniq.size)
        U = B * F                       # fixed unique-slot budget
        uniq_pad = np.zeros(U, "int64")
        uniq_pad[:n_unique] = uniq
        inv = inv.reshape(B, F).astype("int32")

        w1_u, v_w1 = self.client.pull_rows("w1", uniq_pad[:n_unique])
        emb_u, v_emb = self.client.pull_rows("emb",
                                             uniq_pad[:n_unique])
        w1_full = np.zeros((U, 1), "float32")
        w1_full[:n_unique] = w1_u
        emb_full = np.zeros((U, cfg.embed_dim), "float32")
        emb_full[:n_unique] = emb_u
        dense, v_dense = self._pull_dense()

        loss, g_w1, g_emb, g_dense = self.grad_fn(
            w1_full, emb_full, dense, inv, vals, label, wgt)
        loss = float(loss)

        tag = f"{self.push_tag}:{self.steps}"
        applied = 0
        applied += self._push(
            "w1", SelectedRows(uniq_pad[:n_unique],
                               np.asarray(g_w1)[:n_unique],
                               cfg.vocab_size),
            v_w1, f"{tag}:w1")["rows_applied"]
        applied += self._push(
            "emb", SelectedRows(uniq_pad[:n_unique],
                                np.asarray(g_emb)[:n_unique],
                                cfg.vocab_size),
            v_emb, f"{tag}:emb")["rows_applied"]
        # dense towers: full-row SelectedRows (these tables ARE the
        # batch's live rows)
        for name, rows, dim in self.dense_shapes:
            g = np.asarray(g_dense[name], "float32")
            g = g.reshape(rows, dim)
            self._push(name, SelectedRows(np.arange(rows), g, rows),
                       v_dense[name], f"{tag}:{name}")
        # the no-dense-materialization invariant: each sparse push must
        # apply exactly the batch's unique live ids
        if applied != 2 * n_unique:
            self.row_count_mismatches += 1
        self.rows_applied += applied
        self.steps += 1
        return {"loss": loss}


# -- eval / reference ------------------------------------------------------

def auc_score(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-sum (Mann-Whitney) AUC — no sklearn in the image."""
    labels = np.asarray(labels).ravel()
    scores = np.asarray(scores).ravel()
    pos = scores[labels > 0.5]
    neg = scores[labels <= 0.5]
    if pos.size == 0 or neg.size == 0:
        return 0.5
    order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
    ranks = np.empty(order.size)
    ranks[order] = np.arange(1, order.size + 1)
    # midranks for ties
    allv = np.concatenate([pos, neg])
    sortv = allv[order]
    i = 0
    while i < sortv.size:
        j = i
        while j + 1 < sortv.size and sortv[j + 1] == sortv[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j + 2) / 2.0
        i = j + 1
    r_pos = ranks[:pos.size].sum()
    return float((r_pos - pos.size * (pos.size + 1) / 2.0)
                 / (pos.size * neg.size))


def evaluate_ctr(params: Dict[str, np.ndarray], cfg: CTRJobConfig,
                 ids, vals, label) -> Tuple[float, float]:
    """(mean xent loss, AUC) of `params` on a dense dataset — shared by
    the async fleet's end state and the sync reference."""
    import jax.numpy as jnp

    from ..parallel.sharded_embedding import _ctr_forward
    scfg = _sharded_cfg(cfg)
    dense = {k: jnp.asarray(v) for k, v in params.items()
             if k not in ("w1", "emb")}
    w1_rows = jnp.take(jnp.asarray(params["w1"]), ids, axis=0)
    emb_rows = jnp.take(jnp.asarray(params["emb"]), ids, axis=0)
    logit = _ctr_forward(dense, w1_rows, emb_rows,
                         jnp.asarray(vals), scfg)
    z = np.clip(np.asarray(logit), -30, 30)
    lab = np.asarray(label)
    xent = np.maximum(z, 0) - z * lab + np.log1p(np.exp(-np.abs(z)))
    prob = 1.0 / (1.0 + np.exp(-z))
    return float(xent.mean()), auc_score(lab, prob)


def reference_train(cfg: CTRJobConfig, ids, vals, label,
                    epochs: int = 1) -> Dict[str, np.ndarray]:
    """The synchronous single-process ground truth: plain SGD
    reference_ctr_step over the dataset in file order, from the SAME
    seeded init the shard service uses."""
    from ..parallel.sharded_embedding import reference_ctr_step
    scfg = _sharded_cfg(cfg)
    params = init_host_params(cfg)
    B = cfg.batch_size
    for _ in range(max(1, epochs)):
        for s in range(0, ids.shape[0], B):
            bi, bv, bl = (ids[s:s + B], vals[s:s + B], label[s:s + B])
            params, _ = reference_ctr_step(params, scfg, bi, bv, bl)
    return {k: np.asarray(v) for k, v in params.items()}


# -- CLI -------------------------------------------------------------------

def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 3:
        print(__doc__, file=sys.stderr)
        return 2
    endpoints, rank, out_path = argv[0], int(argv[1]), argv[2]
    restart_count = int(os.environ.get("PTPU_WORKER_RESTART_COUNT",
                                       "0"))
    cfg = CTRJobConfig.from_wire(
        json.loads(os.environ.get("PTPU_SPARSE_CFG", "{}")))
    shard_eps = os.environ.get("PTPU_SPARSE_SHARDS", "")
    shard_eps = ([e for e in shard_eps.split(";") if e.strip()]
                 or endpoints)

    from ..distributed.async_update import SparseShardClient
    from ..distributed.task_queue import Heartbeater, TaskMasterClient
    from ..framework.async_executor import AsyncExecutor
    from ..models import deepfm as deepfm_model

    hb = Heartbeater(endpoints, rank).start()
    client = TaskMasterClient(endpoints=endpoints)
    sc = SparseShardClient(shard_eps)
    sc.init_tables(table_specs(cfg))

    feed_desc = deepfm_model.criteo_feed_desc(cfg.num_field,
                                              cfg.batch_size)
    exe = AsyncExecutor()
    completed, fenced_acks, failed_acks = [], 0, 0
    losses: List[float] = []
    # ONE stepper for the whole incarnation: its jitted grad step
    # compiles once; only the push tag changes per lease
    stepper = CTRStepper(cfg, sc, push_tag="idle")
    generations = set()
    try:
        while True:
            t = client.get_task(worker=rank)
            if client.master_generation is not None:
                generations.add(client.master_generation)
            if t is None:
                if client.job_complete:
                    break
                time.sleep(0.05)
                continue
            # a fresh lease means fresh push ids: a RE-executed task's
            # pushes must not collide with the dead incarnation's
            # ledger entries
            stepper.push_tag = f"r{rank}i{restart_count}-{t.lease}"
            try:
                out = exe.run(None, feed_desc, t.shards,
                              thread_num=1, fetch=["loss"],
                              step_fn=stepper)
                losses.append(out["loss"])
            except BaseException:
                try:
                    client.task_failed(t.task_id, lease=t.lease)
                except Exception:
                    pass        # lease timeout covers it
                raise
            status = client.task_finished(t.task_id, lease=t.lease,
                                          worker=rank)
            if status == "ok":
                completed.append([t.task_id, t.epoch])
            elif status == "fenced":
                fenced_acks += 1    # another worker owns it now
            else:
                failed_acks += 1
    finally:
        hb.stop(goodbye=True)
        client.close()
        sc.close()

    doc = {"rank": rank, "restart_count": restart_count,
           "completed": completed, "fenced_acks": fenced_acks,
           "failed_acks": failed_acks,
           "generations": sorted(generations),
           "mean_loss": (float(np.mean(losses)) if losses else None),
           "hb_re_registrations": hb.re_registrations,
           "steps": stepper.steps,
           "rows_applied": stepper.rows_applied,
           "row_count_mismatches": stepper.row_count_mismatches,
           "stale_retries": stepper.stale_retries,
           "max_staleness": stepper.max_staleness}
    with open(out_path, "w") as f:
        json.dump(doc, f)
    print(f"SPARSE_WORKER_OK rank={rank} "
          f"completed={len(completed)} fenced={fenced_acks} "
          f"restarts={restart_count}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
