"""Host-plane sparse embedding tables: hash buckets, row-wise adagrad,
optional int8 row storage.

Capability parity with the reference's pserver big-table stack:
  * go/pserver/optimizer.go + parameter server rows — the table and its
    optimizer state live server-side, updated from sparse gradients;
  * distribute_transpiler.py:1010 `_create_table_optimize_block` — the
    adagrad accumulator is split row-aligned WITH the table shard, so a
    sparse update touches the same rows of both;
  * the hash-bucket trick of the reference's CTR pipelines (ids far
    beyond any dense vocab are folded into a fixed bucket count before
    lookup).

TPU-native framing: the DEVICE fast path for in-HBM tables is
parallel/sharded_embedding.py (shard_map gather + scatter-add).  THIS
module is the host/pserver plane those workers pull from and push to —
numpy rows behind the sparse/service.py RPC verbs, where "table larger
than any one batch touches" means the working set is the pulled rows,
never the table.

int8 row storage rides the PR 6 quantize plane's convention
(ops/quantize_ops.py abs-max affine: scale = rowmax/127, symmetric):
each row stores int8 codes + one f32 scale; pulls dequantize, applies
requantize only the touched rows.  Adagrad accumulators stay f32 —
quantizing optimizer state compounds error quadratically.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from .selected_rows import SelectedRows

__all__ = ["TableConfig", "EmbeddingShard", "hash_bucket",
           "partition_rows", "HASH_MIX"]

# xor-shift/multiply avalanche constant (lowbias32 family).  A bare
# Knuth multiply is ≡ identity mod small powers of two (2654435761 is
# odd), so power-of-two bucket counts would never mix — the xor-shifts
# spread high bits into the low ones.  The SAME sequence is implemented
# by the device-side sparse_embedding_lookup op (ops/nn_ops.py) so host
# bucketing and in-graph bucketing agree on every id.
HASH_MIX = np.uint32(0x45D9F3B)


def hash_bucket(ids, num_buckets: int) -> np.ndarray:
    """Fold arbitrary (possibly > vocab) non-negative ids into
    [0, num_buckets) — the reference CTR pipelines' id folding,
    deterministic across host and device."""
    with np.errstate(over="ignore"):
        x = np.asarray(ids, np.uint64).astype(np.uint32)
        x ^= x >> np.uint32(16)
        x *= HASH_MIX
        x ^= x >> np.uint32(16)
        x *= HASH_MIX
        x ^= x >> np.uint32(16)
    return (x % np.uint32(num_buckets)).astype(np.int64)


def partition_rows(rows: np.ndarray, num_shards: int
                   ) -> Dict[int, np.ndarray]:
    """Mod-partition global row ids across shard owners: shard s owns
    rows where ``row % num_shards == s`` (the transpiler's round-robin
    split of the distributed lookup table).  Returns {shard: rows}."""
    rows = np.asarray(rows, np.int64)
    return {s: rows[rows % num_shards == s] for s in range(num_shards)
            if ((rows % num_shards) == s).any()}


@dataclass
class TableConfig:
    """One sparse table's spec — also the sparse_init RPC payload, so
    every worker and the shard service agree on shape/seed/optimizer
    without a side channel."""

    name: str
    rows: int
    dim: int
    seed: int = 0
    init_std: float = 0.01          # 0.0 = zero-init (bias-like tables)
    learning_rate: float = 0.1
    optimizer: str = "sgd"          # "sgd" | "adagrad"
    adagrad_eps: float = 1e-6
    int8_rows: bool = False

    def to_wire(self) -> dict:
        return {k: getattr(self, k) for k in
                ("name", "rows", "dim", "seed", "init_std",
                 "learning_rate", "optimizer", "adagrad_eps",
                 "int8_rows")}

    @staticmethod
    def from_wire(doc: dict) -> "TableConfig":
        return TableConfig(**doc)


def _init_dense(cfg: TableConfig) -> np.ndarray:
    """Seeded full-table init — shared by the shard service and the
    single-process reference run, so async-vs-sync parity tests start
    from identical weights."""
    if cfg.init_std == 0.0:
        return np.zeros((cfg.rows, cfg.dim), np.float32)
    rng = np.random.RandomState(cfg.seed)
    return (rng.randn(cfg.rows, cfg.dim) * cfg.init_std).astype(
        np.float32)


class EmbeddingShard:
    """The rows of one table owned by one shard service.

    ``shard_id``/``num_shards`` select the mod-partition this shard
    holds (global row r lives at local index r // num_shards on shard
    r % num_shards); the single-service case is shard 0 of 1 holding
    everything.  All mutation goes through :meth:`apply` with a
    SelectedRows gradient — there is no dense-update path at all.
    """

    def __init__(self, cfg: TableConfig, shard_id: int = 0,
                 num_shards: int = 1):
        if not (0 <= shard_id < num_shards):
            raise ValueError(f"shard {shard_id} of {num_shards}")
        self.cfg = cfg
        self.shard_id = int(shard_id)
        self.num_shards = int(num_shards)
        full = _init_dense(cfg)
        local = full[shard_id::num_shards]
        self.local_rows = local.shape[0]
        if cfg.int8_rows:
            self._codes, self._scales = _quantize_rows(local)
            self._table = None
        else:
            self._table = local.copy()
            self._codes = self._scales = None
        # adagrad accumulator, row-aligned with the shard (f32 always)
        self._accum = (np.zeros_like(local)
                       if cfg.optimizer == "adagrad" else None)
        self.version = 0            # bumps once per applied push
        self.rows_pulled = 0
        self.rows_pushed = 0
        # construction-time registration: the memscope census reports
        # state_bytes() as the host-side sparse_tables plane
        from ..observability import memscope as obs_memscope
        obs_memscope.register_sparse_shard(self)

    # -- local/global row mapping ------------------------------------------
    def _local(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows, np.int64)
        if rows.size:
            if (rows < 0).any() or (rows >= self.cfg.rows).any():
                raise ValueError(
                    f"table {self.cfg.name!r}: row ids outside "
                    f"[0, {self.cfg.rows})")
            if (rows % self.num_shards != self.shard_id).any():
                raise ValueError(
                    f"table {self.cfg.name!r}: rows not owned by shard "
                    f"{self.shard_id}/{self.num_shards}")
        return rows // self.num_shards

    # -- read --------------------------------------------------------------
    def pull(self, rows) -> np.ndarray:
        """[N] global row ids -> [N, dim] f32 rows (dequantized when
        the table stores int8)."""
        loc = self._local(rows)
        self.rows_pulled += int(loc.size)
        if self._table is not None:
            return self._table[loc].copy()
        return (self._codes[loc].astype(np.float32)
                * self._scales[loc][:, None])

    def dense(self) -> np.ndarray:
        """This shard's full [local_rows, dim] view — eval/tests only."""
        if self._table is not None:
            return self._table.copy()
        return self._codes.astype(np.float32) * self._scales[:, None]

    # -- sparse update -----------------------------------------------------
    def apply(self, grad: SelectedRows) -> int:
        """Scatter-apply one SelectedRows gradient: merge duplicates,
        update ONLY the touched rows (table + accumulator), bump the
        version.  Returns the number of distinct rows applied."""
        if grad.height != self.cfg.rows:
            raise ValueError(
                f"table {self.cfg.name!r}: grad height {grad.height} "
                f"!= table rows {self.cfg.rows}")
        g = grad.merged()
        loc = self._local(g.rows)
        gv = g.values
        lr = self.cfg.learning_rate
        rows_f32 = (self._table[loc] if self._table is not None
                    else self._codes[loc].astype(np.float32)
                    * self._scales[loc][:, None])
        if self._accum is not None:
            self._accum[loc] += gv * gv
            denom = np.sqrt(self._accum[loc]) + self.cfg.adagrad_eps
            rows_f32 = rows_f32 - lr * gv / denom
        else:
            rows_f32 = rows_f32 - lr * gv
        if self._table is not None:
            self._table[loc] = rows_f32
        else:
            codes, scales = _quantize_rows(rows_f32)
            self._codes[loc] = codes
            self._scales[loc] = scales
        self.version += 1
        self.rows_pushed += int(loc.size)
        return int(loc.size)

    # -- snapshot (ISSUE 14 satellite: service-restart persistence) --------
    def state_view(self) -> dict:
        """CHEAP copied view of this shard's mutable state (np memcpy
        — taken under the service lock; the O(table) JSON serialization
        happens OUTSIDE it, see SparseShardService._snapshot)."""
        v = {"cfg": self.cfg.to_wire(), "shard_id": self.shard_id,
             "num_shards": self.num_shards, "version": self.version,
             "rows_pulled": self.rows_pulled,
             "rows_pushed": self.rows_pushed}
        if self._table is not None:
            v["table"] = self._table.copy()
        else:
            v["codes"] = self._codes.copy()
            v["scales"] = self._scales.copy()
        if self._accum is not None:
            v["accum"] = self._accum.copy()
        return v

    def state_doc(self) -> dict:
        """JSON-able full state of this shard (values/codes, adagrad
        accumulator, version, counters) — the SparseShardService
        snapshots it alongside its push ledger so a restarted shard
        still dedupes re-delivered pushes against the SAME table
        state."""
        return {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                for k, v in self.state_view().items()}

    @classmethod
    def from_state(cls, doc: dict) -> "EmbeddingShard":
        t = cls(TableConfig.from_wire(doc["cfg"]),
                int(doc["shard_id"]), int(doc["num_shards"]))
        if "table" in doc:
            t._table = np.asarray(doc["table"], np.float32)
        else:
            t._codes = np.asarray(doc["codes"], np.int8)
            t._scales = np.asarray(doc["scales"], np.float32)
        if "accum" in doc:
            t._accum = np.asarray(doc["accum"], np.float32)
        t.version = int(doc["version"])
        t.rows_pulled = int(doc.get("rows_pulled", 0))
        t.rows_pushed = int(doc.get("rows_pushed", 0))
        return t

    def state_bytes(self) -> int:
        if self._table is not None:
            n = self._table.nbytes
        else:
            n = self._codes.nbytes + self._scales.nbytes
        if self._accum is not None:
            n += self._accum.nbytes
        return n


def _quantize_rows(rows_f32: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Row-wise symmetric int8: codes [N, D] int8 + scale [N] f32
    (abs-max / 127, the PR 6 quantize-plane convention)."""
    absmax = np.abs(rows_f32).max(axis=1)
    scales = np.maximum(absmax / 127.0, 1e-12).astype(np.float32)
    codes = np.clip(np.rint(rows_f32 / scales[:, None]),
                    -127, 127).astype(np.int8)
    return codes, scales
