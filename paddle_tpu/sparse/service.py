"""Parameter-shard service: the sparse plane's pserver, speaking the
task-queue JSON-lines transport.

Capability parity with the reference's sparse pserver
(/root/reference/paddle/fluid/operators/distributed_ops/
listen_and_serv_op.cc async loop + go/pserver/service.go): trainers
pull the rows a microbatch needs, push SelectedRows gradients, and the
shard applies them as they arrive — no barrier.  Three disciplines from
the PR 5 lease/ledger era carry over:

* **transport** — the verbs ride the SAME JSON-lines TCP server as the
  task master (``serve_master(master, sparse=service)``), so every
  reply carries the master generation, every request carries the
  caller's X-ray traceparent, and the client inherits
  ``TaskMasterClient``'s retry/re-dial loop for free;
* **push ledger** — pushes are at-least-once (the client retries on a
  lost reply): each push names a ``push_id`` and accepted ids land in a
  bounded ledger, so a duplicate delivery re-acks ``ok`` with the
  original row count instead of double-applying the gradient — the
  task-queue completion-ledger discipline applied to gradients;
* **bounded staleness** — each pull returns the table ``version``;
  each push presents the version it pulled.  A push whose staleness
  (current - pulled) exceeds the ``sparse_staleness_bound`` flag is
  rejected with status ``"stale"`` (the worker re-pulls and
  recomputes) — the async pserver loop with a fence against unbounded
  drift, published as the ``sparse_staleness_steps`` histogram.

Metrics: ``sparse_rows_pulled_total{table}``,
``sparse_rows_pushed_total{table}``, ``sparse_staleness_steps``,
``sparse_push_rejected_total{reason}``, ``sparse_table_version{table}``.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import metrics as obs_metrics
from .selected_rows import SelectedRows
from .table import EmbeddingShard, TableConfig

__all__ = ["SparseShardService"]

_m_rows_pulled = obs_metrics.counter(
    "sparse_rows_pulled_total",
    "Embedding rows served to workers by pull_rows, by table.",
    ("table",))
_m_rows_pushed = obs_metrics.counter(
    "sparse_rows_pushed_total",
    "Distinct embedding rows scatter-applied from push_grads "
    "SelectedRows gradients, by table (duplicate ids within a push "
    "merge first; rejected/duplicate pushes don't count).",
    ("table",))
_m_staleness = obs_metrics.histogram(
    "sparse_staleness_steps",
    "Staleness of each accepted async push in applied-push steps "
    "(table version at apply minus version at pull); 0 = fully "
    "synchronous behaviour.",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
_m_rejected = obs_metrics.counter(
    "sparse_push_rejected_total",
    "push_grads RPCs rejected by the shard, by reason (stale = over "
    "the sparse_staleness_bound window; the worker re-pulls).",
    ("reason",))
_m_version = obs_metrics.gauge(
    "sparse_table_version",
    "Applied-push version of each sparse table on this shard.",
    ("table",))


class SparseShardService:
    """One shard process's tables + the RPC verb handlers.

    Attach to a master's transport with
    ``serve_master(master, sparse=service)``; the handler routes
    ``sparse_init`` / ``pull_rows`` / ``push_grads`` / ``sparse_state``
    / ``sparse_stats`` here.  Thread-safe: the transport is
    thread-per-connection."""

    def __init__(self, shard_id: int = 0, num_shards: int = 1,
                 staleness_bound: Optional[int] = None,
                 ledger_size: Optional[int] = None):
        self.shard_id = int(shard_id)
        self.num_shards = int(num_shards)
        self._staleness_bound = staleness_bound
        self._ledger_size = int(
            ledger_size if ledger_size is not None
            else flags.get_flag("sparse_push_ledger_size"))
        self._lock = threading.Lock()
        self.tables: Dict[str, EmbeddingShard] = {}
        # push_id -> rows_applied: the exactly-once record (bounded,
        # oldest-first eviction)
        self._push_ledger: "OrderedDict[str, int]" = OrderedDict()
        self.stale_rejections = 0

    @property
    def staleness_bound(self) -> int:
        if self._staleness_bound is not None:
            return int(self._staleness_bound)
        return int(flags.get_flag("sparse_staleness_bound"))

    # -- table lifecycle ---------------------------------------------------
    def init_tables(self, specs: List[TableConfig]) -> dict:
        """Create tables (idempotent: an existing table with the same
        spec re-acks; a conflicting spec is an error — two workers
        racing sparse_init must agree)."""
        with self._lock:
            for cfg in specs:
                cur = self.tables.get(cfg.name)
                if cur is not None:
                    if cur.cfg.to_wire() != cfg.to_wire():
                        raise ValueError(
                            f"sparse_init: table {cfg.name!r} already "
                            f"exists with a different spec")
                    continue
                self.tables[cfg.name] = EmbeddingShard(
                    cfg, self.shard_id, self.num_shards)
                _m_version.labels(table=cfg.name).set(0)
            return {"tables": sorted(self.tables)}

    def _table(self, name: str) -> EmbeddingShard:
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"unknown sparse table {name!r} (did "
                           f"sparse_init run?)")
        return t

    # -- verbs -------------------------------------------------------------
    def pull_rows(self, table: str, rows: List[int]) -> dict:
        with self._lock:
            t = self._table(table)
            values = t.pull(np.asarray(rows, np.int64))
            _m_rows_pulled.labels(table=table).inc(len(rows))
            return {"values": values.tolist(), "version": t.version}

    def push_grads(self, table: str, grad: SelectedRows,
                   pull_version: int, push_id: str) -> dict:
        """Apply one SelectedRows gradient.  Status:
        ``ok`` (applied, or duplicate re-ack with the recorded count) |
        ``stale`` (over the staleness window; nothing applied)."""
        with self._lock:
            t = self._table(table)
            if push_id in self._push_ledger:
                # at-least-once delivery: the first copy applied and
                # its reply was lost — re-ack, never re-apply
                return {"status": "ok", "duplicate": True,
                        "rows_applied": self._push_ledger[push_id],
                        "version": t.version}
            staleness = t.version - int(pull_version)
            if staleness > self.staleness_bound:
                self.stale_rejections += 1
                _m_rejected.labels(reason="stale").inc()
                obs_flight.record("sparse", "push_stale", table=table,
                                  staleness=staleness,
                                  bound=self.staleness_bound)
                return {"status": "stale", "staleness": staleness,
                        "version": t.version, "rows_applied": 0}
            n = t.apply(grad)
            _m_rows_pushed.labels(table=table).inc(n)
            _m_staleness.observe(max(0, staleness))
            _m_version.labels(table=table).set(t.version)
            self._push_ledger[push_id] = n
            while len(self._push_ledger) > self._ledger_size:
                self._push_ledger.popitem(last=False)
            return {"status": "ok", "rows_applied": n,
                    "staleness": staleness, "version": t.version}

    def state(self, table: str) -> dict:
        """Full local shard (eval/checkpoint path, NOT the training hot
        path — workers pull rows, never tables)."""
        with self._lock:
            t = self._table(table)
            return {"values": t.dense().tolist(), "version": t.version,
                    "shard_id": t.shard_id, "num_shards": t.num_shards,
                    "rows": t.cfg.rows, "dim": t.cfg.dim}

    def stats(self) -> dict:
        with self._lock:
            return {
                "shard_id": self.shard_id,
                "num_shards": self.num_shards,
                "staleness_bound": self.staleness_bound,
                "stale_rejections": self.stale_rejections,
                "ledger": len(self._push_ledger),
                "tables": {
                    name: {"version": t.version,
                           "local_rows": t.local_rows,
                           "rows_pulled": t.rows_pulled,
                           "rows_pushed": t.rows_pushed,
                           "int8": bool(t.cfg.int8_rows),
                           "bytes": t.state_bytes()}
                    for name, t in sorted(self.tables.items())}}

    # -- transport adapter (called by task_queue._Handler) -----------------
    VERBS = ("sparse_init", "pull_rows", "push_grads", "sparse_state",
             "sparse_stats")

    def handle(self, method: str, req: dict) -> dict:
        if method == "sparse_init":
            out = self.init_tables([TableConfig.from_wire(d)
                                    for d in req["tables"]])
            return {"ok": True, **out}
        if method == "pull_rows":
            return {"ok": True,
                    **self.pull_rows(req["table"], req["rows"])}
        if method == "push_grads":
            out = self.push_grads(
                req["table"], SelectedRows.from_wire(req["grad"]),
                req.get("pull_version", 0), req["push_id"])
            return {"ok": out["status"] == "ok", **out}
        if method == "sparse_state":
            return {"ok": True, **self.state(req["table"])}
        if method == "sparse_stats":
            return {"ok": True, "stats": self.stats()}
        return {"ok": False, "error": f"bad sparse method {method}"}
