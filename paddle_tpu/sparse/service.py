"""Parameter-shard service: the sparse plane's pserver, speaking the
task-queue JSON-lines transport.

Capability parity with the reference's sparse pserver
(/root/reference/paddle/fluid/operators/distributed_ops/
listen_and_serv_op.cc async loop + go/pserver/service.go): trainers
pull the rows a microbatch needs, push SelectedRows gradients, and the
shard applies them as they arrive — no barrier.  Three disciplines from
the PR 5 lease/ledger era carry over:

* **transport** — the verbs ride the SAME JSON-lines TCP server as the
  task master (``serve_master(master, sparse=service)``), so every
  reply carries the master generation, every request carries the
  caller's X-ray traceparent, and the client inherits
  ``TaskMasterClient``'s retry/re-dial loop for free;
* **push ledger** — pushes are at-least-once (the client retries on a
  lost reply): each push names a ``push_id`` and accepted ids land in a
  bounded ledger, so a duplicate delivery re-acks ``ok`` with the
  original row count instead of double-applying the gradient — the
  task-queue completion-ledger discipline applied to gradients;
* **bounded staleness** — each pull returns the table ``version``;
  each push presents the version it pulled.  A push whose staleness
  (current - pulled) exceeds the ``sparse_staleness_bound`` flag is
  rejected with status ``"stale"`` (the worker re-pulls and
  recomputes) — the async pserver loop with a fence against unbounded
  drift, published as the ``sparse_staleness_steps`` histogram.

Metrics: ``sparse_rows_pulled_total{table}``,
``sparse_rows_pushed_total{table}``, ``sparse_staleness_steps``,
``sparse_push_rejected_total{reason}``, ``sparse_table_version{table}``,
``sparse_snapshot_corrupt_total``.

Restart persistence (ISSUE 14 satellite, the PR 13 follow-up): give
the service a ``snapshot_path`` and every applied push is durable
BEFORE its reply (process-crash scope, the repo-wide discipline — see
``_wal_append``) — at O(push), not O(table): the push's merged
SelectedRows gradient appends to a CRC-per-line write-ahead log
(``<snapshot_path>.wal``), while full table snapshots (tables + push
ledger, CRC-framed, atomic-rename — the task-master discipline) are
throttled by ``snapshot_interval`` and truncate the WAL they subsume.
Recovery loads the snapshot then re-applies the WAL's gradients (pure
deterministic numpy — bit-identical to the pre-crash state), so a push
re-delivered across the restart still dedupes against the ledger
instead of double-applying; a corrupt snapshot falls back to a FRESH
state with a loud warning, and a torn WAL tail stops replay at the
tear — never a bricked restart.
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import metrics as obs_metrics
from .selected_rows import SelectedRows
from .table import EmbeddingShard, TableConfig

__all__ = ["SparseShardService"]

_m_rows_pulled = obs_metrics.counter(
    "sparse_rows_pulled_total",
    "Embedding rows served to workers by pull_rows, by table.",
    ("table",))
_m_rows_pushed = obs_metrics.counter(
    "sparse_rows_pushed_total",
    "Distinct embedding rows scatter-applied from push_grads "
    "SelectedRows gradients, by table (duplicate ids within a push "
    "merge first; rejected/duplicate pushes don't count).",
    ("table",))
_m_staleness = obs_metrics.histogram(
    "sparse_staleness_steps",
    "Staleness of each accepted async push in applied-push steps "
    "(table version at apply minus version at pull); 0 = fully "
    "synchronous behaviour.",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
_m_rejected = obs_metrics.counter(
    "sparse_push_rejected_total",
    "push_grads RPCs rejected by the shard, by reason (stale = over "
    "the sparse_staleness_bound window; the worker re-pulls).",
    ("reason",))
_m_version = obs_metrics.gauge(
    "sparse_table_version",
    "Applied-push version of each sparse table on this shard.",
    ("table",))
_m_snapshot_corrupt = obs_metrics.counter(
    "sparse_snapshot_corrupt_total",
    "Sparse shard snapshots that failed CRC/parse at recovery; the "
    "service fell back to a fresh state instead of bricking the "
    "restart.")


class SparseShardService:
    """One shard process's tables + the RPC verb handlers.

    Attach to a master's transport with
    ``serve_master(master, sparse=service)``; the handler routes
    ``sparse_init`` / ``pull_rows`` / ``push_grads`` / ``sparse_state``
    / ``sparse_stats`` here.  Thread-safe: the transport is
    thread-per-connection."""

    def __init__(self, shard_id: int = 0, num_shards: int = 1,
                 staleness_bound: Optional[int] = None,
                 ledger_size: Optional[int] = None,
                 snapshot_path: Optional[str] = None,
                 snapshot_interval: float = 5.0):
        self.shard_id = int(shard_id)
        self.num_shards = int(num_shards)
        self._staleness_bound = staleness_bound
        self._ledger_size = int(
            ledger_size if ledger_size is not None
            else flags.get_flag("sparse_push_ledger_size"))
        self._lock = threading.Lock()
        self.tables: Dict[str, EmbeddingShard] = {}
        # push_id -> rows_applied: the exactly-once record (bounded,
        # oldest-first eviction)
        self._push_ledger: "OrderedDict[str, int]" = OrderedDict()
        self.stale_rejections = 0
        # restart persistence: every applied push is durable before
        # its reply via an O(push) WAL append; FULL table snapshots
        # are throttled by snapshot_interval (0 = full snapshot every
        # push — test/debug only, it serializes whole tables) and
        # truncate the WAL they subsume
        self.snapshot_path = snapshot_path
        self.snapshot_interval = float(snapshot_interval)
        self._last_snapshot = 0.0
        self._wal_f = None
        self._snap_pending = False
        if snapshot_path and os.path.exists(snapshot_path):
            if self._recover():
                self._replay_wal()
            else:
                # corrupt snapshot: do NOT replay the WAL onto the
                # fresh state — with no tables the gradients can't
                # apply, and inserting their push_ids into the ledger
                # would dedupe the re-delivered pushes whose updates
                # were never applied (silent loss).  Set the stale WAL
                # aside so the fresh incarnation's version timeline
                # starts clean and those pushes re-apply on
                # re-delivery, as the corrupt-snapshot warning promises
                for suffix in ("", ".old"):
                    p = self._wal_path() + suffix
                    try:
                        if os.path.exists(p):
                            os.replace(p, p + ".corrupt")
                    except OSError:
                        pass

    @property
    def staleness_bound(self) -> int:
        if self._staleness_bound is not None:
            return int(self._staleness_bound)
        return int(flags.get_flag("sparse_staleness_bound"))

    # -- restart persistence ----------------------------------------------
    def _wal_path(self) -> str:
        return self.snapshot_path + ".wal"

    def _snapshot(self, force: bool = False):
        """FULL tables + push ledger persistence (call under the
        lock).  Under the lock only the CHEAP part happens: np-copied
        table views (memcpy) and a WAL rotation; the O(table) JSON
        serialization + write run OUTSIDE the lock — on a background
        thread unless ``force`` — so the push/pull path never stalls
        behind a snapshot (review finding).  Single-flight: while one
        snapshot is still writing, due snapshots are skipped (the WAL
        keeps every push durable meanwhile) — except ``force``, which
        WAITS the in-flight write out: forced snapshots (init_tables'
        table creation has no WAL record) must never be dropped."""
        if not self.snapshot_path:
            return
        if self._snap_pending:
            if not force:
                return
            deadline = time.time() + 30.0
            while self._snap_pending and time.time() < deadline:
                time.sleep(0.005)
        now = time.time()
        if not force and self.snapshot_interval > 0 \
                and now - self._last_snapshot < self.snapshot_interval:
            return
        self._last_snapshot = now
        self._snap_pending = True
        view = {"shard_id": self.shard_id,
                "num_shards": self.num_shards,
                "stale_rejections": self.stale_rejections,
                "ledger": list(self._push_ledger.items()),
                "tables": {name: t.state_view()
                           for name, t in self.tables.items()}}
        # rotate the WAL: everything appended so far is subsumed by
        # this view; new pushes land in a fresh file.  Single-flight
        # guarantees `.old` is gone (removed by the previous write)
        # before the next rotation.
        if self._wal_f is not None:
            self._wal_f.close()
            self._wal_f = None
        wal = self._wal_path()
        try:
            if os.path.exists(wal):
                os.replace(wal, wal + ".old")
        except OSError:
            pass
        if force:
            self._write_snapshot(view)
        else:
            threading.Thread(target=self._write_snapshot, args=(view,),
                             daemon=True,
                             name="sparse-snapshot").start()

    def _write_snapshot(self, view: dict):
        """Serialize + atomically commit one snapshot view, then drop
        the rotated WAL it subsumes.  Runs OUTSIDE the service lock.
        The task-master discipline: serialized once, CRC'd as bytes,
        unique-temp + atomic rename."""
        try:
            tables = {name: {k: (v.tolist()
                                 if isinstance(v, np.ndarray) else v)
                             for k, v in tview.items()}
                      for name, tview in view["tables"].items()}
            payload = json.dumps({**view, "tables": tables})
            doc = {"v": 1, "crc": zlib.crc32(payload.encode()),
                   "state": payload}
            tmp = f"{self.snapshot_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.snapshot_path)
            # the committed snapshot holds everything the rotated WAL
            # recorded; a crash BEFORE this remove replays `.old`
            # entries the snapshot already has — the ledger/version
            # guards in _replay_wal skip them
            try:
                os.remove(self._wal_path() + ".old")
            except OSError:
                pass
        finally:
            self._snap_pending = False

    def _wal_append(self, entry: dict):
        """One applied push → one CRC-framed JSON line, flushed before
        the RPC reply: O(push size), the durable-before-reply lever.
        Durability scope is PROCESS crash (the repo-wide discipline —
        the task master's snapshot is likewise fsync-free): flush()
        hands the line to the OS, an OS/power crash can still lose the
        tail — add os.fsync here if that scope ever tightens."""
        if not self.snapshot_path:
            return
        payload = json.dumps(entry)
        if self._wal_f is None:
            self._wal_f = open(self._wal_path(), "a")
        self._wal_f.write(json.dumps(
            {"crc": zlib.crc32(payload.encode()), "e": payload}) + "\n")
        self._wal_f.flush()

    def _replay_wal(self):
        """Re-apply WAL gradients on top of the recovered snapshot
        (pure deterministic numpy — bit-identical to the pre-crash
        state).  The rotated ``.old`` file (a snapshot commit that
        never finished) replays first, then the live WAL; entries the
        snapshot already holds skip via the ledger/version guards, and
        a torn tail (crash mid-append) stops that file's replay at the
        tear with a warning."""
        replayed = 0
        for path in (self._wal_path() + ".old", self._wal_path()):
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for ln, line in enumerate(f):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                        payload = doc["e"]
                        if zlib.crc32(payload.encode()) != doc["crc"]:
                            raise ValueError("WAL line CRC mismatch")
                        e = json.loads(payload)
                        push_id, table = e["push_id"], e["table"]
                    except (ValueError, KeyError, TypeError) as exc:
                        warnings.warn(
                            f"sparse shard WAL {path!r} torn at line "
                            f"{ln} ({exc}); replay stops here — "
                            f"earlier entries applied, the torn push "
                            f"re-applies on re-delivery",
                            RuntimeWarning, stacklevel=3)
                        break
                    if push_id in self._push_ledger:
                        continue         # snapshot already holds it
                    t = self.tables.get(table)
                    if t is None:
                        # no table to apply to (shouldn't happen when
                        # recovery succeeded — tables snapshot at
                        # init): do NOT ledger it, or the re-delivery
                        # would dedupe an update that never applied
                        continue
                    if e["version_after"] > t.version:
                        t.apply(SelectedRows.from_wire(e["grad"]))
                        replayed += 1
                    # ledger lands whenever the effect is present
                    # (just applied, or already in the snapshot)
                    self._push_ledger[push_id] = int(e["rows_applied"])
                    while len(self._push_ledger) > self._ledger_size:
                        self._push_ledger.popitem(last=False)
        if replayed:
            for name, t in self.tables.items():
                _m_version.labels(table=name).set(t.version)
            obs_flight.record("sparse", "wal_replayed",
                              entries=replayed)

    def _recover(self) -> bool:
        """Restore tables + push ledger from the snapshot; a corrupt
        file (torn write, bit flip) falls back to a FRESH service with
        a loud warning (returns False — the caller must then skip WAL
        replay) — recovery failing at exactly the moment it matters is
        the one unacceptable outcome (the task-master corrupt-snapshot
        idiom)."""
        try:
            with open(self.snapshot_path) as f:
                doc = json.load(f)
            payload = doc["state"]
            if zlib.crc32(payload.encode()) != doc["crc"]:
                raise ValueError("snapshot CRC mismatch (torn or "
                                 "bit-flipped write)")
            state = json.loads(payload)
            tables = {name: EmbeddingShard.from_state(d)
                      for name, d in state["tables"].items()}
        except (OSError, ValueError, KeyError, TypeError) as e:
            _m_snapshot_corrupt.inc()
            obs_flight.record("sparse", "snapshot_corrupt",
                              error=repr(e)[:200])
            warnings.warn(
                f"sparse shard snapshot {self.snapshot_path!r} is "
                f"corrupt ({e}); recovering with a FRESH state — "
                f"tables must be re-initialised and pushes this "
                f"snapshot recorded will re-apply", RuntimeWarning,
                stacklevel=3)
            return False
        self.tables = tables
        self._push_ledger = OrderedDict(
            (str(k), int(v)) for k, v in state.get("ledger", []))
        self.stale_rejections = int(state.get("stale_rejections", 0))
        for name, t in self.tables.items():
            _m_version.labels(table=name).set(t.version)
        return True

    # -- table lifecycle ---------------------------------------------------
    def init_tables(self, specs: List[TableConfig]) -> dict:
        """Create tables (idempotent: an existing table with the same
        spec re-acks; a conflicting spec is an error — two workers
        racing sparse_init must agree)."""
        with self._lock:
            for cfg in specs:
                cur = self.tables.get(cfg.name)
                if cur is not None:
                    if cur.cfg.to_wire() != cfg.to_wire():
                        raise ValueError(
                            f"sparse_init: table {cfg.name!r} already "
                            f"exists with a different spec")
                    continue
                self.tables[cfg.name] = EmbeddingShard(
                    cfg, self.shard_id, self.num_shards)
                _m_version.labels(table=cfg.name).set(0)
            self._snapshot(force=True)
            return {"tables": sorted(self.tables)}

    def _table(self, name: str) -> EmbeddingShard:
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"unknown sparse table {name!r} (did "
                           f"sparse_init run?)")
        return t

    # -- verbs -------------------------------------------------------------
    def pull_rows(self, table: str, rows: List[int]) -> dict:
        with self._lock:
            t = self._table(table)
            values = t.pull(np.asarray(rows, np.int64))
            _m_rows_pulled.labels(table=table).inc(len(rows))
            return {"values": values.tolist(), "version": t.version}

    def push_grads(self, table: str, grad: SelectedRows,
                   pull_version: int, push_id: str) -> dict:
        """Apply one SelectedRows gradient.  Status:
        ``ok`` (applied, or duplicate re-ack with the recorded count) |
        ``stale`` (over the staleness window; nothing applied)."""
        with self._lock:
            t = self._table(table)
            if push_id in self._push_ledger:
                # at-least-once delivery: the first copy applied and
                # its reply was lost — re-ack, never re-apply
                return {"status": "ok", "duplicate": True,
                        "rows_applied": self._push_ledger[push_id],
                        "version": t.version}
            staleness = t.version - int(pull_version)
            if staleness > self.staleness_bound:
                self.stale_rejections += 1
                _m_rejected.labels(reason="stale").inc()
                obs_flight.record("sparse", "push_stale", table=table,
                                  staleness=staleness,
                                  bound=self.staleness_bound)
                return {"status": "stale", "staleness": staleness,
                        "version": t.version, "rows_applied": 0}
            n = t.apply(grad)
            _m_rows_pushed.labels(table=table).inc(n)
            _m_staleness.observe(max(0, staleness))
            _m_version.labels(table=table).set(t.version)
            self._push_ledger[push_id] = n
            while len(self._push_ledger) > self._ledger_size:
                self._push_ledger.popitem(last=False)
            # durable BEFORE the reply: the exactly-once-across-restart
            # guarantee needs this push on disk by the time the worker
            # sees "ok" — an O(push) WAL append, with the O(table)
            # full snapshot throttled behind it
            self._wal_append({"push_id": push_id, "table": table,
                              "grad": grad.to_wire(),
                              "rows_applied": n,
                              "version_after": t.version})
            self._snapshot()
            return {"status": "ok", "rows_applied": n,
                    "staleness": staleness, "version": t.version}

    def state(self, table: str) -> dict:
        """Full local shard (eval/checkpoint path, NOT the training hot
        path — workers pull rows, never tables)."""
        with self._lock:
            t = self._table(table)
            return {"values": t.dense().tolist(), "version": t.version,
                    "shard_id": t.shard_id, "num_shards": t.num_shards,
                    "rows": t.cfg.rows, "dim": t.cfg.dim}

    def stats(self) -> dict:
        with self._lock:
            return {
                "shard_id": self.shard_id,
                "num_shards": self.num_shards,
                "staleness_bound": self.staleness_bound,
                "stale_rejections": self.stale_rejections,
                "ledger": len(self._push_ledger),
                "tables": {
                    name: {"version": t.version,
                           "local_rows": t.local_rows,
                           "rows_pulled": t.rows_pulled,
                           "rows_pushed": t.rows_pushed,
                           "int8": bool(t.cfg.int8_rows),
                           "bytes": t.state_bytes()}
                    for name, t in sorted(self.tables.items())}}

    # -- transport adapter (called by task_queue._Handler) -----------------
    VERBS = ("sparse_init", "pull_rows", "push_grads", "sparse_state",
             "sparse_stats")

    def handle(self, method: str, req: dict) -> dict:
        if method == "sparse_init":
            out = self.init_tables([TableConfig.from_wire(d)
                                    for d in req["tables"]])
            return {"ok": True, **out}
        if method == "pull_rows":
            return {"ok": True,
                    **self.pull_rows(req["table"], req["rows"])}
        if method == "push_grads":
            out = self.push_grads(
                req["table"], SelectedRows.from_wire(req["grad"]),
                req.get("pull_version", 0), req["push_id"])
            return {"ok": out["status"] == "ok", **out}
        if method == "sparse_state":
            return {"ok": True, **self.state(req["table"])}
        if method == "sparse_stats":
            return {"ok": True, "stats": self.stats()}
        return {"ok": False, "error": f"bad sparse method {method}"}
