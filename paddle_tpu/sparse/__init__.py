"""Sparse plane: streaming CTR training with sharded embeddings.

The fifth plane of the stack (after observability, resilience,
low-precision perf, serving, and static analysis): the reference's
signature industrial workload — AsyncExecutor CTR trainers feeding
hash-bucketed sparse embedding pservers (PAPER.md §1, layers L4/L5) —
as one production-shaped story:

  * :mod:`selected_rows` — the {rows, values} sparse-gradient carrier
    (ref framework/selected_rows.h); duplicate ids merge by ADDITION.
  * :mod:`table` — hash-bucketed host tables with row-wise adagrad
    state and optional int8 row storage (PR 6 quantize convention).
  * :mod:`service` — the parameter-shard service: pull_rows/push_grads
    verbs on the task-queue JSON-lines transport with a push ledger
    (exactly-once under at-least-once delivery) and bounded-staleness
    accounting.
  * :class:`SparseShardClient` (distributed/async_update.py) — the
    worker-side client: every RPC rides TaskMasterClient._call
    (resilience/retry.py backoff, traceparent propagation) plus the
    sparse.pull / sparse.push chaos fault points.
  * :mod:`worker` — the streaming CTR worker CLI: lease file shards
    from the task master, stream criteo-shaped MultiSlot batches,
    gather-compute-scatter against the shard service.  Dense
    gradients never materialize.

The DEVICE twin (in-HBM tables inside one shard_map) stays in
parallel/sharded_embedding.py; docs/SPARSE.md maps both to the
reference stack.
"""
from ..distributed.async_update import SparseShardClient, StalePushError
from .selected_rows import SelectedRows
from .service import SparseShardService
from .table import (EmbeddingShard, TableConfig, hash_bucket,
                    partition_rows)

__all__ = ["SelectedRows", "SparseShardService", "SparseShardClient",
           "StalePushError", "EmbeddingShard", "TableConfig",
           "hash_bucket", "partition_rows"]
