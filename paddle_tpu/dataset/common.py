"""Dataset cache/shard helpers (ref python/paddle/dataset/common.py).

The reference downloads public datasets into ~/.cache/paddle/dataset
(common.py `download`).  This environment has no network egress, so every
dataset module accepts a local cache if present and otherwise falls back to
a *deterministic synthetic* generator with the same sample schema —
documented per module.  The split/sharding helpers are exact capability
ports.
"""
from __future__ import annotations

import glob
import hashlib
import os
import pickle
from typing import Callable, List

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def must_mkdirs(path: str):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def split(reader: Callable, line_count: int, suffix: str = "%05d.pickle",
          dumper=pickle.dump):
    """Split a reader's samples into chunked files (ref common.py split)."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= (indx_f + 1) * line_count - 1:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader=pickle.load):
    """Read this trainer's shard of chunked files (ref common.py
    cluster_files_reader) — the file-level sharding used for multi-host
    input (each host reads files where index % trainer_count == trainer_id)."""
    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, fn in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    lines = loader(f)
                    yield from lines
    return reader
