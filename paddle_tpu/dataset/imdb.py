"""IMDB sentiment (ref python/paddle/dataset/imdb.py).

Sample schema: (token ids list[int], label 0/1). word_dict() -> vocab map.
Synthetic fallback: two token distributions (positive/negative skew),
deterministic — models can fit it, keeping the LSTM/text-class benchmark
(BASELINE.md "LSTM text-class") runnable offline.
"""
from __future__ import annotations

import numpy as np

VOCAB = 5000
TRAIN_N, TEST_N = 2048, 256


def word_dict():
    return {f"w{i}": i for i in range(VOCAB)}


def _creator(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(20, 120))
            # positive reviews skew to low ids, negative to high ids
            if label:
                ids = rng.zipf(1.3, length) % (VOCAB // 2)
            else:
                ids = VOCAB // 2 + (rng.zipf(1.3, length) % (VOCAB // 2))
            yield list(np.clip(ids, 0, VOCAB - 1).astype(int)), label
    return reader


def train(word_idx=None):
    return _creator(TRAIN_N, seed=0)


def test(word_idx=None):
    return _creator(TEST_N, seed=1)
