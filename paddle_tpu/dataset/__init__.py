"""Datasets (ref python/paddle/dataset/): local-cache parse when files are
present, deterministic synthetic fallback otherwise (no network egress).
Schemas match the reference's readers sample-for-sample."""
from . import cifar, common, imdb, imikolov, mnist, uci_housing
