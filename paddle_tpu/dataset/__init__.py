"""Datasets (ref python/paddle/dataset/): local-cache parse when files are
present, deterministic synthetic fallback otherwise (no network egress).
Schemas match the reference's readers sample-for-sample."""
from . import (cifar, common, conll05, flowers, imdb, imikolov, mnist,
               movielens, mq2007, sentiment, uci_housing, voc2012, wmt14,
               wmt16)
