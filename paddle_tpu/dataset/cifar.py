"""CIFAR-10/100 (ref python/paddle/dataset/cifar.py).

Sample schema: (image float32[3072] in [0,1], label int).
Synthetic fallback: class-colored noise images, deterministic.
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from .common import DATA_HOME

TRAIN_N, TEST_N = 4096, 512


def _synthetic(n, num_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n)
    imgs = rng.rand(n, 3, 32, 32).astype("float32") * 0.4
    for c in range(num_classes):
        idx = labels == c
        imgs[idx, c % 3] += 0.4 + 0.2 * ((c // 3) % 2)
    return np.clip(imgs, 0, 1).reshape(n, 3072), labels


def _tar_reader(path, sub_name):
    with tarfile.open(path, mode="r") as f:
        names = [n for n in f.getnames() if sub_name in n]
        for name in names:
            batch = pickle.load(f.extractfile(name), encoding="latin1")
            for s, l in zip(batch["data"],
                            batch.get("labels", batch.get("fine_labels"))):
                yield s.astype("float32") / 255.0, int(l)


def _creator(kind, num_classes, n, seed):
    fname = "cifar-10-python.tar.gz" if num_classes == 10 else \
        "cifar-100-python.tar.gz"
    path = os.path.join(DATA_HOME, "cifar", fname)
    sub = ("data_batch" if kind == "train" else "test_batch") \
        if num_classes == 10 else kind

    def reader():
        if os.path.exists(path):
            yield from _tar_reader(path, sub)
        else:
            imgs, labels = _synthetic(n, num_classes, seed)
            for img, lbl in zip(imgs, labels):
                yield img, int(lbl)
    return reader


def train10():
    return _creator("train", 10, TRAIN_N, seed=0)


def test10():
    return _creator("test", 10, TEST_N, seed=1)


def train100():
    return _creator("train", 100, TRAIN_N, seed=2)


def test100():
    return _creator("test", 100, TEST_N, seed=3)
