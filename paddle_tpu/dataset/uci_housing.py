"""UCI housing (ref python/paddle/dataset/uci_housing.py).

Sample schema: (features float32[13] normalized, price float32[1]).
Synthetic fallback: linear ground truth + noise, deterministic.
"""
from __future__ import annotations

import os

import numpy as np

from .common import DATA_HOME

FEATURE_NUM = 13
TRAIN_N, TEST_N = 404, 102


def _load():
    path = os.path.join(DATA_HOME, "uci_housing", "housing.data")
    if os.path.exists(path):
        data = np.loadtxt(path)
        feats = data[:, :-1].astype("float32")
        prices = data[:, -1:].astype("float32")
    else:
        rng = np.random.RandomState(42)
        feats = rng.randn(TRAIN_N + TEST_N, FEATURE_NUM).astype("float32")
        w = rng.randn(FEATURE_NUM, 1).astype("float32")
        prices = (feats @ w + 22.5
                  + 0.5 * rng.randn(TRAIN_N + TEST_N, 1)).astype("float32")
    mu, sigma = feats.mean(0), feats.std(0) + 1e-6
    return (feats - mu) / sigma, prices


def _creator(lo, hi):
    def reader():
        feats, prices = _load()
        for i in range(lo, min(hi, len(feats))):
            yield feats[i], prices[i]
    return reader


def train():
    return _creator(0, TRAIN_N)


def test():
    return _creator(TRAIN_N, TRAIN_N + TEST_N)
