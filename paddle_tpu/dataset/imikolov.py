"""PTB-style n-gram language model data (ref python/paddle/dataset/
imikolov.py — word2vec book example). Sample: tuple of n token ids.
Synthetic fallback: Markov-chain token stream, deterministic."""
from __future__ import annotations

import numpy as np

VOCAB = 2000
TRAIN_N, TEST_N = 4096, 512


def build_dict(min_word_freq: int = 50):
    return {f"w{i}": i for i in range(VOCAB)}


def _creator(n_samples, n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        # sticky Markov chain: next ~ (cur + small step) mod VOCAB
        cur = int(rng.randint(VOCAB))
        window = []
        count = 0
        while count < n_samples:
            step = int(rng.choice([1, 2, 3, 5, 7]))
            cur = (cur + step) % VOCAB
            window.append(cur)
            if len(window) == n:
                yield tuple(window)
                window = window[1:]
                count += 1
    return reader


def train(word_idx=None, n: int = 5):
    return _creator(TRAIN_N, n, seed=0)


def test(word_idx=None, n: int = 5):
    return _creator(TEST_N, n, seed=1)
