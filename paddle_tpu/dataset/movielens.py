"""MovieLens-1M (ref python/paddle/dataset/movielens.py).

Sample schema (ref movielens.py:167 `usr.value() + mov.value() +
[[rating]]`): [user_id, gender_id, age_id, job_id, movie_id,
category_ids list, title_ids list, [rating]].
Synthetic fallback: deterministic preference structure (rating depends
on user/movie id parity) so models can fit it.
"""
from __future__ import annotations

import numpy as np

MAX_USER_ID = 6040
MAX_MOVIE_ID = 3952
MAX_JOB_ID = 20
age_table = [1, 18, 25, 35, 45, 50, 56]
CATEGORIES = ["Action", "Adventure", "Animation", "Children's", "Comedy",
              "Crime", "Documentary", "Drama", "Fantasy", "Film-Noir",
              "Horror", "Musical", "Mystery", "Romance", "Sci-Fi",
              "Thriller", "War", "Western"]
TITLE_VOCAB = 5174
TRAIN_N, TEST_N = 4096, 512


def max_user_id():
    return MAX_USER_ID


def max_movie_id():
    return MAX_MOVIE_ID


def max_job_id():
    return MAX_JOB_ID


def movie_categories():
    return {c: i for i, c in enumerate(CATEGORIES)}


def get_movie_title_dict():
    return {f"w{i}": i for i in range(TITLE_VOCAB)}


def _creator(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            uid = int(rng.randint(1, MAX_USER_ID + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, MAX_JOB_ID + 1))
            mid = int(rng.randint(1, MAX_MOVIE_ID + 1))
            cats = list(rng.randint(0, len(CATEGORIES),
                                    rng.randint(1, 4)).astype(int))
            title = list(rng.randint(0, TITLE_VOCAB,
                                     rng.randint(2, 9)).astype(int))
            rating = float(1 + (uid + mid) % 5)
            yield [uid, gender, age, job, mid, cats, title, [rating]]
    return reader


def train():
    return _creator(TRAIN_N, 0)


def test():
    return _creator(TEST_N, 1)
