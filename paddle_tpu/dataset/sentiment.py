"""Movie-review sentiment (ref python/paddle/dataset/sentiment.py,
NLTK movie_reviews).  Sample schema: (word_ids list, label 0/1)."""
from __future__ import annotations

import numpy as np

VOCAB = 5147
TRAIN_N, TEST_N = 1600, 400


def get_word_dict():
    return {f"w{i}": i for i in range(VOCAB)}


def _creator(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(10, 80))
            ids = (rng.zipf(1.35, length) + (0 if label else VOCAB // 2))
            yield list(np.clip(ids, 0, VOCAB - 1).astype(int)), label
    return reader


def train():
    return _creator(TRAIN_N, 0)


def test():
    return _creator(TEST_N, 1)
