"""CoNLL-2005 SRL (ref python/paddle/dataset/conll05.py).

Sample schema (ref conll05.py:199): (word_ids, ctx_n2, ctx_n1, ctx_0,
ctx_p1, ctx_p2, verb_ids, mark, label_ids) — 9 parallel int lists per
sentence (ctx/verb/mark are repeated per token).
Synthetic fallback: deterministic tag structure tied to word ids.
"""
from __future__ import annotations

import numpy as np

WORD_DICT_LEN = 44068
VERB_DICT_LEN = 3162
LABEL_DICT_LEN = 59
TEST_N = 512


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_DICT_LEN)}
    verb_dict = {f"v{i}": i for i in range(VERB_DICT_LEN)}
    label_dict = {f"t{i}": i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """ref conll05.py:218: pretrained word embedding table."""
    rng = np.random.RandomState(123)
    return rng.randn(WORD_DICT_LEN, 32).astype("float32") * 0.1


def _creator(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(5, 40))
            words = rng.randint(0, WORD_DICT_LEN, length)
            verb_pos = int(rng.randint(0, length))
            verb = int(words[verb_pos] % VERB_DICT_LEN)
            pad = lambda off: np.clip(
                np.roll(words, -off), 0, WORD_DICT_LEN - 1)
            mark = (np.arange(length) == verb_pos).astype(int)
            labels = ((words + verb) % LABEL_DICT_LEN).astype(int)
            yield (list(words.astype(int)), list(pad(-2).astype(int)),
                   list(pad(-1).astype(int)), list(words.astype(int)),
                   list(pad(1).astype(int)), list(pad(2).astype(int)),
                   [verb] * length, list(mark), list(labels))
    return reader


def test():
    return _creator(TEST_N, 1)
