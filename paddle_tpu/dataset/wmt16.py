"""WMT-16 en-de (ref python/paddle/dataset/wmt16.py); same sample
schema as wmt14 but with per-language dict sizes."""
from __future__ import annotations

from . import wmt14

START, END, UNK = wmt14.START, wmt14.END, wmt14.UNK


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14._creator(wmt14.TRAIN_N, 0, min(src_dict_size,
                                                trg_dict_size))


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14._creator(wmt14.TEST_N, 1, min(src_dict_size,
                                               trg_dict_size))


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14._creator(256, 2, min(src_dict_size, trg_dict_size))


def get_dict(lang, dict_size, reverse=False):
    d = {f"{lang}{i}": i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d
