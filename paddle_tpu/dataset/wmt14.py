"""WMT-14 en-fr (ref python/paddle/dataset/wmt14.py).

Sample schema (ref wmt14.py:113): (src_ids, trg_ids, trg_ids_next) with
<s>=0, <e>=1, <unk>=2 and trg_ids = [<s>] + sentence,
trg_ids_next = sentence + [<e>].
Synthetic fallback: target = deterministic function of source.
"""
from __future__ import annotations

import numpy as np

START, END, UNK = 0, 1, 2
TRAIN_N, TEST_N = 2048, 256


def _creator(n, seed, dict_size):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, length)
            trg = (src + 7) % (dict_size - 3) + 3     # deterministic map
            src_ids = list(src.astype(int))
            trg_ids = [START] + list(trg.astype(int))
            trg_next = list(trg.astype(int)) + [END]
            yield src_ids, trg_ids, trg_next
    return reader


def train(dict_size):
    return _creator(TRAIN_N, 0, dict_size)


def test(dict_size):
    return _creator(TEST_N, 1, dict_size)


def get_dict(dict_size, reverse=False):
    src = {f"w{i}": i for i in range(dict_size)}
    trg = {f"w{i}": i for i in range(dict_size)}
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
