"""PASCAL VOC2012 segmentation (ref python/paddle/dataset/voc2012.py).

Sample schema: (image chw float32, label hw int32 segmentation mask).
Synthetic fallback: rectangles of the class id on background 0.
"""
from __future__ import annotations

import numpy as np

N_CLASSES = 21
SIZE = 32
TRAIN_N, TEST_N, VAL_N = 512, 128, 128


def _creator(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            img = rng.rand(3, SIZE, SIZE).astype(np.float32)
            mask = np.zeros((SIZE, SIZE), np.int32)
            cls = int(rng.randint(1, N_CLASSES))
            x0, y0 = rng.randint(0, SIZE // 2, 2)
            mask[y0:y0 + SIZE // 2, x0:x0 + SIZE // 2] = cls
            img[0][mask > 0] += cls / N_CLASSES
            yield np.clip(img, 0, 1), mask
    return reader


def train():
    return _creator(TRAIN_N, 0)


def test():
    return _creator(TEST_N, 1)


def val():
    return _creator(VAL_N, 2)
