"""102-category flowers (ref python/paddle/dataset/flowers.py).

Sample schema: (image chw float32 in [0,1], label int 0..101).
Synthetic fallback: class-colored gaussian blobs, deterministic.
"""
from __future__ import annotations

import numpy as np

N_CLASSES = 102
SIZE = (3, 32, 32)     # synthetic keeps a small canvas; reference center-
                       # crops 224 — models take the shape from the sample
TRAIN_N, TEST_N, VALID_N = 1024, 128, 128


def _creator(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, N_CLASSES))
            base = np.zeros(SIZE, np.float32)
            base[label % 3] = (label / N_CLASSES)
            img = np.clip(base + rng.rand(*SIZE).astype(np.float32) * .3,
                          0, 1)
            yield img, label
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _creator(TRAIN_N, 0)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _creator(TEST_N, 1)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _creator(VALID_N, 2)
