"""MNIST dataset (ref python/paddle/dataset/mnist.py).

Sample schema: (image float32[784] scaled to [-1, 1], label int in [0, 10)).
If the real IDX files exist under DATA_HOME/mnist (user-provided; no egress
in this environment), they are parsed; otherwise a deterministic synthetic
set with the same schema is generated (class-dependent blob patterns so
models can actually fit it).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .common import DATA_HOME

TRAIN_N, TEST_N = 8192, 1024


def _real_path(kind: str):
    d = os.path.join(DATA_HOME, "mnist")
    img = os.path.join(d, f"{kind}-images-idx3-ubyte.gz")
    lbl = os.path.join(d, f"{kind}-labels-idx1-ubyte.gz")
    return (img, lbl) if os.path.exists(img) and os.path.exists(lbl) else None


def _parse_idx(img_path, lbl_path):
    with gzip.open(lbl_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(img_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    return images, labels


def _synthetic(n: int, seed: int):
    """Class-conditional gaussian blobs on a 28x28 grid."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n)
    yy, xx = np.mgrid[0:28, 0:28]
    images = np.empty((n, 784), dtype=np.float32)
    for c in range(10):
        cy, cx = 6 + 2 * (c // 5) * 6, 4 + (c % 5) * 5
        blob = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 18.0)
        idx = np.where(labels == c)[0]
        noise = rng.rand(len(idx), 784).astype(np.float32) * 0.3
        images[idx] = blob.ravel()[None, :].astype(np.float32) + noise
    images = images / images.max()
    return (images * 255).astype(np.uint8), labels.astype(np.uint8)


def _reader_creator(kind: str, n: int, seed: int):
    def reader():
        real = _real_path(kind)
        if real:
            images, labels = _parse_idx(*real)
        else:
            images, labels = _synthetic(n, seed)
        for img, lbl in zip(images, labels):
            yield img.astype("float32") / 127.5 - 1.0, int(lbl)
    return reader


def train():
    return _reader_creator("train", TRAIN_N, seed=0)


def test():
    return _reader_creator("t10k", TEST_N, seed=1)
