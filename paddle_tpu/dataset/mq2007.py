"""MQ2007 learning-to-rank (ref python/paddle/dataset/mq2007.py).

Modes (ref gen_point/gen_pair/gen_list): pointwise (score, 46-dim
feature), pairwise (better, worse features), listwise
(query_id, scores list, feature matrix).
Synthetic fallback: relevance = thresholded linear function of features.
"""
from __future__ import annotations

import numpy as np

FEATURE_DIM = 46
N_QUERIES = 339


def _queries(seed):
    rng = np.random.RandomState(seed)
    w = np.linspace(-1, 1, FEATURE_DIM)
    for qid in range(N_QUERIES):
        n_docs = int(rng.randint(5, 20))
        feats = rng.rand(n_docs, FEATURE_DIM).astype("float32")
        raw = feats @ w
        rel = np.digitize(raw, np.quantile(raw, [0.5, 0.8]))
        yield qid, rel.astype(int), feats


def train_point(seed=0):
    def reader():
        for _, rel, feats in _queries(seed):
            for r, f in zip(rel, feats):
                yield float(r), f
    return reader


def train_pair(seed=0):
    def reader():
        rng = np.random.RandomState(seed + 1)
        for _, rel, feats in _queries(seed):
            for _ in range(len(rel)):
                i, j = rng.randint(0, len(rel), 2)
                if rel[i] == rel[j]:
                    continue
                hi, lo = (i, j) if rel[i] > rel[j] else (j, i)
                yield feats[hi], feats[lo]
    return reader


def train_list(seed=0):
    def reader():
        for qid, rel, feats in _queries(seed):
            yield qid, list(rel.astype(float)), feats
    return reader


def train(format="pairwise"):
    return {"pointwise": train_point, "pairwise": train_pair,
            "listwise": train_list}[format]()


def test(format="pairwise"):
    return {"pointwise": train_point, "pairwise": train_pair,
            "listwise": train_list}[format](seed=7)
