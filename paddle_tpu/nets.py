"""Composed network helpers (ref python/paddle/fluid/nets.py:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""
from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None):
    conv_out = layers.conv2d(input, num_filters, filter_size,
                             stride=conv_stride, padding=conv_padding,
                             dilation=conv_dilation, groups=conv_groups,
                             param_attr=param_attr, bias_attr=bias_attr,
                             act=act)
    return layers.pool2d(conv_out, pool_size, pool_type, pool_stride,
                         pool_padding, global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act="relu",
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max"):
    tmp = input
    if isinstance(conv_with_batchnorm, bool):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if isinstance(conv_batchnorm_drop_rate, (int, float)):
        conv_batchnorm_drop_rate = ([conv_batchnorm_drop_rate]
                                    * len(conv_num_filter))
    for i, nf in enumerate(conv_num_filter):
        local_act = None if conv_with_batchnorm[i] else conv_act
        tmp = layers.conv2d(tmp, nf, conv_filter_size,
                            padding=conv_padding, act=local_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i] > 0:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate[i])
    return layers.pool2d(tmp, pool_size, pool_type, pool_stride)


def glu(input, dim=-1):
    a, b = layers.split(input, 2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head attention composed from program ops
    (ref nets.py scaled_dot_product_attention).  For the fused Pallas
    flash-attention path use layers.nn-level models with
    kernels/flash_attention."""
    d_key = int(queries.shape[-1]) // num_heads

    def _split_heads(x):
        b = x.shape[0]
        t = int(x.shape[1])
        d = int(x.shape[2])
        y = layers.reshape(x, [0 if b == -1 else b, t, num_heads,
                               d // num_heads])
        return layers.transpose(y, [0, 2, 1, 3])

    q = _split_heads(queries)
    k = _split_heads(keys)
    v = _split_heads(values)
    scores = layers.matmul(q, k, transpose_y=True,
                           alpha=float(d_key) ** -0.5)
    weights = layers.softmax(scores)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_rate,
                                 dropout_implementation="upscale_in_train")
    ctx = layers.matmul(weights, v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    t = int(ctx.shape[2]) if len(ctx.shape) > 2 else -1
    return layers.reshape(ctx, [0, int(queries.shape[1]),
                                int(queries.shape[2])])


def sequence_conv_pool(input, num_filters, filter_size, act="sigmoid",
                       pool_type="max", mask=None):
    """ref nets.py sequence_conv_pool: context conv over time + pool."""
    conv_out = layers.sequence_conv(input, num_filters=num_filters,
                                    filter_size=filter_size, act=act)
    return layers.sequence_pool(conv_out, pool_type, mask=mask)
