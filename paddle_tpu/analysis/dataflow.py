"""Dataflow lints over the Program IR.

The interpreter-era reference caught these at run time, one op deep
(scope lookup failures, fetch misses); here they are whole-program
static checks emitting structured findings (findings.py):

  * ``undefined_read`` (error): an op input that no earlier op
    produces and that is neither fed, persistable, a data var, nor
    visible from an ancestor block — the executor would die mid-trace
    with "is not materialised";
  * ``missing_fetch`` (error): a fetch name nothing defines;
  * ``dead_op`` (warn): an op none of whose outputs reach a fetch, a
    persistable write, or any downstream reader — fetch- and
    GRAD-aware: liveness flows backwards from the fetch set +
    persistable state through the autodiff op's params/grads, exactly
    like the executor's one-function lowering (XLA would DCE these;
    the lint names what the user probably thought they were running);
  * ``double_write`` (warn): two ops write the same var in one block —
    functional-env shadowing, a transpiler-rewrite hazard (control-flow
    carry init writes are exempt);
  * ``orphan_param`` (warn): a Parameter declared in the program that
    no op reads or writes (left behind by a partial rewrite).
"""
from __future__ import annotations

from typing import Optional, Sequence, Set

from ..framework.program import Parameter
from . import traversal
from .findings import ERROR, WARN, AnalysisResult, Finding

PASS = "dataflow"

# control-flow ops re-write their carried vars by design: an earlier
# init write (fill_constant) + the loop's write is the documented
# pattern, not a hazard
_CARRY_WRITERS = frozenset({"while", "conditional_block", "scan",
                            "static_rnn_scan", "increment_loop_counter"})


class DataflowPass:
    name = PASS

    def run(self, program, result: AnalysisResult,
            feed_names: Optional[Set[str]] = None,
            fetch_names: Optional[Sequence[str]] = None,
            scope=None):
        result.passes_run.append(self.name)
        block = program.global_block()
        persistable = {v.name for v in program.list_vars()
                       if v.persistable}
        data_vars = {v.name for v in program.list_vars() if v.is_data}
        # scope-provided state counts as defined even when the program
        # forgot to mark it persistable (executor contract: only
        # persistables ride in, so don't silently widen beyond it)
        fed = set(feed_names) if feed_names is not None else set(data_vars)

        self._undefined_reads(program, result, block, fed, persistable,
                              data_vars)
        self._double_writes(result, block)
        if fetch_names is not None:
            self._missing_fetch(result, block, fed, persistable,
                                fetch_names)
            self._dead_ops(program, result, block, persistable,
                           fetch_names)
        self._orphan_params(program, result)

    # ------------------------------------------------------------------
    def _undefined_reads(self, program, result, block, fed, persistable,
                         data_vars):
        defined = set(fed) | persistable
        # feeds not named in the feed set but declared as data vars are
        # STILL undefined reads — that is exactly the "fetch ran before
        # its producer / forgot to feed" trace crash, caught statically
        for i, op in enumerate(block.ops):
            if op.type in traversal.STRUCTURAL_OPS:
                continue
            for slot, names in op.inputs.items():
                for n in names:
                    if n and n not in defined:
                        what = ("is a data var missing from the feed"
                                if n in data_vars else
                                "has no producer before this op and is "
                                "neither fed nor persistable")
                        result.add(Finding(
                            pass_name=self.name, code="undefined_read",
                            severity=ERROR,
                            message=(f"op {op.type!r} reads {slot}:"
                                     f"{n!r}, which {what}"),
                            block_idx=block.idx, op_index=i,
                            op_type=op.type, var_names=(n,),
                            callsite=getattr(op, "callsite", None)))
            defined.update(traversal.op_output_names(op))
        # sub-blocks: conservative — anything defined anywhere in an
        # ancestor is visible (control-flow carry ordering is the
        # executor's business); only truly nonexistent names flag
        if len(program.blocks) > 1:
            all_defined = set(defined)
            for b in program.blocks[1:]:
                sub_defined = set(all_defined)
                for i, op in enumerate(b.ops):
                    if op.type in traversal.STRUCTURAL_OPS:
                        continue
                    for slot, names in op.inputs.items():
                        for n in names:
                            if n and n not in sub_defined \
                                    and not b.has_var(n):
                                result.add(Finding(
                                    pass_name=self.name,
                                    code="undefined_read",
                                    severity=ERROR,
                                    message=(f"op {op.type!r} in "
                                             f"sub-block {b.idx} reads "
                                             f"{slot}:{n!r}, which is "
                                             f"defined nowhere"),
                                    block_idx=b.idx, op_index=i,
                                    op_type=op.type, var_names=(n,),
                                    callsite=getattr(op, "callsite",
                                                     None)))
                    sub_defined.update(traversal.op_output_names(op))

    # ------------------------------------------------------------------
    def _double_writes(self, result, block):
        writers: dict = {}
        for i, op in enumerate(block.ops):
            if op.type in traversal.STRUCTURAL_OPS:
                continue
            for n in traversal.op_output_names(op):
                writers.setdefault(n, []).append((i, op))
        from ..framework.program import GRAD_SUFFIX
        for n, ws in writers.items():
            if len(ws) < 2:
                continue
            if any(op.type in _CARRY_WRITERS for _, op in ws):
                continue        # loop-carry init + loop write pattern
            if GRAD_SUFFIX in n:
                # GRAD-aware: the distributed transpilers rewrite
                # gradients IN PLACE (autodiff writes g, the inserted
                # allreduce/scale/assign writes g back) so downstream
                # optimizer ops need no rewiring — the documented
                # idiom, not a hazard
                continue
            i, op = ws[-1]
            result.add(Finding(
                pass_name=self.name, code="double_write", severity=WARN,
                message=(f"var {n!r} is written by "
                         f"{len(ws)} ops (op #"
                         f"{', #'.join(str(j) for j, _ in ws)}); later "
                         f"writes shadow earlier ones in the compiled "
                         f"step"),
                block_idx=block.idx, op_index=i, op_type=op.type,
                var_names=(n,), callsite=getattr(op, "callsite", None)))

    # ------------------------------------------------------------------
    def _missing_fetch(self, result, block, fed, persistable,
                       fetch_names):
        produced = set(fed) | persistable
        for op in block.ops:
            produced.update(traversal.op_output_names(op))
        for n in fetch_names:
            if n not in produced:
                result.add(Finding(
                    pass_name=self.name, code="missing_fetch",
                    severity=ERROR,
                    message=(f"fetch {n!r} is produced by no op and is "
                             f"neither fed nor persistable"),
                    block_idx=block.idx, var_names=(n,)))

    # ------------------------------------------------------------------
    def _dead_ops(self, program, result, block, persistable,
                  fetch_names):
        """Backward liveness from fetches + persistable writes.  Reads
        from sub-blocks keep a parent var live (conservative)."""
        sub_reads: Set[str] = set()
        for b in program.blocks[1:]:
            for op in b.ops:
                sub_reads.update(traversal.op_input_names(op))
        needed = set(fetch_names) | sub_reads
        for i in range(len(block.ops) - 1, -1, -1):
            op = block.ops[i]
            if op.type in traversal.STRUCTURAL_OPS:
                continue
            outs = traversal.op_output_names(op)
            live = (not outs                      # side-effect-only op
                    or any(n in needed for n in outs)
                    or any(n in persistable for n in outs))
            if live:
                needed.update(traversal.op_input_names(op))
            else:
                result.add(Finding(
                    pass_name=self.name, code="dead_op", severity=WARN,
                    message=(f"op {op.type!r} writes only "
                             f"{sorted(outs)!r}, which nothing reads, "
                             f"fetches, or persists — dead code the "
                             f"compiled step will DCE"),
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    var_names=tuple(outs),
                    callsite=getattr(op, "callsite", None)))

    # ------------------------------------------------------------------
    def _orphan_params(self, program, result):
        used: Set[str] = set()
        for _, _, op in traversal.iter_ops(program):
            used.update(traversal.op_input_names(op))
            used.update(traversal.op_output_names(op))
        block = program.global_block()
        for name, var in block.vars.items():
            if isinstance(var, Parameter) and name not in used:
                result.add(Finding(
                    pass_name=self.name, code="orphan_param",
                    severity=WARN,
                    message=(f"parameter {name!r} is declared (and will "
                             f"be staged from the scope) but no op "
                             f"reads or writes it"),
                    block_idx=block.idx, var_names=(name,)))
