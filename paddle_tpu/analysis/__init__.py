"""Static program verifier & lint plane (ISSUE 10).

Capability parity with two reference subsystems:

  * per-op compile-time InferShape/InferVarType (framework/
    shape_inference.h; PAPER.md §1 framework-layer contract) ->
    shape_inference.py + the infer rules registered alongside OpDef
    (framework/registry.py register_shape_infer);
  * the inference analysis pass manager (paddle/fluid/inference/
    analysis/) that validated graphs ahead of the predictor ->
    passes.py over the Program IR, read-only, emitting structured
    Finding records (schema ``paddle_tpu.analysis.v1``).

Consumers: the Executor's pre-dispatch gate (verify_program flag), the
five transpilers' post-conditions (check_transpiled), the lint CLI
(``python -m paddle_tpu.analysis.lint`` — the static-analysis CI
gate), Executor.explain()'s analysis section, bench.py's workload
gate, and debugger.draw_block_graphviz(highlight=...).
"""
from .findings import (ERROR, INFO, SCHEMA, WARN, AnalysisResult,
                       Finding)
from .infer_rules import InferError
from .passes import (ProgramVerificationError, check_transpiled,
                     maybe_check_transpiled, quick_lints, reset,
                     verify_program)
from . import traversal

__all__ = [
    "AnalysisResult", "Finding", "InferError",
    "ProgramVerificationError", "SCHEMA", "ERROR", "WARN", "INFO",
    "check_transpiled", "maybe_check_transpiled", "quick_lints",
    "reset", "traversal", "verify_program",
]
