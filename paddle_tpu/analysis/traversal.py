"""Program-IR traversal helpers shared by every analysis pass (and by
contrib/: op_frequence, memory_usage_calc — they walk the SAME iterators
so they cannot rot against the IR independently again)."""
from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from ..framework.program import Block, Operator, Program, Variable

# Ops the executor interprets structurally (no dataflow of their own).
STRUCTURAL_OPS = ("feed", "fetch", "data")


def iter_blocks(program: Program) -> Iterator[Block]:
    yield from program.blocks


def iter_ops(program: Program,
             include_structural: bool = True
             ) -> Iterator[Tuple[Block, int, Operator]]:
    """Yield (block, op_index, op) over every block in program order.
    ``op_index`` is the position in ``block.ops`` INCLUDING structural
    ops, so it is stable against the debugger's node ids."""
    for block in program.blocks:
        for i, op in enumerate(block.ops):
            if not include_structural and op.type in STRUCTURAL_OPS:
                continue
            yield block, i, op


def iter_vars(program: Program) -> Iterator[Tuple[Block, Variable]]:
    for block in program.blocks:
        for var in block.vars.values():
            yield block, var


def op_input_names(op: Operator) -> List[str]:
    return [n for ns in op.inputs.values() for n in ns if n]


def op_output_names(op: Operator) -> List[str]:
    return [n for ns in op.outputs.values() for n in ns if n]


def consumers(program: Program) -> Dict[str, List[Tuple[int, int]]]:
    """var name -> [(block_idx, op_index)] of every op reading it,
    across ALL blocks (a sub-block read keeps a parent var alive)."""
    out: Dict[str, List[Tuple[int, int]]] = {}
    for block, i, op in iter_ops(program):
        for n in op_input_names(op):
            out.setdefault(n, []).append((block.idx, i))
    return out


def producers(program: Program) -> Dict[str, List[Tuple[int, int]]]:
    """var name -> [(block_idx, op_index)] of every op writing it."""
    out: Dict[str, List[Tuple[int, int]]] = {}
    for block, i, op in iter_ops(program):
        for n in op_output_names(op):
            out.setdefault(n, []).append((block.idx, i))
    return out


def adjacent_op_pairs(program: Program) -> Iterator[Tuple[str, str]]:
    """(prev_type, type) for each adjacent op pair within a block —
    the contrib op_frequence adjacency walk."""
    for block in program.blocks:
        prev = None
        for op in block.ops:
            if prev is not None:
                yield prev, op.type
            prev = op.type


def declared_info(block: Block, name: str):
    """(shape tuple | None, dtype str | None) of a var as DECLARED in
    the program, walking ancestor blocks; (None, None) when unknown."""
    if not block.has_var(name):
        return None, None
    v = block.var(name)
    shape = tuple(int(s) for s in v.shape) if v.shape else None
    return shape, (str(v.dtype) if v.dtype else None)
