"""Pre-dispatch hazard lints: the run-context checks the Executor
consults before compiling (ISSUE 10 part d).

These need feed/fetch context, so they live apart from the structural
dataflow lints:

  * ``donated_fetch`` (error): a donated feed buffer is also fetched —
    the fetch would read memory XLA just reused (donate_feeds is the
    trainer-prefetch fast path);
  * ``unknown_feed`` (warn): a feed name the program declares no var
    for — each distinctly-shaped value forks a fresh executable keyed
    on a name the program never reads (the predictor's silent-fork bug
    class);
  * ``unset_feed_shape`` (warn): a fed var with NO static shape
    recorded — every caller-side shape drift is a fresh compile, the
    "feed_shapes" recompile-storm cause forensics diagnoses after the
    fact, caught statically here;
  * ``lowp_accum`` (warn): a matmul/conv/reduction consuming
    fp16/bf16 values while the amp plane (which keeps f32
    accumulation + master params) is off — silent precision loss the
    reference's float16 transpiler existed to prevent.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Set

from ..core import flags
from . import traversal
from .findings import ERROR, WARN, AnalysisResult, Finding

PASS = "hazards"

_ACCUM_OPS = frozenset({"mul", "matmul", "bmm", "conv2d",
                        "conv2d_transpose", "reduce_sum", "reduce_mean",
                        "sum", "mean"})
_LOWP = ("float16", "bfloat16")


class HazardPass:
    name = PASS

    def run(self, program, result: AnalysisResult,
            feed_names: Optional[Set[str]] = None,
            fetch_names: Optional[Sequence[str]] = None,
            donate_feeds: bool = False,
            var_dtypes: Optional[Dict[str, str]] = None):
        result.passes_run.append(self.name)
        block = program.global_block()
        feed_names = set(feed_names or ())

        if donate_feeds:
            for n in set(fetch_names or ()) & feed_names:
                result.add(Finding(
                    pass_name=self.name, code="donated_fetch",
                    severity=ERROR,
                    message=(f"feed {n!r} is donated (donate_feeds) AND "
                             f"fetched: the fetch would alias a buffer "
                             f"XLA may already have reused — fetch a "
                             f"copy or drop the donation"),
                    block_idx=block.idx, var_names=(n,)))

        for n in sorted(feed_names):
            if not block.has_var(n):
                result.add(Finding(
                    pass_name=self.name, code="unknown_feed",
                    severity=WARN,
                    message=(f"feed {n!r} names no var in the program; "
                             f"its value enters the compile key but no "
                             f"op can read it — every shape drift on it "
                             f"forks a fresh executable"),
                    block_idx=block.idx, var_names=(n,)))
                continue
            var = block.var(n)
            if var.shape is None:
                result.add(Finding(
                    pass_name=self.name, code="unset_feed_shape",
                    severity=WARN,
                    message=(f"fed var {n!r} has no static shape "
                             f"recorded: every caller-side shape drift "
                             f"compiles a fresh executable (the "
                             f"'feed_shapes' recompile-storm cause) — "
                             f"declare it via layers.data"),
                    block_idx=block.idx, var_names=(n,)))

        if not flags.get_flag("amp_bf16"):
            for i, op in enumerate(block.ops):
                if op.type not in _ACCUM_OPS:
                    continue
                lowp = []
                for n in traversal.op_input_names(op):
                    _, dt = traversal.declared_info(block, n)
                    dt = (var_dtypes or {}).get(n, dt)
                    if dt in _LOWP:
                        lowp.append((n, dt))
                if lowp:
                    names = ", ".join(f"{n} ({d})" for n, d in lowp)
                    result.add(Finding(
                        pass_name=self.name, code="lowp_accum",
                        severity=WARN,
                        message=(f"op {op.type!r} accumulates over "
                                 f"low-precision input(s) {names} with "
                                 f"the amp plane off — enable amp_bf16 "
                                 f"(f32 accumulation, f32 master "
                                 f"params) or cast before reducing"),
                        block_idx=block.idx, op_index=i,
                        op_type=op.type,
                        var_names=tuple(n for n, _ in lowp),
                        callsite=getattr(op, "callsite", None)))
