"""Static-analysis gate CLI: build and verify every bundled model.

    python -m paddle_tpu.analysis.lint [--models a,b,...] [-v] [--list]

Builds each ``models/*`` network (small configs — program construction
only, nothing is compiled or run), attaches an optimizer where the net
is trainable, and runs the full verifier (shape inference + dataflow +
hazard lints) over the main AND startup programs with the model's
natural fetch set.  Exit codes: 0 clean, 1 error-severity findings (or
a build crash), 2 bad usage.

This is the CI gate (tier-1: tests/test_analysis.py::
test_analysis_cli_all_models) — a transpiler or op-registry change
that breaks any bundled model's program now fails with a named
finding instead of a mid-jit XLA trace.
"""
from __future__ import annotations

import argparse
import sys
from typing import Callable, Dict, Tuple

from .passes import verify_program


def _optimize(loss):
    from .. import optimizer
    optimizer.SGD(learning_rate=0.01).minimize(loss)


def _simple(builder, train=True):
    def build():
        out = builder()
        feeds, rest = out[0], out[1:]
        if train:
            _optimize(rest[0])
        return feeds, [r for r in rest if r is not None]
    return build


def model_builders() -> Dict[str, Callable[[], Tuple[list, list]]]:
    """name -> zero-arg builder running inside a fresh program_guard;
    returns (feed vars, fetch vars)."""
    from .. import models

    def transformer_cfg(T=16, dropout=0.1):
        return models.transformer.TransformerConfig(
            src_vocab_size=64, tgt_vocab_size=64, max_length=T,
            n_layer=2, n_head=2, d_model=16, d_inner=32,
            dropout=dropout)

    def lm():
        # flash-attention contract: no attention-prob dropout
        feeds, cost, logits = models.transformer.build_lm_net(
            transformer_cfg(dropout=0.0), seq_len=16)
        _optimize(cost)
        return feeds, [cost, logits]

    def nmt():
        feeds, cost = models.machine_translation.build_train_net(
            src_vocab=50, tgt_vocab=50, src_len=8, tgt_len=8)
        _optimize(cost)
        return feeds, [cost]

    def bert():
        cfg = models.bert.BertConfig(
            vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
            intermediate_size=32, max_position=32, dropout=0.1)
        feeds, loss, (mlm, nsp) = models.bert.build_pretrain_net(
            cfg, seq_len=16)
        _optimize(loss)
        return feeds, [loss, mlm, nsp]

    def deepfm():
        cfg = models.deepfm.DeepFMConfig(
            num_field=4, vocab_size=50, embed_dim=4, fc_sizes=(8, 8))
        feeds, cost, prob = models.deepfm.build_train_net(cfg)
        _optimize(cost)
        return feeds, [cost, prob]

    def deepfm_sparse():
        # the sparse plane's Program-path DeepFM: hash-bucketed
        # sparse_embedding_lookup ops (19th gate model, ISSUE 13)
        cfg = models.deepfm.DeepFMConfig(
            num_field=4, vocab_size=50, embed_dim=4, fc_sizes=(8, 8))
        feeds, cost, prob = models.deepfm.build_sparse_train_net(cfg)
        _optimize(cost)
        return feeds, [cost, prob]

    return {
        "lenet": _simple(models.lenet.build_train_net),
        "alexnet": _simple(lambda: models.alexnet.build_train_net(
            class_dim=10, img_shape=(3, 64, 64))),
        "vgg": _simple(models.vgg.build_train_net),
        "googlenet": _simple(lambda: models.googlenet.build_train_net(
            class_dim=10, img_shape=(3, 64, 64))),
        "resnet": _simple(lambda: models.resnet.build_train_net(
            class_dim=10, img_shape=(3, 32, 32), depth=18)),
        "se_resnext": _simple(lambda: models.se_resnext.build_train_net(
            class_dim=10, img_shape=(3, 32, 32), depth=50,
            stage_blocks=(1, 1, 1, 1))),
        "transformer": _simple(lambda: models.transformer.build_train_net(
            transformer_cfg(), src_len=8, tgt_len=8)),
        "transformer_lm": lm,
        "bert": bert,
        "deepfm": deepfm,
        "deepfm_sparse": deepfm_sparse,
        "nmt": nmt,
        "stacked_lstm": _simple(models.stacked_lstm.build_train_net),
        "book_fit_a_line": _simple(models.book.fit_a_line),
        "book_word2vec": _simple(lambda: models.book.word2vec(
            dict_size=50)),
        "book_recommender": _simple(models.book.recommender_system),
        "book_rnn_enc_dec": _simple(models.book.rnn_encoder_decoder),
        "book_db_lstm": _simple(models.book.db_lstm),
        "mt_beam_decode": _simple(
            lambda: models.machine_translation.build_decode_net(
                src_vocab=50, tgt_vocab=50, src_len=8),
            train=False),
    }


def lint_model(name: str, build, verbose: bool = False) -> Tuple[int, int]:
    """Build one model in fresh programs and verify; returns
    (#errors, #warnings).  Build crashes count as one error."""
    from ..framework.program import Program, program_guard
    main, startup = Program(), Program()
    try:
        with program_guard(main, startup):
            feeds, fetches = build()
    except Exception as e:
        print(f"[lint] {name}: BUILD FAILED: {e!r}")
        return 1, 0
    res = verify_program(main, feed=[v.name for v in feeds],
                         fetch_list=fetches)
    sres = verify_program(startup)
    errs = len(res.errors) + len(sres.errors)
    warns = len(res.warnings) + len(sres.warnings)
    status = "FAIL" if errs else "ok"
    print(f"[lint] {name}: {status} ({len(main.global_block().ops)} ops, "
          f"{errs} errors, {warns} warnings)")
    if verbose or errs:
        for scope_name, r in (("main", res), ("startup", sres)):
            for f in r.sorted():
                if f.severity == "error" or verbose:
                    print(f"  {scope_name}: {f}")
        if verbose and res.unknown_shape_ops:
            uniq = sorted(set(res.unknown_shape_ops))
            print(f"  unknown-shape ops: {uniq}")
    return errs, warns


def _self_test() -> int:
    """--self-test: the gate must CATCH a deliberately broken program
    (exit 1) — validates the exit-code contract end to end."""
    from ..framework.program import Program, program_guard
    from .. import layers
    main = Program()
    with program_guard(main, Program()):
        x = layers.data("x", [4], dtype="float32")
        y = layers.scale(x, scale=2.0)
        # sever the dataflow: rewire the op to a var nothing produces
        main.global_block().ops[-1].inputs["X"] = ["missing_input"]
    res = verify_program(main, feed=["x"], fetch_list=[y])
    if res.by_code("undefined_read"):
        print("[lint] self-test: broken program caught (exit 1)")
        return 1
    print("[lint] self-test: verifier MISSED the broken program")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis.lint",
        description="Build and statically verify every bundled model.")
    ap.add_argument("--models", default="",
                    help="comma list of model names (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="print the model names and exit")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--self-test", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.self_test:
        return _self_test()
    builders = model_builders()
    if args.list:
        print("\n".join(builders))
        return 0
    names = ([n.strip() for n in args.models.split(",") if n.strip()]
             or list(builders))
    unknown = [n for n in names if n not in builders]
    if unknown:
        print(f"[lint] unknown model(s): {unknown}; see --list")
        return 2
    total_e = total_w = 0
    for n in names:
        e, w = lint_model(n, builders[n], verbose=args.verbose)
        total_e += e
        total_w += w
    print(f"[lint] {len(names)} models: {total_e} errors, "
          f"{total_w} warnings")
    return 1 if total_e else 0


if __name__ == "__main__":
    sys.exit(main())
