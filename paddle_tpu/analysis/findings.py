"""Finding records: the structured output of every analysis pass.

Capability parity with the reference's inference analysis-pass logging
(paddle/fluid/inference/analysis/analyzer.cc pass manager prints) and
the InferShape error surface (framework/shape_inference.h + per-op
PADDLE_ENFORCE messages) — re-designed as DATA: each pass emits Finding
records (schema ``paddle_tpu.analysis.v1``) instead of prose, so the
executor gate, the lint CLI, Executor.explain(), the graphviz overlay
and the metrics plane all consume one shape.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..observability import metrics as obs_metrics

SCHEMA = "paddle_tpu.analysis.v1"

ERROR = "error"
WARN = "warn"
INFO = "info"
_SEV_ORDER = {ERROR: 0, WARN: 1, INFO: 2}

_m_findings = obs_metrics.counter(
    "analysis_findings_total",
    "Static-analysis findings emitted by the program verifier / lint "
    "pass manager (paddle_tpu/analysis), by pass and severity.",
    ("pass", "severity"))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic from one pass over one Program.

    ``op_index`` is the op's position in ``program.blocks[block_idx]
    .ops`` (structural feed/fetch/data ops included), so it indexes the
    same list the debugger's graphviz overlay and pprint use.  -1 means
    the finding is not anchored to a single op (e.g. a missing fetch).
    ``callsite`` is the user-code ``file:line`` that appended the op,
    when the program was built in this process (None for deserialized
    programs).
    """
    pass_name: str
    code: str
    severity: str
    message: str
    block_idx: int = 0
    op_index: int = -1
    op_type: Optional[str] = None
    var_names: Tuple[str, ...] = ()
    callsite: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "schema": SCHEMA,
            "pass": self.pass_name,
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "block_idx": self.block_idx,
            "op_index": self.op_index,
            "op_type": self.op_type,
            "var_names": list(self.var_names),
            "callsite": self.callsite,
        }

    def __str__(self):
        loc = ""
        if self.op_index >= 0:
            loc = f" [block {self.block_idx} op #{self.op_index}"
            if self.op_type:
                loc += f" {self.op_type!r}"
            loc += "]"
        site = f" ({self.callsite})" if self.callsite else ""
        return f"{self.severity}:{self.code}{loc} {self.message}{site}"


class AnalysisResult:
    """Ordered findings of one verifier run, errors first.

    ``record_metrics=False`` builds a pure-observer result (no
    ``analysis_findings_total`` increments) — for read-only views like
    Executor.explain() that would otherwise turn the counter into a
    call-rate proxy."""

    def __init__(self, record_metrics: bool = True):
        self.record_metrics = record_metrics
        self.findings: List[Finding] = []
        # passes that ran (for report/debug; dead_op may be skipped
        # when no fetch list is known)
        self.passes_run: List[str] = []
        # op types whose output shapes degraded to unknown (no infer
        # rule and generic abstract eval unavailable) — not findings,
        # but the CLI's -v view shows them
        self.unknown_shape_ops: List[str] = []

    def add(self, finding: Finding):
        self.findings.append(finding)
        if self.record_metrics:
            _m_findings.labels(**{"pass": finding.pass_name,
                                  "severity": finding.severity}).inc()

    def extend(self, other: "AnalysisResult"):
        for f in other.findings:
            self.findings.append(f)
        self.passes_run.extend(other.passes_run)
        self.unknown_shape_ops.extend(other.unknown_shape_ops)

    # -- views ---------------------------------------------------------
    def sorted(self) -> List[Finding]:
        return sorted(self.findings,
                      key=lambda f: (_SEV_ORDER.get(f.severity, 9),
                                     f.block_idx, f.op_index))

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARN]

    def by_code(self, code: str) -> List[Finding]:
        return [f for f in self.findings if f.code == code]

    def counts(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {"schema": SCHEMA,
                "counts": self.counts(),
                "passes": list(dict.fromkeys(self.passes_run)),
                "findings": [f.to_dict() for f in self.sorted()]}

    def report(self, max_findings: int = 50) -> str:
        """Human-readable multi-line summary (the CLI / raise text)."""
        fs = self.sorted()
        lines = [f"{len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s), "
                 f"{len(self.findings) - len(self.errors) - len(self.warnings)} "
                 f"info finding(s)"]
        for f in fs[:max_findings]:
            lines.append("  " + str(f))
        if len(fs) > max_findings:
            lines.append(f"  ... {len(fs) - max_findings} more")
        return "\n".join(lines)

    def __repr__(self):
        c = self.counts()
        return (f"AnalysisResult(errors={c.get(ERROR, 0)}, "
                f"warnings={c.get(WARN, 0)}, infos={c.get(INFO, 0)})")
