"""Pass manager: the whole-program verifier.

The reference shipped a static-analysis pass manager ahead of its
predictor (paddle/fluid/inference/analysis/: Analyzer runs a
registered pass list over the graph, each pass validating/rewriting);
this is the same discipline over the Program IR, read-only: passes
emit findings, callers decide (warn / raise / exit 1).

Entry points:
  * ``verify_program(program, ...)`` — full verification (shape
    inference + dataflow + hazards) -> AnalysisResult;
  * ``quick_lints(program, ...)`` — the cheap O(ops) subset the
    Executor runs pre-dispatch in warn mode (no abstract eval);
  * ``check_transpiled(program, name)`` — transpiler post-condition:
    re-verify the rewritten program in strict mode and RAISE on any
    error finding, turning a silent miscompile into a named
    diagnostic.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Set

from ..core.enforce import EnforceNotMet
from ..framework import registry as _registry
from .dataflow import DataflowPass
from .findings import AnalysisResult
from .hazards import HazardPass
from .shape_inference import ShapeInferencePass


class ProgramVerificationError(EnforceNotMet):
    """Raised when verification rejects a program (error-severity
    findings under verify_program=error / a transpiler post-condition).
    Carries the full AnalysisResult as ``.result``."""

    def __init__(self, message: str, result: AnalysisResult):
        super().__init__(message)
        self.result = result


def _norm_feed(feed) -> Optional[Set[str]]:
    if feed is None:
        return None
    return set(feed)        # dict -> keys; sequence -> names


def _norm_fetch(fetch_list) -> Optional[Sequence[str]]:
    if fetch_list is None:
        return None
    out = []
    for f in fetch_list:
        out.append(f if isinstance(f, str) else getattr(f, "name", str(f)))
    return out


def verify_program(program=None,
                   feed=None,
                   fetch_list=None,
                   scope=None,
                   donate_feeds: bool = False,
                   strict_shapes: bool = False,
                   feed_shapes: Optional[Dict[str, tuple]] = None,
                   record_metrics: bool = True) -> AnalysisResult:
    """Run every analysis pass over ``program``; returns the findings.

    ``feed`` may be a feed dict or an iterable of feed names; None
    means "every data var is fed" (the lint-CLI view).  ``fetch_list``
    accepts Variables or names; None skips the fetch-relative lints
    (missing_fetch, dead_op).  ``strict_shapes`` promotes generic
    abstract-eval failures on fully-known shapes to errors (the
    transpiler post-condition mode).  ``feed_shapes`` overrides the
    declared shapes of fed vars with runtime shapes (the executor
    passes the actual batch).  ``record_metrics=False`` makes the run
    a pure observer (no analysis_findings_total increments) — for
    explain()-style read-only views."""
    from ..framework.program import default_main_program
    program = program or default_main_program()
    feed_names = _norm_feed(feed)
    fetch_names = _norm_fetch(fetch_list)

    result = AnalysisResult(record_metrics=record_metrics)
    env = ShapeInferencePass().run(program, result,
                                   feed_shapes=feed_shapes,
                                   strict=strict_shapes)
    DataflowPass().run(program, result, feed_names=feed_names,
                       fetch_names=fetch_names, scope=scope)
    HazardPass().run(program, result, feed_names=feed_names,
                     fetch_names=fetch_names, donate_feeds=donate_feeds,
                     var_dtypes={n: d for n, (s, d) in env.items()
                                 if d is not None})
    return result


def quick_lints(program,
                feed=None,
                fetch_list=None,
                scope=None,
                donate_feeds: bool = False) -> AnalysisResult:
    """The O(ops) dict-walk subset (dataflow + hazards, NO abstract
    shape eval): cheap enough to run on every executor cache miss."""
    result = AnalysisResult()
    DataflowPass().run(program, result, feed_names=_norm_feed(feed),
                       fetch_names=_norm_fetch(fetch_list), scope=scope)
    HazardPass().run(program, result, feed_names=_norm_feed(feed),
                     fetch_names=_norm_fetch(fetch_list),
                     donate_feeds=donate_feeds)
    return result


def check_transpiled(program, transpiler: str) -> AnalysisResult:
    """Transpiler post-condition: the rewritten program must re-verify
    clean.  Raises ProgramVerificationError naming the transpiler on
    any error-severity finding; returns the result otherwise."""
    result = verify_program(program, strict_shapes=True)
    errs = result.errors
    if errs:
        raise ProgramVerificationError(
            f"{transpiler} produced a program that fails verification "
            f"— a transpiler bug, not a user error.  Findings:\n"
            + result.report(), result)
    return result


def maybe_check_transpiled(program, transpiler: str):
    """The hook the transpilers call: post-condition verification
    unless verify_program=off (the escape hatch that restores pre-PR
    behavior end to end)."""
    from ..core import flags
    if str(flags.get_flag("verify_program")) == "off":
        return None
    return check_transpiled(program, transpiler)


# --- test-isolation hook (tests/conftest.py) ------------------------------
_BUILTIN_RULES = None


def _snapshot_builtin_rules():
    global _BUILTIN_RULES
    if _BUILTIN_RULES is None:
        _BUILTIN_RULES = set(_registry._INFER_RULES)


_snapshot_builtin_rules()


def reset():
    """Drop infer rules registered by a test and zero the findings
    metric family — per-test isolation (conftest)."""
    from .findings import _m_findings
    for t in list(_registry._INFER_RULES):
        if t not in (_BUILTIN_RULES or ()):
            _registry.unregister_shape_infer(t)
    _m_findings.reset()
