"""Abstract shape/dtype inference over a whole Program.

The reference validated every program at build time through per-op
InferShape/InferVarType (PAPER.md §1: the framework layer's
compile-time contract).  This pass reproduces that capability over the
Program IR: symbolic shapes (-1 = dynamic batch dims) propagate
op-by-op through the global block, each op resolved by

  1. its registered infer rule (framework/registry.py
     register_shape_infer — the InferShape analogue), else
  2. generic abstract evaluation of the op's own lowering under
     jax.eval_shape (the layer_helper build-time trick: dynamic dims
     ride through as a prime sentinel), else
  3. "unknown shape" — unknown ops NEVER crash the pass.

Findings:
  * ``shape_mismatch`` (error): an infer rule proves the op's inputs
    incompatible, or the inferred output provably contradicts the
    shape the program declares for that var;
  * ``dtype_mismatch`` (warn): inferred vs declared element type
    disagree;
  * ``shape_infer_failed`` (error only under ``strict=True`` — the
    transpiler post-condition mode — else silent): the generic
    abstract eval of an op with fully-known input shapes raised,
    which on a transpiled program means a miscompiled consumer.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..core.dtypes import to_jnp_dtype
from ..framework.registry import LowerContext, get_op_def, get_shape_infer
from . import traversal
from .findings import ERROR, INFO, WARN, AnalysisResult, Finding
from .infer_rules import InferError

PASS = "shape_inference"

# prime sentinel for -1 dims during abstract eval (survives products
# through reshape/flatten; layer_helper.py uses the same trick)
_DYN = 97

# ops whose lowering cannot be abstractly evaluated outside the
# executor (they read ctx.env / need a mesh axis in scope / have
# executor-side semantics) AND have no rule: degrade to unknown even
# under strict mode
UNEVALUABLE_OPS = frozenset({
    "while", "conditional_block", "scan", "static_rnn_scan",
    "increment_loop_counter", "autodiff",
    "c_allgather", "c_reducescatter", "c_alltoall",
    "fused_attention", "moe_ffn",
})

ShapeDtype = Tuple[Optional[tuple], Optional[str]]


def _canon_dtype(dt) -> Optional[str]:
    if dt is None:
        return None
    try:
        return str(np.dtype(dt).name)
    except TypeError:
        return str(dt)          # bfloat16 & fp8: np.dtype handles via ml_dtypes


def _abstract(shape, dtype):
    shp = tuple(_DYN if d == -1 else int(d) for d in shape)
    return jax.ShapeDtypeStruct(shp, to_jnp_dtype(dtype))


def _from_abstract(sd, had_dyn: bool) -> ShapeDtype:
    shape = list(sd.shape)
    if had_dyn:
        shape = [-1 if s != 0 and s % _DYN == 0 else s for s in shape]
    return tuple(shape), _canon_dtype(sd.dtype)


def _fully_known(shape) -> bool:
    return shape is not None and all(int(d) != -1 for d in shape)


def _shapes_conflict(a, b) -> bool:
    """Both known, provably different (rank or a non-dynamic dim)."""
    if a is None or b is None:
        return False
    if len(a) != len(b):
        # a scalar () vs (1,) style rank drift is common benign
        # squeeze territory; only call rank conflicts when both sides
        # have real extent
        return bool(a) and bool(b)
    return any(x != -1 and y != -1 and int(x) != int(y)
               for x, y in zip(a, b))


def _generic_eval(opdef, ins_info: Dict[str, List[ShapeDtype]], attrs,
                  key) -> Optional[Dict[str, List[ShapeDtype]]]:
    """One jax.eval_shape of the op's lowering over abstract inputs.
    Returns None when any input is unknown; raises on lowering error."""
    flat, slots = [], []
    had_dyn = False
    for slot, infos in ins_info.items():
        for shape, dtype in infos:
            if shape is None or dtype is None:
                return None
            had_dyn = had_dyn or any(int(d) == -1 for d in shape)
            flat.append(_abstract(shape, dtype))
            slots.append(slot)

    def g(*arrs):
        d: Dict[str, List] = {}
        for slot, a in zip(slots, arrs):
            d.setdefault(slot, []).append(a)
        ctx = LowerContext(key)
        return {k: list(v) for k, v in opdef.lower(ctx, d, attrs).items()}

    out_abs = jax.eval_shape(g, *flat)
    return {slot: [_from_abstract(sd, had_dyn) for sd in sds]
            for slot, sds in out_abs.items()}


class ShapeInferencePass:
    """Propagate symbolic shapes through the global block, checking
    inferred against declared.  Sub-blocks keep their declared
    (build-time) metadata; the lint surface is block 0, where every
    transpiler rewrites."""

    name = PASS

    def run(self, program, result: AnalysisResult,
            feed_shapes: Optional[Dict[str, tuple]] = None,
            strict: bool = False) -> Dict[str, ShapeDtype]:
        result.passes_run.append(self.name)
        block = program.global_block()
        env: Dict[str, ShapeDtype] = {}
        # seed: feeds (runtime shapes when the executor knows them),
        # data vars and persistable state from declared metadata
        for name, var in block.vars.items():
            shape, dtype = traversal.declared_info(block, name)
            if var.is_data or var.persistable:
                env[name] = (shape, dtype)
        for name, shape in (feed_shapes or {}).items():
            _, dtype = traversal.declared_info(block, name)
            env[name] = (tuple(shape), dtype)

        key = jax.random.PRNGKey(0)
        for i, op in enumerate(block.ops):
            if op.type in traversal.STRUCTURAL_OPS:
                continue
            ins_info = {
                slot: [env.get(n) or traversal.declared_info(block, n)
                       for n in names]
                for slot, names in op.inputs.items()}
            outs = None
            rule = get_shape_infer(op.type)
            try:
                if rule is not None:
                    outs = rule(op, ins_info, op.attrs)
                if outs is None and op.type not in UNEVALUABLE_OPS:
                    outs = _generic_eval(get_op_def(op.type), ins_info,
                                         op.attrs, key)
            except InferError as e:
                result.add(Finding(
                    pass_name=self.name, code="shape_mismatch",
                    severity=ERROR, message=str(e), block_idx=block.idx,
                    op_index=i, op_type=op.type,
                    var_names=tuple(traversal.op_input_names(op)),
                    callsite=getattr(op, "callsite", None)))
                outs = None
            except Exception as e:      # generic abstract eval failed
                known = all(
                    info is not None and _fully_known(info[0])
                    for infos in ins_info.values() for info in infos)
                if strict and known:
                    result.add(Finding(
                        pass_name=self.name, code="shape_infer_failed",
                        severity=ERROR,
                        message=(f"abstract evaluation of {op.type!r} "
                                 f"failed on fully-known input shapes: "
                                 f"{str(e)[:300]}"),
                        block_idx=block.idx, op_index=i,
                        op_type=op.type,
                        var_names=tuple(traversal.op_input_names(op)),
                        callsite=getattr(op, "callsite", None)))
                outs = None

            if outs is None:
                result.unknown_shape_ops.append(op.type)
            for slot, names in op.outputs.items():
                inferred = (outs or {}).get(slot, [])
                for j, n in enumerate(names):
                    if not n:
                        continue
                    inf: ShapeDtype = (inferred[j] if j < len(inferred)
                                       else (None, None))
                    decl_shape, decl_dtype = traversal.declared_info(
                        block, n)
                    if _shapes_conflict(inf[0], decl_shape):
                        result.add(Finding(
                            pass_name=self.name, code="shape_mismatch",
                            severity=ERROR,
                            message=(f"op {op.type!r} produces "
                                     f"{_fmt(inf[0])} for var {n!r} but "
                                     f"the program declares "
                                     f"{_fmt(decl_shape)}"),
                            block_idx=block.idx, op_index=i,
                            op_type=op.type, var_names=(n,),
                            callsite=getattr(op, "callsite", None)))
                    elif (inf[1] is not None and decl_dtype is not None
                          and _canon_dtype(inf[1])
                          != _canon_dtype(decl_dtype)):
                        result.add(Finding(
                            pass_name=self.name, code="dtype_mismatch",
                            severity=WARN,
                            message=(f"op {op.type!r} produces "
                                     f"{inf[1]} for var {n!r} but the "
                                     f"program declares {decl_dtype}"),
                            block_idx=block.idx, op_index=i,
                            op_type=op.type, var_names=(n,),
                            callsite=getattr(op, "callsite", None)))
                    # prefer the propagated view; fall back to declared
                    env[n] = (inf[0] if inf[0] is not None
                              else decl_shape,
                              inf[1] if inf[1] is not None
                              else decl_dtype)
        return env


def _fmt(shape):
    return "?" if shape is None else list(shape)
