"""Per-op compile-time shape/dtype inference rules.

The reference gave every op a C++ InferShape (framework/
shape_inference.h, registered with REGISTER_OPERATOR); here the rules
register alongside the OpDef via framework/registry.py
``register_shape_infer``.  Only the op families whose mismatch
diagnostics matter get explicit rules — everything else is covered by
the generic abstract-eval fallback in shape_inference.py (one
jax.eval_shape of the op's own lowering, the layer_helper build-time
trick), and ops where neither applies degrade to "unknown shape".

Rule contract: ``rule(op, ins, attrs) -> {slot: [(shape, dtype)]}``
with shape a tuple (-1 = dynamic) or None, dtype a canonical string or
None; raise InferError on a provable mismatch; return None to defer to
the generic fallback.
"""
from __future__ import annotations

import numpy as np

from ..framework.registry import register_shape_infer


class InferError(Exception):
    """A provable compile-time shape/dtype mismatch."""


def _fmt(shape):
    return "?" if shape is None else list(shape)


def _prod_known(dims):
    """Product of dims; None when any dim is dynamic (-1)."""
    p = 1
    for d in dims:
        if d == -1:
            return None
        p *= int(d)
    return p


def _dims_conflict(a, b) -> bool:
    """Two dims provably differ (dynamic -1 matches anything)."""
    return a != -1 and b != -1 and int(a) != int(b)


def _first(ins, slot):
    vs = ins.get(slot) or [(None, None)]
    return vs[0]


# --- matmul family --------------------------------------------------------

@register_shape_infer("mul")
def _infer_mul(op, ins, attrs):
    (xs, xd) = _first(ins, "X")
    (ws, wd) = _first(ins, "Y")
    nc = int(attrs.get("x_num_col_dims", 1))
    if ws is not None and len(ws) != 2:
        raise InferError(
            f"mul weight {op.inputs.get('Y', ['?'])[0]!r} must be 2-D, "
            f"got {_fmt(ws)}")
    if xs is None or ws is None:
        out = None
        if xs is not None:
            out = tuple(xs[:nc]) + (-1,)
        return {"Out": [(out, xd)]}
    k = _prod_known(xs[nc:])
    if k is not None and _dims_conflict(k, ws[0]):
        raise InferError(
            f"mul contraction mismatch: X {op.inputs['X'][0]!r} "
            f"{_fmt(xs)} flattens to [.., {k}] at x_num_col_dims={nc} "
            f"but W {op.inputs['Y'][0]!r} is {_fmt(ws)} "
            f"(expects leading dim {k})")
    return {"Out": [(tuple(xs[:nc]) + (ws[1],), xd)]}


@register_shape_infer("matmul")
def _infer_matmul(op, ins, attrs):
    (xs, xd) = _first(ins, "X")
    (ys, yd) = _first(ins, "Y")
    if xs is None or ys is None or len(xs) < 1 or len(ys) < 1:
        return {"Out": [(None, xd or yd)]}
    tx = bool(attrs.get("transpose_X", False))
    ty = bool(attrs.get("transpose_Y", False))
    if len(xs) == 1 or len(ys) == 1:
        return None                 # vector cases: defer to generic
    xk = xs[-2] if tx else xs[-1]
    xm = xs[-1] if tx else xs[-2]
    yk = ys[-1] if ty else ys[-2]
    yn = ys[-2] if ty else ys[-1]
    if _dims_conflict(xk, yk):
        raise InferError(
            f"matmul contraction mismatch: X {op.inputs['X'][0]!r} "
            f"{_fmt(xs)} (contract dim {xk}) vs Y "
            f"{op.inputs['Y'][0]!r} {_fmt(ys)} (contract dim {yk})"
            + (" with transpose attrs" if (tx or ty) else ""))
    batch_x, batch_y = xs[:-2], ys[:-2]
    for a, b in zip(reversed(batch_x), reversed(batch_y)):
        if _dims_conflict(a, b) and 1 not in (a, b):
            raise InferError(
                f"matmul batch dims incompatible: {_fmt(xs)} vs "
                f"{_fmt(ys)}")
    # numpy-style broadcast, aligned from the right: size-1 dims defer
    # to the other side, dynamic (-1) defers to a concrete non-1 dim
    batch = []
    for i in range(max(len(batch_x), len(batch_y))):
        a = batch_x[-1 - i] if i < len(batch_x) else 1
        b = batch_y[-1 - i] if i < len(batch_y) else 1
        if a == b:
            batch.append(a)
        elif a == 1:
            batch.append(b)
        elif b == 1:
            batch.append(a)
        else:                   # one side is -1 (conflicts raised above)
            batch.append(a if b == -1 else b)
    batch.reverse()
    return {"Out": [(tuple(batch) + (xm, yn), xd or yd)]}


@register_shape_infer("lookup_table")
def _infer_lookup_table(op, ins, attrs):
    (ids, idt) = _first(ins, "Ids")
    (ws, wd) = _first(ins, "W")
    if idt is not None and not np.issubdtype(np.dtype(idt), np.integer):
        raise InferError(
            f"lookup_table ids {op.inputs['Ids'][0]!r} must be integer, "
            f"got {idt}")
    if ws is not None and len(ws) != 2:
        raise InferError(
            f"lookup_table table {op.inputs['W'][0]!r} must be 2-D "
            f"[vocab, dim], got {_fmt(ws)}")
    if ids is None or ws is None:
        return {"Out": [(None, wd)]}
    base = ids[:-1] if (len(ids) >= 2 and ids[-1] == 1) else ids
    return {"Out": [(tuple(base) + (ws[1],), wd)]}


# --- sparse plane (paddle_tpu/sparse; ops/nn_ops.py) ----------------------

@register_shape_infer("sparse_embedding_lookup")
def _infer_sparse_embedding_lookup(op, ins, attrs):
    """lookup_table's contract plus hash bucketing: ids may exceed the
    vocab when hash_bucket is on, so only type/rank are checkable."""
    (ids, idt) = _first(ins, "Ids")
    (ws, wd) = _first(ins, "W")
    if idt is not None and not np.issubdtype(np.dtype(idt), np.integer):
        raise InferError(
            f"sparse_embedding_lookup ids {op.inputs['Ids'][0]!r} must "
            f"be integer, got {idt}")
    if ws is not None and len(ws) != 2:
        raise InferError(
            f"sparse_embedding_lookup table {op.inputs['W'][0]!r} must "
            f"be 2-D [buckets, dim], got {_fmt(ws)}")
    if ids is None or ws is None:
        return {"Out": [(None, wd)]}
    base = ids[:-1] if (len(ids) >= 2 and ids[-1] == 1) else ids
    return {"Out": [(tuple(base) + (ws[1],), wd)]}


@register_shape_infer("sparse_scatter_update")
def _infer_sparse_scatter_update(op, ins, attrs):
    """Out mirrors W (the scatter is in-place-shaped); Grad's trailing
    dim must match the table dim — the scatter-add-vs-overwrite bug
    class surfaces as silently wrong numerics, but a transposed grad
    surfaces HERE."""
    (ws, wd) = _first(ins, "W")
    (ids, idt) = _first(ins, "Ids")
    (gs, gd) = _first(ins, "Grad")
    if idt is not None and not np.issubdtype(np.dtype(idt), np.integer):
        raise InferError(
            f"sparse_scatter_update ids {op.inputs['Ids'][0]!r} must "
            f"be integer, got {idt}")
    if ws is not None and len(ws) != 2:
        raise InferError(
            f"sparse_scatter_update table {op.inputs['W'][0]!r} must "
            f"be 2-D [rows, dim], got {_fmt(ws)}")
    if ws is not None and gs is not None and len(gs) >= 1:
        # trailing dims must agree when both are concrete
        if gs[-1] not in (-1, ws[1]) and ws[1] != -1:
            raise InferError(
                f"sparse_scatter_update grad {op.inputs['Grad'][0]!r} "
                f"trailing dim {gs[-1]} != table dim {ws[1]}")
    return {"Out": [(ws, wd)]}


# --- structural / executor-interpreted ops -------------------------------

@register_shape_infer("autodiff")
def _infer_autodiff(op, ins, attrs):
    """Grads mirror Params exactly (the vjp contract)."""
    params = ins.get("Params", [])
    return {"Grads": [(s, d) for (s, d) in params]}


def _identity_rule(slot_in="X", slot_out="Out"):
    def rule(op, ins, attrs):
        return {slot_out: [(s, d) for (s, d) in ins.get(slot_in, [])]}
    return rule


# collectives are shape-preserving for the allreduce/broadcast family;
# their lowerings need a mesh axis in scope so the generic abstract
# eval cannot run them
for _t in ("c_allreduce_sum", "c_allreduce_max", "c_allreduce_mean",
           "c_broadcast", "c_ppermute", "c_sync_calc_stream"):
    register_shape_infer(_t)(_identity_rule())

# pipeline stage cut: identity over its (possibly multi-var) payload
register_shape_infer("pipeline_boundary")(_identity_rule())


# --- fused / quantized consumers -----------------------------------------

@register_shape_infer("fused_transformer_block")
def _infer_fused_block(op, ins, attrs):
    (xs, xd) = _first(ins, "X")
    (w1s, _) = _first(ins, "W1")
    (w2s, _) = _first(ins, "W2")
    if (w1s is not None and w2s is not None
            and _dims_conflict(w1s[-1], w2s[0])):
        raise InferError(
            f"fused_transformer_block MLP mismatch: W1 {_fmt(w1s)} vs "
            f"W2 {_fmt(w2s)}")
    if xs is not None and w1s is not None \
            and _dims_conflict(xs[-1], w1s[0]):
        raise InferError(
            f"fused_transformer_block width mismatch: X {_fmt(xs)} "
            f"model dim {xs[-1]} vs W1 {_fmt(w1s)}")
    return {"Out": [(xs, xd)]}


@register_shape_infer("quantized_matmul")
def _infer_quantized_matmul(op, ins, attrs):
    (xs, xd) = _first(ins, "X")
    (ws, _) = _first(ins, "W")
    nc = int(attrs.get("x_num_col_dims", 1))
    if xs is None or ws is None:
        return {"Out": [(None, "float32")]}
    k = _prod_known(xs[nc:])
    if k is not None and len(ws) == 2 and _dims_conflict(k, ws[0]):
        raise InferError(
            f"quantized_matmul contraction mismatch: X {_fmt(xs)} "
            f"flattens to [.., {k}], W {op.inputs['W'][0]!r} is "
            f"{_fmt(ws)}")
    return {"Out": [(tuple(xs[:nc]) + (ws[1],), "float32")]}


@register_shape_infer("quantized_conv2d")
def _infer_quantized_conv2d(op, ins, attrs):
    (xs, _) = _first(ins, "Input")
    (fs, _) = _first(ins, "Filter")
    if xs is not None and fs is not None and len(xs) == 4 \
            and len(fs) == 4:
        groups = int(attrs.get("groups", 1) or 1)
        if _dims_conflict(xs[1], fs[1] * groups):
            raise InferError(
                f"quantized_conv2d channel mismatch: Input {_fmt(xs)} "
                f"C={xs[1]} vs Filter {_fmt(fs)} "
                f"(expects C={fs[1] * groups})")
    return {"Output": [(None, "float32")]}
