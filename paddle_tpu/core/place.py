"""Places and device meshes.

Capability parity with the reference's Place variant
(/root/reference/paddle/fluid/platform/place.h: CPUPlace / CUDAPlace /
CUDAPinnedPlace) and DeviceContextPool (platform/device_context.h:319).

TPU-first design: a Place resolves to one jax.Device for single-device
execution, and MeshPlace wraps a jax.sharding.Mesh for SPMD execution — the
reference's ParallelExecutor places-list becomes a named mesh.  There is no
per-device stream/handle bundle to manage; XLA owns scheduling.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np


class Place:
    """Base device tag."""

    device_kind: str = "any"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self) -> jax.Device:
        devs = self._platform_devices()
        if self.device_id >= len(devs):
            raise ValueError(
                f"{self!r}: only {len(devs)} device(s) of kind "
                f"{self.device_kind!r} visible")
        return devs[self.device_id]

    def _platform_devices(self):
        if self.device_kind == "any":
            return jax.devices()
        try:
            return jax.devices(self.device_kind)
        except RuntimeError:
            return jax.devices()


class CPUPlace(Place):
    device_kind = "cpu"


class TPUPlace(Place):
    """The accelerator place (ref CUDAPlace -> TPU).  Falls back to the default
    jax backend when no TPU platform is present (e.g. CPU test meshes)."""
    device_kind = "tpu"

    def _platform_devices(self):
        for kind in ("tpu", "axon"):
            try:
                devs = jax.devices(kind)
                if devs:
                    return devs
            except RuntimeError:
                continue
        return jax.devices()


# Alias so scripts written against the reference's spelling still read well.
CUDAPlace = TPUPlace


def default_place() -> Place:
    """Accelerator if present, else CPU."""
    try:
        d = jax.devices()[0]
    except RuntimeError:
        return CPUPlace(0)
    if d.platform in ("tpu", "axon"):
        return TPUPlace(0)
    return CPUPlace(0)


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices: Optional[Sequence[jax.Device]] = None) -> jax.sharding.Mesh:
    """Build a device mesh.  Replaces the reference's places-list +
    NCCLContextMap (platform/nccl_helper.h:83): collectives ride ICI within a
    mesh axis instead of NCCL rings."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} needs {n} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return jax.sharding.Mesh(arr, tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None) -> jax.sharding.Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return make_mesh((n,), ("data",), devs)
