"""Profiling / tracing plane.

Capability parity with the reference's RecordEvent/RecordBlock RAII markers and
EnableProfiler/DisableProfiler (platform/profiler.h:72,99,117,122) plus the
CUPTI DeviceTracer -> chrome trace path (platform/device_tracer.cc:41,
tools/timeline.py).

TPU-native: host-side scoping uses jax.profiler.TraceAnnotation (shows up in
XPlane/TensorBoard and perfetto, the chrome://tracing successor); whole-profile
capture uses jax.profiler.start_trace/stop_trace.  Host-event recording rides
the unified trace buffer (observability/trace.py), so `export_chrome_trace`
emits ONE merged timeline: these RecordEvent scopes plus executor op/step
spans and trainer markers.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax

from ..observability import trace as _trace


class RecordEvent:
    """Context manager marking a named host scope (ref profiler.h:99)."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        _trace.add_span(self.name, self._t0,
                        time.perf_counter() - self._t0,
                        tid=_trace.HOST_TID, cat="host")
        return False


RecordBlock = RecordEvent  # ref profiler.h:117 — same capability on host side


def reset_profiler():
    _trace.reset()


def enable_profiler(trace_dir: Optional[str] = None):
    _trace.enable()
    if trace_dir:
        jax.profiler.start_trace(trace_dir)


def disable_profiler(sorted_key: str = "total", trace_dir_used: bool = False):
    _trace.disable()
    if trace_dir_used:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def profiler(trace_dir: Optional[str] = None, print_summary: bool = True):
    """`with profiler.profiler(): ...` — ref python/paddle/fluid/profiler.py."""
    enable_profiler(trace_dir)
    try:
        yield
    finally:
        disable_profiler(trace_dir_used=trace_dir is not None)
        if print_summary:
            print(summary())


def summary() -> str:
    agg: Dict[str, List[float]] = defaultdict(list)
    for e in _trace.events():
        if e["ph"] == "X":
            agg[e["name"]].append(e["dur"])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, durs in sorted(agg.items(), key=lambda kv: -sum(kv[1])):
        lines.append(f"{name:<40}{len(durs):>8}{sum(durs)*1e3:>12.3f}"
                     f"{sum(durs)/len(durs)*1e3:>12.3f}")
    return "\n".join(lines)


def export_chrome_trace(path: str):
    """Dump the UNIFIED timeline — host scopes, executor op/step spans,
    trainer markers — as chrome://tracing JSON (ref tools/timeline.py)."""
    return _trace.export_chrome_trace(path)
