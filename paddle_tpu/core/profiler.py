"""Profiling / tracing plane.

Capability parity with the reference's RecordEvent/RecordBlock RAII markers and
EnableProfiler/DisableProfiler (platform/profiler.h:72,99,117,122) plus the
CUPTI DeviceTracer -> chrome trace path (platform/device_tracer.cc:41,
tools/timeline.py).

TPU-native: host-side scoping uses jax.profiler.TraceAnnotation (shows up in
XPlane/TensorBoard and perfetto, the chrome://tracing successor); whole-profile
capture uses jax.profiler.start_trace/stop_trace.  A lightweight host-event
recorder is kept for environments without the profiler plugin so
`profiler.profiler()` always yields usable per-scope wall timings.
"""
from __future__ import annotations

import contextlib
import json
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax

_events: List[dict] = []
_enabled = False


class RecordEvent:
    """Context manager marking a named host scope (ref profiler.h:99)."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        if _enabled:
            _events.append({
                "name": self.name,
                "ts": self._t0,
                "dur": time.perf_counter() - self._t0,
            })
        return False


RecordBlock = RecordEvent  # ref profiler.h:117 — same capability on host side


def reset_profiler():
    _events.clear()


def enable_profiler(trace_dir: Optional[str] = None):
    global _enabled
    _enabled = True
    if trace_dir:
        jax.profiler.start_trace(trace_dir)


def disable_profiler(sorted_key: str = "total", trace_dir_used: bool = False):
    global _enabled
    _enabled = False
    if trace_dir_used:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def profiler(trace_dir: Optional[str] = None, print_summary: bool = True):
    """`with profiler.profiler(): ...` — ref python/paddle/fluid/profiler.py."""
    enable_profiler(trace_dir)
    try:
        yield
    finally:
        disable_profiler(trace_dir_used=trace_dir is not None)
        if print_summary:
            print(summary())


def summary() -> str:
    agg: Dict[str, List[float]] = defaultdict(list)
    for e in _events:
        agg[e["name"]].append(e["dur"])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, durs in sorted(agg.items(), key=lambda kv: -sum(kv[1])):
        lines.append(f"{name:<40}{len(durs):>8}{sum(durs)*1e3:>12.3f}"
                     f"{sum(durs)/len(durs)*1e3:>12.3f}")
    return "\n".join(lines)


def export_chrome_trace(path: str):
    """Dump host events as chrome://tracing JSON (ref tools/timeline.py)."""
    trace = {"traceEvents": [
        {"name": e["name"], "ph": "X", "pid": 0, "tid": 0,
         "ts": e["ts"] * 1e6, "dur": e["dur"] * 1e6}
        for e in _events]}
    with open(path, "w") as f:
        json.dump(trace, f)
