"""Error-reporting plane.

Equivalent capability to the reference's PADDLE_ENFORCE macro family
(/root/reference/paddle/fluid/platform/enforce.h): rich errors carrying the
failing condition and user message.  Python exceptions already carry stack
traces, so this is a thin layer providing uniform error types.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Raised when an internal framework invariant is violated."""


class InvalidArgumentError(ValueError):
    """Raised when user-provided arguments are invalid (shape/dtype/attr)."""


def enforce(cond, msg: str = "", *args):
    if not cond:
        raise EnforceNotMet(msg % args if args else msg)


def enforce_eq(a, b, msg: str = ""):
    if a != b:
        raise EnforceNotMet(f"Expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg: str = ""):
    if not a > b:
        raise EnforceNotMet(f"Expected {a!r} > {b!r}. {msg}")


def check_arg(cond, msg: str = ""):
    if not cond:
        raise InvalidArgumentError(msg)
