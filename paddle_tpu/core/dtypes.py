"""Dtype plane for the framework.

Capability parity with the reference's VarType dtype enum
(/root/reference/paddle/fluid/framework/framework.proto:105) and the software
float16 type (platform/float16.h).  On TPU the native low-precision type is
bfloat16 (MXU-preferred), so bf16 is first-class here rather than fp16.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical names -> jnp dtypes.  These are the dtypes kernels may be
# registered for; mirrors VarType.Type minus the LoD/reader plumbing types.
_DTYPE_MAP = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
}
# fp8 storage types (quantized-execution plane); availability depends on
# the jax/ml_dtypes build, so register only what exists
for _f8 in ("float8_e4m3fn", "float8_e5m2"):
    if hasattr(jnp, _f8):
        _DTYPE_MAP[_f8] = getattr(jnp, _f8)

_CANONICAL = {np.dtype(v).name: k for k, v in _DTYPE_MAP.items()}
_CANONICAL["bfloat16"] = "bfloat16"


def convert_dtype(dtype) -> str:
    """Normalise any dtype spelling (str, np.dtype, jnp dtype) to a canonical
    framework name such as ``'float32'``."""
    if isinstance(dtype, str):
        if dtype in _DTYPE_MAP:
            return dtype
        # numpy-style spellings
        name = np.dtype(dtype).name if dtype != "bfloat16" else "bfloat16"
        if name in _CANONICAL:
            return _CANONICAL[name]
        raise ValueError(f"Unsupported dtype: {dtype!r}")
    if dtype in (jnp.bfloat16,) or getattr(dtype, "name", "") == "bfloat16":
        return "bfloat16"
    name = np.dtype(dtype).name
    if name not in _CANONICAL:
        raise ValueError(f"Unsupported dtype: {dtype!r}")
    return _CANONICAL[name]


# 64-bit names lowered on the x32 plane (TPUs have no i64/f64 compute)
_X32_LOWER = {"int64": "int32", "float64": "float32"}


def to_jnp_dtype(dtype):
    """Framework/any dtype -> jnp dtype object, honoring the x32 plane:
    when jax runs without 64-bit enabled (the default), 64-bit requests
    lower to their 32-bit counterparts HERE rather than letting every
    jnp call emit its "requested dtype int64 ... truncated to int32"
    UserWarning — the end dtype is identical, the warning noise is not
    (round-3 Weak #8)."""
    name = convert_dtype(dtype)
    import jax
    if not jax.config.jax_enable_x64:
        name = _X32_LOWER.get(name, name)
    return _DTYPE_MAP[name]


def is_floating(dtype) -> bool:
    return convert_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in ("int8", "uint8", "int16", "int32", "int64")


def index_dtype():
    """The widest integer dtype jax will actually materialize: int64 when
    x64 is enabled, else int32.  Ops whose reference contract says int64
    use this to avoid per-call truncation warnings under 32-bit mode
    (the value range of indices/shapes here always fits int32)."""
    import jax
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
