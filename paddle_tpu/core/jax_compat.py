"""Version compatibility shims for the jax APIs this repo leans on.

jax moves surfaces between releases faster than this codebase re-pins:
``shard_map`` graduated from ``jax.experimental.shard_map`` to the
``jax`` namespace (>= 0.8) and renamed ``check_rep`` -> ``check_vma``
on the way; ``jax.lax.axis_size`` exists in some builds and not in
others (this image's 0.4.37 has neither).  Every call site that used
to guess inline goes through this module instead, so the next drift is
one fix, not a grep across the parallel planes (the 28 tier-1 failures
ROADMAP item 2 calls out came from exactly that).
"""
from __future__ import annotations

import jax
from jax import lax


def axis_size(axis_name) -> int:
    """Size of a named mesh axis, inside shard_map/pmap scope.

    ``jax.lax.axis_size`` where the build has it; otherwise
    ``lax.psum(1, axis_name)`` — jax special-cases a non-tracer operand
    and returns the concrete axis size without binding a collective, so
    the result is a plain int usable in Python control flow (ppermute
    permutation tables, stage counts)."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, check_rep=None):
    """``jax.shard_map`` (>= 0.8) / ``jax.experimental.shard_map``
    (older builds), absorbing the ``check_rep`` -> ``check_vma``
    rename.  ``check_rep=None`` keeps the build's default."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_rep is None:
        return sm(f, **kwargs)
    try:
        return sm(f, check_vma=check_rep, **kwargs)
    except TypeError:
        return sm(f, check_rep=check_rep, **kwargs)
