"""Process-wide config plane.

Equivalent capability to the reference's gflags plane (96 DEFINE_* flags across
fluid; whitelisted env exposure at python/paddle/fluid/__init__.py:95-152).
Flags are declared with defaults, overridable via ``PTPU_<NAME>`` environment
variables at import, and mutable at runtime via set_flag/get_flag.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_FLAGS: Dict[str, Any] = {}


def define_flag(name: str, default: Any, help_str: str = ""):
    env_name = "PTPU_" + name.upper()
    env = os.environ.get(env_name)
    value = default
    if env is not None:
        try:
            if isinstance(default, bool):
                value = env.lower() in ("1", "true", "yes")
            elif isinstance(default, int):
                value = int(env)
            elif isinstance(default, float):
                value = float(env)
            else:
                value = env
        except ValueError as e:
            # a bare ValueError at import time names neither the flag nor
            # the environment variable; wrap it so the operator can find
            # the offending setting
            raise ValueError(
                f"malformed value for flag {name!r}: {env_name}={env!r} "
                f"is not a valid {type(default).__name__} ({e})") from e
    _FLAGS[name] = value


def get_flag(name: str) -> Any:
    return _FLAGS[name]


def set_flag(name: str, value: Any):
    if name not in _FLAGS:
        raise KeyError(f"Unknown flag {name!r}")
    _FLAGS[name] = value


def all_flags() -> Dict[str, Any]:
    return dict(_FLAGS)


# --- Flag registry (mirrors the reference's whitelisted knobs where they ---
# --- still make sense on TPU)                                            ---
define_flag("check_nan_inf", False,
            "Scan every fetched value for NaN/Inf (ref FLAGS_check_nan_inf).")
define_flag("check_nan_inf_per_op", False,
            "Debug mode: run the program eagerly (un-jitted) and scan "
            "every op's outputs, naming the first op that produces "
            "NaN/Inf (the reference's per-op scan, operator.cc:829). "
            "Slow; for localization only.")
define_flag("deterministic", False,
            "Force deterministic reductions/samplers "
            "(ref FLAGS_cpu_deterministic/cudnn_deterministic).")
define_flag("use_pallas_kernels", True,
            "Use hand-written Pallas TPU kernels for hot ops when available.")
define_flag("default_dtype", "float32", "Default parameter dtype.")
define_flag("matmul_precision", "default",
            "jax matmul precision: default|high|highest.")
define_flag("executor_log_compiles", False,
            "Log every program (re)compilation in the executor.")
define_flag("profile_ops", False,
            "Run programs eagerly (un-jitted) and record per-op wall "
            "timings into the executor_op_seconds histogram and the "
            "trace buffer (observability/trace.py).  Slow; the "
            "interpreted-mode analogue of the reference's per-op "
            "profiler (platform/profiler.h RecordEvent per kernel).")
define_flag("recompile_warn_threshold", 5,
            "Warn once when the same (program, fetch-list) key has "
            "compiled more than this many distinct executables — a "
            "recompile storm, usually drifting feed shapes/dtypes. "
            "0 disables the check.")
define_flag("rng_seed", 0, "Global RNG seed used when a program has no seed.")
define_flag("amp_bf16", False,
            "Mixed precision: f32 matmul/conv/attention inputs enter the "
            "MXU as bfloat16 (f32 accumulation, f32 master params) — the "
            "capability of the reference's float16 transpiler "
            "(contrib/float16), applied at lowering time.")
define_flag("quantize_dtype", "",
            "Real low-precision matmul execution (ops/quantize_ops.py): "
            "'' = off; 'int8' = dynamic-scale int8 x int8 -> int32 "
            "dot_general; 'e4m3'/'e5m2' = fp8 matmul with f32 "
            "accumulation.  Applies to the mul/matmul/bmm op family at "
            "lowering time with straight-through (bf16) gradients — the "
            "training-side twin of QuantizeTranspiler.freeze_program, "
            "which emits genuinely quantized programs regardless of "
            "this flag.  Part of the executor's compile key: toggling "
            "it recompiles instead of aliasing executables.")
define_flag("fuse_block", False,
            "Fuse whole transformer blocks (LN -> attention -> residual "
            "-> LN -> MLP -> residual) into single fused_transformer_"
            "block ops via transpiler/fused_block.py pattern matching; "
            "the op lowers to the Pallas VMEM-resident block kernel "
            "(kernels/fused_block.py) on TPU and to an equivalent XLA "
            "composition elsewhere.  Part of the executor's compile "
            "key.")
define_flag("verify_program", "warn",
            "Static program verification before the executor compiles "
            "a (program, feed, fetch) key (paddle_tpu/analysis): "
            "'off' = pre-PR behavior, byte-identical compile keys and "
            "outputs; 'warn' (default) = run the O(ops) dataflow + "
            "hazard lints on every cache miss and emit ONE "
            "RuntimeWarning per (program, fetch-list) key with "
            "error-severity findings; 'error' = also run abstract "
            "shape inference and REJECT the program "
            "(ProgramVerificationError, nothing compiles, "
            "executor_compile_total unchanged) — the mode tests/CI "
            "run.")
define_flag("prefetch_depth", 0,
            "Trainer input pipeline: number of feed batches the "
            "device-prefetch wrapper (reader.device_prefetch) stages on "
            "device AHEAD of the training step (double buffering = 2). "
            "0 disables; feed build + host->device copy then happen "
            "synchronously inside the step's data wait.")

define_flag("jit_cache_dir", "",
            "Persistent executable cache (framework/jit_cache.py): "
            "directory where compiled executables are serialized "
            "(jax.experimental.serialize_executable) keyed by a stable "
            "content hash (program topology, feed shapes/dtypes, fetch "
            "names, state signature, numerics flags, jax/jaxlib/"
            "backend identity), so a restarted process deserializes "
            "its executables instead of recompiling — the Executor "
            "step + run_steps loops, the Predictor AOT grid, and the "
            "serving prefill-grid/decode step all ride it.  '' = off: "
            "byte-identical pre-cache behavior (compile keys, outputs, "
            "explain() reports).  Safe to share across a fleet: writes "
            "are atomic-rename, corrupt/stale entries recompile with a "
            "loud warning (jit_cache_errors_total), never a failed "
            "start.")
define_flag("jit_cache_limit_bytes", 2_000_000_000,
            "Byte budget for the persistent executable cache dir; the "
            "LRU GC (oldest mtime first; hits touch mtime) runs after "
            "every store and via the jit_cache CLI --gc.  0 = "
            "unlimited.")

# --- compiled-program introspection (observability/: costmodel, flight) ----
define_flag("cost_model", True,
            "Allow the XLA cost model (observability/costmodel.py) to "
            "analyze compiled programs: per-program FLOPs / bytes / "
            "peak-HBM gauges, Executor.explain reports and the trainer "
            "MFU gauge.  Analysis is lazy (first request per program) "
            "and costs one extra AOT lower+compile of that program.")
define_flag("device_peak_flops", 0.0,
            "Per-device peak FLOP/s used for MFU gauges.  0 = "
            "auto-detect (TPU: 197e12 bf16 v5e peak; other backends "
            "have no peak and MFU is not exported).")
define_flag("flight_recorder_path", "",
            "Where the flight recorder (observability/flight.py) writes "
            "its JSON diagnostic bundle on NumericGuard trips, retry "
            "exhaustion, preemption and uncaught trainer exceptions. "
            "Empty: the bundle is still built and kept in memory "
            "(flight.last_bundle()), but no file is written.")
define_flag("flight_recorder_events", 256,
            "Ring-buffer capacity of the always-on flight recorder "
            "(recent spans, compile/chaos/guard/retry events). "
            "0 disables event recording entirely.")

# --- model-health telemetry (observability/: tensorstats, runlog) ----------
define_flag("tensor_stats", False,
            "Compute per-variable tensor statistics (min/max/mean/rms, "
            "NaN/Inf counts, grad norms, weight-update ratios) INSIDE "
            "the compiled train step (observability/tensorstats.py) and "
            "fetch them as one packed array every tensor_stats_interval "
            "steps.  Off: zero extra compiles, byte-identical compile "
            "keys.  On: exactly one extra executable (the stats "
            "variant); flips diagnose as 'flags' drift in forensics.")
define_flag("tensor_stats_interval", 10,
            "Sample every Nth train-program step when tensor_stats is "
            "on (1 = every step — what first-bad-layer NaN attribution "
            "wants while debugging; larger = cheaper).")
define_flag("tensor_stats_topk", 8,
            "Bounded gauge cardinality: how many per-variable series "
            "(largest grad norms / update ratios / NaN counts) the "
            "model_* gauges keep per sample, next to the '__all__' "
            "aggregate row.")
define_flag("runlog_path", "",
            "Append-only JSONL run history (observability/runlog.py, "
            "schema paddle_tpu.runlog.v1): the Trainer writes one "
            "record per step (loss, lr, throughput, MFU, guard "
            "verdicts, sampled tensor stats).  A pre-existing file is "
            "atomically rotated to <path>.1 when a new Trainer opens "
            "it.  Empty disables.")
define_flag("grad_divergence_factor", 10.0,
            "FleetAggregator cross-rank divergence check: warn when "
            "same-step per-rank global grad norms (shipped by "
            "FleetReporter from tensorstats samples) differ by more "
            "than this factor under data parallelism — a desynced "
            "rank.  <= 1 disables.")

# --- fleet telemetry (observability/: server, fleet) -----------------------
define_flag("alert_rules_path", "",
            "Watchtower alert rules (observability/alerts.py): path "
            "to a JSON rules file loaded ON TOP of the built-in "
            "default set, or the sentinel 'builtin' for the defaults "
            "alone.  Empty disables alerting entirely (no engine, no "
            "ticker thread, byte-identical outputs).")
define_flag("alert_eval_interval", 1.0,
            "Seconds between background alert-rule evaluations (the "
            "ticker the pending->firing 'for:' holds are measured "
            "against); /alerts scrapes also evaluate.")
define_flag("healthz_stall_seconds", 60.0,
            "How long a RUNNING trainer may go without completing a "
            "step before /healthz reads it as hung (503) — was a "
            "hardcoded 60s; miniature soaks want it small and "
            "slow-step training wants it large.  The Watchtower "
            "stalled_rank default alert rule (observability/alerts.py) "
            "shares this knob: a rank silent past it alerts on the "
            "coordinator.")
define_flag("obs_http_port", 0,
            "Port for the live observability HTTP endpoint "
            "(observability/server.py): /metrics (Prometheus text), "
            "/metrics.json, /healthz, /flight.  0 disables the server; "
            "the Trainer starts it on first train() when set.")
define_flag("obs_http_host", "127.0.0.1",
            "Bind address for the observability HTTP endpoint.  The "
            "loopback default keeps metrics host-private; set 0.0.0.0 "
            "(or a NIC address) so remote operators / a Prometheus "
            "scraper can reach the port.")
define_flag("fleet_report_interval", 2.0,
            "Seconds between FleetReporter pushes of this worker's "
            "metric snapshot (and new trace spans / flight bundles) to "
            "the coordinator's FleetAggregator.  A worker is considered "
            "stale after 3x this interval without a report.")
define_flag("straggler_factor", 2.0,
            "FleetAggregator straggler threshold: warn when a rank's "
            "completed-step count falls behind the fleet median by more "
            "than this factor (median / factor).  <= 1 disables the "
            "check.")
define_flag("input_bound_warn_fraction", 0.5,
            "Trainer input-bound warning: warn once per train() when "
            "the cumulative data-wait time (reader next + feed build) "
            "exceeds this fraction of total step time.  0 disables.")

# --- perf attribution (observability/perfscope.py) -------------------------
define_flag("perfscope", False,
            "Performance-attribution engine (observability/"
            "perfscope.py): joins the cost model's FLOPs/bytes with "
            "measured dispatch time into a roofline verdict (achieved "
            "FLOP/s, arithmetic intensity, bound classification "
            "compute|memory|comms|input|host), accounts exposed "
            "collective time from the jaxpr's collective:* named "
            "scopes (perf_comm_exposed_seconds / perf_bubble_fraction "
            "gauges), and runs the rolling per-phase step-time "
            "regression watch behind the built-in perf_regression "
            "Watchtower rule.  Off: byte-identical outputs, compile "
            "keys and explain() reports — zero extra compiles either "
            "way (the comm model is a jaxpr trace, not an XLA "
            "compile).")
define_flag("perf_regression_factor", 2.0,
            "Regression-watch trip point: a phase's rolling step-time "
            "median exceeding its frozen baseline median by this "
            "factor marks the phase regressed (perf_regression_ratio "
            "gauge; the built-in perf_regression alert fires at this "
            "same bar).  <= 1 disables the watch.")
define_flag("perf_baseline_window", 32,
            "Samples per phase the regression watch keeps: the FIRST "
            "window freezes as the baseline, the newest window is the "
            "rolling median compared against it.")
define_flag("perf_hbm_gbps", 0.0,
            "Per-device HBM bandwidth (GB/s) for roofline ridge "
            "points.  0 = auto: TPU uses the v5e ~819 GB/s figure; "
            "other backends fall back to a documented 100 GB/s CPU "
            "prior so classification stays deterministic in tests.")
define_flag("perf_ici_gbps", 0.0,
            "Per-link interconnect bandwidth (GB/s) used to cost "
            "collective bytes in the comm model.  0 = auto: TPU uses "
            "a ~45 GB/s ICI figure; other backends fall back to a "
            "documented 10 GB/s prior.")

# --- memory attribution (observability/memscope.py) ------------------------
define_flag("memscope", False,
            "Live-HBM attribution engine (observability/memscope.py): "
            "census over jax.live_arrays() + device memory_stats "
            "attributing resident bytes per owner plane (params, "
            "optimizer state, serving KV slabs, sparse tables, "
            "jit-cache executables, feeds) into mem_resident_bytes"
            "{plane}; per-program predicted-vs-measured peak "
            "reconciliation (mem_peak_ratio); KV-cache occupancy "
            "accounting (serving_kv_*); OOM forensics at the "
            "memory.alloc chaos site and the built-in hbm_pressure "
            "Watchtower rule.  Off: byte-identical outputs and "
            "compile keys, zero step-path work.")
define_flag("memscope_interval", 0.0,
            "Census ticker period in seconds: > 0 starts one bounded "
            "daemon thread sampling the census between step/dispatch "
            "boundaries.  0 (default) samples only at boundaries.")
define_flag("memscope_topk", 8,
            "Top-N fattest live buffers kept in the census doc, the "
            "OOM flight bundle, and the CLI report.")
define_flag("memscope_pressure_fraction", 0.9,
            "hbm_pressure trip point: the built-in alert fires when "
            "mem_pressure_fraction (used/limit, max over devices) "
            "holds at or above this value.  <= 0 disables the rule.")
define_flag("memscope_hbm_limit_bytes", 0,
            "Device memory budget used for the pressure fraction.  "
            "0 = auto from Device.memory_stats()['bytes_limit'] (TPU); "
            "backends without allocator stats (CPU) report no "
            "pressure unless this is set explicitly.")
define_flag("memscope_ratio_factor", 8.0,
            "Predicted-vs-measured acceptance band: a program's "
            "mem_peak_ratio (measured high-water / cost-model "
            "peak_hbm_bytes) gets verdict 'ok' iff it lies within "
            "[1/factor, factor].  The wide default absorbs the "
            "analytic cost fallback double-counting donated state "
            "on backends without compiled HLO cost analysis.")

# --- fleet chip-time accounting (observability/goodput.py) -----------------
define_flag("goodput", False,
            "Timecard chip-time accounting (observability/goodput.py): "
            "a per-rank wall-clock state machine partitioning the "
            "rank's lifetime into compute|input_wait|compile|"
            "checkpoint_save|checkpoint_restore|resize_barrier|"
            "restart_gap|drain|idle, fed from boundaries the stack "
            "already times (trainer anatomy, executor compile spans, "
            "checkpoint save/restore, elastic-worker waits, serving "
            "drain).  Publishes chip_seconds_total{state} + "
            "goodput_fraction and arms the built-in goodput_collapse "
            "Watchtower rule.  Off: byte-identical outputs and compile "
            "keys, zero step-path work.")
define_flag("goodput_collapse_fraction", 0.3,
            "goodput_collapse trip point: the built-in alert fires "
            "when goodput_fraction (compute chip-seconds / total "
            "tracked chip-seconds) holds at or below this value for "
            "goodput_collapse_for_s.  Watched via the published "
            "badput_fraction complement (>= 1 - this value), which is "
            "0.0 until any chip-time is tracked, so an idle or "
            "just-started rank never false-fires.  <= 0 disables the "
            "rule.")
define_flag("goodput_collapse_for_s", 3.0,
            "for:-hold of the built-in goodput_collapse rule: the "
            "fraction must stay collapsed this many seconds before "
            "the alert fires (one slow accounting tick is not an "
            "efficiency incident).")

# --- resilience plane (resilience/: chaos, guard, retry) -------------------
define_flag("chaos_spec", "",
            "Deterministic fault-injection spec, "
            "'site=kind[:prob[:arg]][;...]' — e.g. "
            "'trainer.step=nan:0.1;task_queue.rpc=raise:0.2'. Empty "
            "disables every fault point (zero-overhead no-ops). Grammar "
            "and site catalog: docs/RESILIENCE.md.")
define_flag("chaos_seed", 0,
            "Seed for the fault-injection schedule; the same (spec, seed) "
            "reproduces the identical fault sequence.")
define_flag("nan_policy", "raise",
            "Numeric-guard policy for a NaN/Inf or loss-spike step in "
            "Trainer.train: raise | skip_step | rollback (restore the "
            "newest valid checkpoint and continue).")
define_flag("bad_step_limit", 5,
            "Circuit breaker: consecutive bad (NaN/Inf/spike) steps "
            "tolerated before Trainer.train raises regardless of "
            "nan_policy. 0 disables the breaker.")
define_flag("retry_max_attempts", 3,
            "Default attempt budget for resilience.retry policies "
            "(task-queue RPC reconnects, transient checkpoint-save "
            "OSErrors).")

# --- serving plane (serving/: kv_cache, batcher, loadgen) ------------------
define_flag("serving_max_batch", 8,
            "Decode-slot count of the serving plane "
            "(serving/kv_cache.py DecodeEngine): the continuous "
            "batcher advances this many sequences per compiled decode "
            "step, retiring finished slots and backfilling from the "
            "queue at step boundaries.")
define_flag("serving_queue_limit", 64,
            "Admission control: pending requests past this bound are "
            "SHED with an explicit rejection (ShedError / HTTP 429) "
            "instead of queueing unboundedly — the load-shedding half "
            "of the serving SLO story.  0 sheds everything (drain "
            "mode for tests).")
define_flag("serving_prompt_buckets", "32,64,128",
            "Comma list of prompt-length buckets the decode engine "
            "AOT-compiles prefill executables for at prepare() time; "
            "a prompt pads up to the smallest fitting bucket so the "
            "request path never compiles.")
define_flag("serving_max_new_tokens", 32,
            "Default per-request generation cap when a request does "
            "not name its own (serving/batcher.py).")
define_flag("serving_p99_budget_ms", 0.0,
            "Serving SLO bar: loadgen (serving/loadgen.py) fails its "
            "run when p99 per-token latency exceeds this many "
            "milliseconds, and a request whose TTFT or per-token "
            "latency breaches it auto-captures an X-ray bundle keyed "
            "by its trace id (observability/tracectx.py).  0 = report "
            "only, no assertion, no captures.")
define_flag("serving_lazy_bucket_compile", False,
            "Allow the decode engine to compile a missing prompt "
            "bucket ON the request path (recorded as a compile span "
            "inside the triggering request's X-ray timeline and as "
            "serving_compiles_total{kind=prefill_lazy}).  Off = the "
            "PR 8 AOT discipline: an unprepared bucket is an error, "
            "never a silent compile.")

# --- multi-replica serving router (serving/router.py, ISSUE 20) ------------
define_flag("router_retry_budget", 3,
            "Router retry-elsewhere budget: dispatch attempts beyond "
            "the first a single client request may consume before the "
            "router answers 503 (no healthy replica) / 504 (deadline). "
            "Each retry targets a different replica when one exists.")
define_flag("router_probe_interval_s", 0.5,
            "Seconds between router health probes (GET /healthz on "
            "every replica).  The probe loop is also the router's "
            "control loop: it notices revived replicas, closes "
            "recovered circuit breakers and honors a pending SIGTERM "
            "drain.")
define_flag("router_breaker_threshold", 3,
            "Per-replica circuit breaker: consecutive dispatch/probe "
            "failures before the replica's breaker opens and the "
            "router stops routing to it.")
define_flag("router_breaker_reset_s", 2.0,
            "Seconds an open per-replica breaker holds before "
            "half-open: the next probe (or, with no alternative, one "
            "trial request) decides recovery — success closes the "
            "breaker, failure re-opens it for another window.")
define_flag("router_backoff_s", 0.05,
            "Base delay of the router's deterministic retry-elsewhere "
            "backoff (resilience/retry.py jitter keyed on chaos_seed; "
            "doubles per attempt, capped at 1s).")
define_flag("router_default_deadline_s", 30.0,
            "Default end-to-end request deadline when a client body "
            "names no timeout_s: the router stops retrying and "
            "answers 504 once it expires, and the remaining budget "
            "rides to the replica on every hop.")

# --- elastic fleet (distributed/: task_queue membership, supervisor) -------
define_flag("worker_timeout", 6.0,
            "Master-side heartbeat lease: a registered worker silent "
            "for this many seconds is declared dead and every task "
            "lease it holds is requeued immediately (no waiting out "
            "per-task lease timeouts).")
define_flag("worker_heartbeat_interval", 2.0,
            "Seconds between a worker's membership heartbeats "
            "(task_queue.Heartbeater).  Keep well under worker_timeout "
            "(3x margin) so one dropped RPC doesn't read as death.")
define_flag("max_worker_restarts", 3,
            "Supervisor restart budget PER RANK: a worker crashing "
            "more than this many times is declared failed for good "
            "(distributed/supervisor.py; restarts back off "
            "exponentially with deterministic jitter).")

# --- Helmsman self-healing controller (observability/controller.py) --------
define_flag("controller", False,
            "Closed-loop self-healing (ISSUE 17 'Helmsman'): alert "
            "rules with an action: clause actuate the fleet "
            "(request_resize / drain / revive / log) through a policy "
            "layer with cooldowns, hysteresis, world clamps, fenced "
            "single-flight actuation and a failure circuit breaker.  "
            "Off (default) = Watchtower stays observe-only: no "
            "controller object, no extra thread, no decision events.")
define_flag("controller_cooldown_s", 30.0,
            "Default per-action-class cooldown between APPLIED "
            "controller decisions when the rule's action clause does "
            "not set its own 'cooldown'.  The anti-flap floor: total "
            "applied decisions per class is bounded by run_duration / "
            "cooldown (+1).")
define_flag("controller_hysteresis_s", 60.0,
            "Default direction-reversal guard for resize actions: "
            "after a grow (shrink) applies, a shrink (grow) decision "
            "is suppressed for this many seconds unless the rule's "
            "action clause sets its own 'hysteresis'.  Stops "
            "grow/shrink ping-pong around a target band.")
define_flag("controller_min_world", 1,
            "Default lower world clamp for controller resize actions "
            "(per-rule 'min_world' overrides).  The controller never "
            "shrinks the fleet below this.")
define_flag("controller_max_world", 0,
            "Default upper world clamp for controller resize actions "
            "(per-rule 'max_world' overrides).  0 = unbounded; set it "
            "— an unbounded grower is a cost incident.")
define_flag("controller_max_step", 4,
            "Cap on a burn-rate-proportional resize step: however hot "
            "the triggering signal reads, one decision changes the "
            "world by at most this many ranks.")
define_flag("controller_breaker_threshold", 3,
            "Consecutive actuator failures (per action class) that "
            "trip the controller's circuit breaker into alert-only "
            "mode: rules keep firing and journaling, nothing "
            "actuates until reset_breaker() — a broken controller "
            "must never be worse than no controller.")
define_flag("controller_backoff_s", 5.0,
            "Base delay before retrying an action class after an "
            "actuator failure (doubles per consecutive failure up to "
            "the breaker threshold).")
define_flag("controller_state_path", "",
            "Path for persisted controller state (cooldown clocks, "
            "breaker counters, decision seq).  A restarted "
            "coordinator resumes its cooldowns instead of instantly "
            "re-firing every held action.  Empty = in-memory only.")

# --- sparse plane (paddle_tpu/sparse/: CTR streaming + shard service) ------
define_flag("sparse_staleness_bound", 16,
            "Bounded-staleness window for async sparse pushes: a "
            "push_grads whose pull_version lags the table version by "
            "more than this many applied pushes is rejected with "
            "status 'stale' (the worker re-pulls and recomputes) "
            "instead of silently applying an arbitrarily old "
            "gradient.  0 = fully synchronous (any staleness "
            "rejects); raise for more async slack.")
define_flag("sparse_push_ledger_size", 4096,
            "Entries kept in a sparse shard's push ledger (push_id -> "
            "rows_applied): the exactly-once record that lets an "
            "at-least-once retried push_grads re-ack instead of "
            "double-applying.  Oldest entries evict first; keep it "
            "larger than workers x in-flight pushes.")
