"""Process-wide config plane.

Equivalent capability to the reference's gflags plane (96 DEFINE_* flags across
fluid; whitelisted env exposure at python/paddle/fluid/__init__.py:95-152).
Flags are declared with defaults, overridable via ``PTPU_<NAME>`` environment
variables at import, and mutable at runtime via set_flag/get_flag.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_FLAGS: Dict[str, Any] = {}


def define_flag(name: str, default: Any, help_str: str = ""):
    env = os.environ.get("PTPU_" + name.upper())
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _FLAGS[name] = value


def get_flag(name: str) -> Any:
    return _FLAGS[name]


def set_flag(name: str, value: Any):
    if name not in _FLAGS:
        raise KeyError(f"Unknown flag {name!r}")
    _FLAGS[name] = value


def all_flags() -> Dict[str, Any]:
    return dict(_FLAGS)


# --- Flag registry (mirrors the reference's whitelisted knobs where they ---
# --- still make sense on TPU)                                            ---
define_flag("check_nan_inf", False,
            "Scan every fetched value for NaN/Inf (ref FLAGS_check_nan_inf).")
define_flag("check_nan_inf_per_op", False,
            "Debug mode: run the program eagerly (un-jitted) and scan "
            "every op's outputs, naming the first op that produces "
            "NaN/Inf (the reference's per-op scan, operator.cc:829). "
            "Slow; for localization only.")
define_flag("deterministic", False,
            "Force deterministic reductions/samplers "
            "(ref FLAGS_cpu_deterministic/cudnn_deterministic).")
define_flag("use_pallas_kernels", True,
            "Use hand-written Pallas TPU kernels for hot ops when available.")
define_flag("default_dtype", "float32", "Default parameter dtype.")
define_flag("matmul_precision", "default",
            "jax matmul precision: default|high|highest.")
define_flag("executor_log_compiles", False,
            "Log every program (re)compilation in the executor.")
define_flag("profile_ops", False,
            "Run programs eagerly (un-jitted) and record per-op wall "
            "timings into the executor_op_seconds histogram and the "
            "trace buffer (observability/trace.py).  Slow; the "
            "interpreted-mode analogue of the reference's per-op "
            "profiler (platform/profiler.h RecordEvent per kernel).")
define_flag("recompile_warn_threshold", 5,
            "Warn once when the same (program, fetch-list) key has "
            "compiled more than this many distinct executables — a "
            "recompile storm, usually drifting feed shapes/dtypes. "
            "0 disables the check.")
define_flag("rng_seed", 0, "Global RNG seed used when a program has no seed.")
define_flag("amp_bf16", False,
            "Mixed precision: f32 matmul/conv/attention inputs enter the "
            "MXU as bfloat16 (f32 accumulation, f32 master params) — the "
            "capability of the reference's float16 transpiler "
            "(contrib/float16), applied at lowering time.")
