from . import dtypes, enforce, flags, place, profiler  # noqa: F401
