"""Optimizers — program rewriters appending update ops.

Capability parity with /root/reference/python/paddle/fluid/optimizer.py
(Optimizer:43, minimize:294 = append_backward + _create_optimization_pass;
SGD:326, Momentum:372, LarsMomentum:456, Adagrad:541, Adam:616, Adamax,
DecayedAdagrad, Adadelta, RMSProp, Ftrl, ModelAverage:1373) and
regularizer/clip application.

The update stays IN the program as ops (ops/optimizer_ops.py); accumulators
(moments, beta pows) are persistable vars initialised in the startup
program — exactly the reference's _add_accumulator contract.  The whole
(forward + vjp + updates) program compiles to one XLA executable with
donated param buffers.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .framework import unique_name
from .framework.backward import append_backward
from .framework.initializer import ConstantInitializer
from .framework.program import (Parameter, Program, Variable,
                                default_main_program,
                                default_startup_program, grad_var_name)


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._lr_input = learning_rate
        self.regularization = regularization
        self._name = name or unique_name.generate(type(self).__name__)
        self._accumulators: Dict[str, Dict[str, str]] = {}
        self._lr_var: Optional[Variable] = None

    # -- learning rate -----------------------------------------------------
    def _create_lr_var(self, program: Program) -> Variable:
        if isinstance(self._lr_input, Variable):
            return self._lr_input
        block = program.global_block()
        name = self._name + ".lr"
        if block.has_var(name):
            return block.var(name)
        lr = block.create_var(name=name, shape=[1], dtype="float32",
                              persistable=True, stop_gradient=True)
        sb = default_startup_program().global_block()
        if not sb.has_var(name):
            sb.create_var(name=name, shape=[1], dtype="float32",
                          persistable=True)
            sb.append_op("fill_constant", outputs={"Out": [name]},
                         attrs={"shape": [1], "dtype": "float32",
                                "value": float(self._lr_input)})
        return lr

    # -- accumulators (ref optimizer.py _add_accumulator) ------------------
    def _add_accumulator(self, name: str, param: Parameter, block,
                         fill_value=0.0, shape=None, dtype=None) -> str:
        acc_name = f"{self._name}.{param.name}.{name}"
        self._accumulators.setdefault(name, {})[param.name] = acc_name
        if block.has_var(acc_name):
            return acc_name
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        block.create_var(name=acc_name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=True)
        sb = default_startup_program().global_block()
        if not sb.has_var(acc_name):
            sb.create_var(name=acc_name, shape=shape, dtype=dtype,
                          persistable=True)
            sb.append_op("fill_constant", outputs={"Out": [acc_name]},
                         attrs={"shape": shape, "dtype": dtype,
                                "value": float(fill_value)})
        return acc_name

    # -- the per-param update op (subclass hook) ---------------------------
    def _append_optimize_op(self, block, param: Parameter, grad_name: str,
                            lr_name: str):
        raise NotImplementedError

    def _create_accumulators(self, block, params: List[Parameter]):
        pass

    # -- minimize (ref optimizer.py:294) -----------------------------------
    def minimize(self, loss: Variable, startup_program=None,
                 parameter_list=None, no_grad_set=None
                 ) -> Tuple[None, List[Tuple[Parameter, Variable]]]:
        from .clip import append_gradient_clip_ops
        program = loss.block.program
        param_grads = append_backward(loss, parameter_list, no_grad_set)
        block = program.global_block()
        append_gradient_clip_ops(program, param_grads)
        lr = self._create_lr_var(program)
        self._create_accumulators(block, [p for p, _ in param_grads])
        for param, grad in param_grads:
            reg = param.regularizer or self.regularization
            if reg is not None:
                reg.append_regularization_op(param, grad.name, block)
            # per-param lr scaling (ParamAttr.learning_rate)
            lr_name = lr.name
            plr = getattr(param, "optimize_attr",
                          {"learning_rate": 1.0})["learning_rate"]
            if plr != 1.0:
                scaled = f"{self._name}.{param.name}.lr"
                if not block.has_var(scaled):
                    block.create_var(name=scaled, shape=[1],
                                     dtype="float32", stop_gradient=True)
                block.append_op("scale", {"X": [lr.name]},
                                {"Out": [scaled]}, {"scale": float(plr)})
                lr_name = scaled
            self._append_optimize_op(block, param, grad.name, lr_name)
        return None, param_grads


class SGD(Optimizer):
    def _append_optimize_op(self, block, param, grad_name, lr_name):
        block.append_op("sgd",
                        {"Param": [param.name], "Grad": [grad_name],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name]}, {})


SGDOptimizer = SGD


class Momentum(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        vel = self._accumulators["velocity"][param.name]
        block.append_op("momentum",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Velocity": [vel], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "VelocityOut": [vel]},
                        {"mu": self._momentum,
                         "use_nesterov": self._use_nesterov})


MomentumOptimizer = Momentum


class LarsMomentum(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=1e-3,
                 lars_weight_decay=5e-4, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        vel = self._accumulators["velocity"][param.name]
        block.append_op("lars_momentum",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Velocity": [vel], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "VelocityOut": [vel]},
                        {"mu": self._momentum,
                         "lars_coeff": self._lars_coeff,
                         "lars_weight_decay": self._lars_weight_decay})


LarsMomentumOptimizer = LarsMomentum


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p, block)
            self._add_accumulator("moment2", p, block)
            self._add_accumulator("beta1_pow", p, block,
                                  fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, block,
                                  fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        m1 = a["moment1"][param.name]
        m2 = a["moment2"][param.name]
        b1 = a["beta1_pow"][param.name]
        b2 = a["beta2_pow"][param.name]
        block.append_op("adam",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment1": [m1], "Moment2": [m2],
                         "Beta1Pow": [b1], "Beta2Pow": [b2],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "Moment1Out": [m1],
                         "Moment2Out": [m2], "Beta1PowOut": [b1],
                         "Beta2PowOut": [b2]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon})


AdamOptimizer = Adam


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        m1 = a["moment1"][param.name]
        m2 = a["moment2"][param.name]
        b1 = a["beta1_pow"][param.name]
        b2 = a["beta2_pow"][param.name]
        block.append_op("adamw",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment1": [m1], "Moment2": [m2],
                         "Beta1Pow": [b1], "Beta2Pow": [b2],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "Moment1Out": [m1],
                         "Moment2Out": [m2], "Beta1PowOut": [b1],
                         "Beta2PowOut": [b2]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon, "coeff": self._coeff})


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, block)
            self._add_accumulator("inf_norm", p, block)
            self._add_accumulator("beta1_pow", p, block,
                                  fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        m = a["moment"][param.name]
        inf = a["inf_norm"][param.name]
        b1 = a["beta1_pow"][param.name]
        block.append_op("adamax",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment": [m], "InfNorm": [inf], "Beta1Pow": [b1],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "MomentOut": [m],
                         "InfNormOut": [inf], "Beta1PowOut": [b1]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon})


AdamaxOptimizer = Adamax


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        m = self._accumulators["moment"][param.name]
        block.append_op("adagrad",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment": [m], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "MomentOut": [m]},
                        {"epsilon": self._epsilon})


AdagradOptimizer = Adagrad


class DecayedAdagrad(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        m = self._accumulators["moment"][param.name]
        block.append_op("decayed_adagrad",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment": [m], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "MomentOut": [m]},
                        {"decay": self._decay, "epsilon": self._epsilon})


DecayedAdagradOptimizer = DecayedAdagrad


class Adadelta(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("avg_squared_grad", p, block)
            self._add_accumulator("avg_squared_update", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        g = a["avg_squared_grad"][param.name]
        u = a["avg_squared_update"][param.name]
        block.append_op("adadelta",
                        {"Param": [param.name], "Grad": [grad_name],
                         "AvgSquaredGrad": [g], "AvgSquaredUpdate": [u]},
                        {"ParamOut": [param.name], "AvgSquaredGradOut": [g],
                         "AvgSquaredUpdateOut": [u]},
                        {"rho": self._rho, "epsilon": self._epsilon})


AdadeltaOptimizer = Adadelta


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("mean_square", p, block)
            self._add_accumulator("moment", p, block)
            if self._centered:
                self._add_accumulator("mean_grad", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        ms = a["mean_square"][param.name]
        m = a["moment"][param.name]
        ins = {"Param": [param.name], "Grad": [grad_name],
               "MeanSquare": [ms], "Moment": [m], "LearningRate": [lr_name]}
        outs = {"ParamOut": [param.name], "MeanSquareOut": [ms],
                "MomentOut": [m]}
        if self._centered:
            mg = a["mean_grad"][param.name]
            ins["MeanGrad"] = [mg]
            outs["MeanGradOut"] = [mg]
        block.append_op("rmsprop", ins, outs,
                        {"decay": self._rho, "epsilon": self._epsilon,
                         "momentum": self._momentum,
                         "centered": self._centered})


RMSPropOptimizer = RMSProp


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("squared", p, block)
            self._add_accumulator("linear", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        sq = a["squared"][param.name]
        lin = a["linear"][param.name]
        block.append_op("ftrl",
                        {"Param": [param.name], "Grad": [grad_name],
                         "SquaredAccumulator": [sq],
                         "LinearAccumulator": [lin],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "SquaredAccumOut": [sq],
                         "LinearAccumOut": [lin]},
                        {"l1": self._l1, "l2": self._l2,
                         "lr_power": self._lr_power})


FtrlOptimizer = Ftrl


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p, block)
            self._add_accumulator("moment2", p, block)
            self._add_accumulator("beta1_pow", p, block,
                                  fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, block,
                                  fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        block.append_op("lamb",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment1": [a["moment1"][param.name]],
                         "Moment2": [a["moment2"][param.name]],
                         "Beta1Pow": [a["beta1_pow"][param.name]],
                         "Beta2Pow": [a["beta2_pow"][param.name]],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name],
                         "Moment1Out": [a["moment1"][param.name]],
                         "Moment2Out": [a["moment2"][param.name]],
                         "Beta1PowOut": [a["beta1_pow"][param.name]],
                         "Beta2PowOut": [a["beta2_pow"][param.name]]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon,
                         "weight_decay": self._wd})


LambOptimizer = Lamb
