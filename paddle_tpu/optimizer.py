"""Optimizers — program rewriters appending update ops.

Capability parity with /root/reference/python/paddle/fluid/optimizer.py
(Optimizer:43, minimize:294 = append_backward + _create_optimization_pass;
SGD:326, Momentum:372, LarsMomentum:456, Adagrad:541, Adam:616, Adamax,
DecayedAdagrad, Adadelta, RMSProp, Ftrl, ModelAverage:1373) and
regularizer/clip application.

The update stays IN the program as ops (ops/optimizer_ops.py); accumulators
(moments, beta pows) are persistable vars initialised in the startup
program — exactly the reference's _add_accumulator contract.  The whole
(forward + vjp + updates) program compiles to one XLA executable with
donated param buffers.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .framework import unique_name
from .framework.backward import append_backward
from .framework.initializer import ConstantInitializer
from .framework.program import (Parameter, Program, Variable,
                                default_main_program,
                                default_startup_program, grad_var_name)


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._lr_input = learning_rate
        self.regularization = regularization
        self._name = name or unique_name.generate(type(self).__name__)
        self._accumulators: Dict[str, Dict[str, str]] = {}
        self._lr_var: Optional[Variable] = None

    # -- learning rate -----------------------------------------------------
    def _create_lr_var(self, program: Program) -> Variable:
        if isinstance(self._lr_input, Variable):
            return self._lr_input
        block = program.global_block()
        name = self._name + ".lr"
        if block.has_var(name):
            return block.var(name)
        lr = block.create_var(name=name, shape=[1], dtype="float32",
                              persistable=True, stop_gradient=True)
        sb = default_startup_program().global_block()
        if not sb.has_var(name):
            sb.create_var(name=name, shape=[1], dtype="float32",
                          persistable=True)
            sb.append_op("fill_constant", outputs={"Out": [name]},
                         attrs={"shape": [1], "dtype": "float32",
                                "value": float(self._lr_input)})
        return lr

    # -- accumulators (ref optimizer.py _add_accumulator) ------------------
    def _add_accumulator(self, name: str, param: Parameter, block,
                         fill_value=0.0, shape=None, dtype=None) -> str:
        acc_name = f"{self._name}.{param.name}.{name}"
        self._accumulators.setdefault(name, {})[param.name] = acc_name
        if block.has_var(acc_name):
            return acc_name
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        block.create_var(name=acc_name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=True)
        sb = default_startup_program().global_block()
        if not sb.has_var(acc_name):
            sb.create_var(name=acc_name, shape=shape, dtype=dtype,
                          persistable=True)
            sb.append_op("fill_constant", outputs={"Out": [acc_name]},
                         attrs={"shape": shape, "dtype": dtype,
                                "value": float(fill_value)})
        return acc_name

    # -- the per-param update op (subclass hook) ---------------------------
    def _append_optimize_op(self, block, param: Parameter, grad_name: str,
                            lr_name: str):
        raise NotImplementedError

    def _create_accumulators(self, block, params: List[Parameter]):
        pass

    # -- gradient accumulation (ref ir/multi_batch_merge_pass.cc) ----------
    def _append_grad_accumulation(self, program, block, param_grads, k):
        """Rewrite grads into running accumulators and return the update
        gate: every k-th `exe.run` applies the optimizer with the mean of
        the last k micro-batch grads; other steps only accumulate.  This
        is the reference's batch-merge capability
        (framework/ir/multi_batch_merge_pass.cc) expressed as a program
        transformation — the update ops are gated in-place, so one jitted
        step serves both the accumulate and the apply iterations."""
        cname = self._name + ".acc_counter"
        block.create_var(name=cname, shape=[1], dtype="float32",
                         persistable=True, stop_gradient=True)
        sb = default_startup_program().global_block()
        if not sb.has_var(cname):
            sb.create_var(name=cname, shape=[1], dtype="float32",
                          persistable=True)
            sb.append_op("fill_constant", outputs={"Out": [cname]},
                         attrs={"shape": [1], "dtype": "float32",
                                "value": 0.0})
        block.append_op("increment", {"X": [cname]}, {"Out": [cname]},
                        {"step": 1.0})

        def tmp(suffix, dtype="float32"):
            name = unique_name.generate(f"{self._name}.{suffix}")
            block.create_var(name=name, dtype=dtype, stop_gradient=True)
            return name

        kc, zc = tmp("k_const"), tmp("zero_const")
        block.append_op("fill_constant", outputs={"Out": [kc]},
                        attrs={"shape": [1], "dtype": "float32",
                               "value": float(k)})
        block.append_op("fill_constant", outputs={"Out": [zc]},
                        attrs={"shape": [1], "dtype": "float32",
                               "value": 0.0})
        # Wrap the counter in place (counter <- counter mod k) so it stays
        # in [0, k) forever — an unbounded fp32 counter would saturate at
        # 2^24 and freeze the gate.
        block.append_op("elementwise_mod", {"X": [cname], "Y": [kc]},
                        {"Out": [cname]})
        eq = tmp("is_boundary_b", "bool")
        block.append_op("equal", {"X": [cname], "Y": [zc]}, {"Out": [eq]})
        gate = tmp("is_boundary")
        block.append_op("cast", {"X": [eq]}, {"Out": [gate]},
                        {"out_dtype": "float32"})

        new_pairs, acc_names = [], []
        for param, grad in param_grads:
            acc = self._add_accumulator("grad_acc", param, block)
            block.append_op("elementwise_add", {"X": [acc],
                                                "Y": [grad.name]},
                            {"Out": [acc]})
            eff = tmp(f"{param.name}.grad_avg")
            block.append_op("scale", {"X": [acc]}, {"Out": [eff]},
                            {"scale": 1.0 / k})
            new_pairs.append((param, block.var(eff)))
            acc_names.append(acc)
        return new_pairs, gate, acc_names

    def _append_gated_optimize_op(self, block, param, grad_name, lr_name,
                                  gate):
        """Run the subclass update, then gate every written var back to its
        pre-update value unless this step is an accumulation boundary."""
        start = len(block.ops)
        self._append_optimize_op(block, param, grad_name, lr_name)
        written = sorted({n for op in block.ops[start:]
                          for names in op.outputs.values() for n in names})
        saves = []
        for i, w in enumerate(written):
            old = unique_name.generate(f"{w}.preupdate")
            block.create_var(name=old, dtype="float32",
                             stop_gradient=True)
            # snapshot BEFORE the update ops (insert preserves order)
            block.append_op("assign", {"X": [w]}, {"Out": [old]},
                            index=start + i)
            saves.append((w, old))
        for w, old in saves:
            diff = unique_name.generate(f"{w}.upd_delta")
            block.create_var(name=diff, dtype="float32",
                            stop_gradient=True)
            block.append_op("elementwise_sub", {"X": [w], "Y": [old]},
                            {"Out": [diff]})
            block.append_op("elementwise_mul", {"X": [diff], "Y": [gate]},
                            {"Out": [diff]})
            block.append_op("elementwise_add", {"X": [old], "Y": [diff]},
                            {"Out": [w]})

    # -- minimize (ref optimizer.py:294) -----------------------------------
    def minimize(self, loss: Variable, startup_program=None,
                 parameter_list=None, no_grad_set=None,
                 accumulate_steps: int = 1
                 ) -> Tuple[None, List[Tuple[Parameter, Variable]]]:
        from .clip import append_gradient_clip_ops
        program = loss.block.program
        param_grads = append_backward(loss, parameter_list, no_grad_set)
        block = program.global_block()
        gate = None
        acc_names: List[str] = []
        if accumulate_steps and int(accumulate_steps) > 1:
            param_grads, gate, acc_names = self._append_grad_accumulation(
                program, block, param_grads, int(accumulate_steps))
        append_gradient_clip_ops(program, param_grads)
        lr = self._create_lr_var(program)
        self._create_accumulators(block, [p for p, _ in param_grads])
        for param, grad in param_grads:
            reg = param.regularizer or self.regularization
            if reg is not None:
                reg.append_regularization_op(param, grad.name, block)
            # per-param lr scaling (ParamAttr.learning_rate)
            lr_name = lr.name
            plr = getattr(param, "optimize_attr",
                          {"learning_rate": 1.0})["learning_rate"]
            if plr != 1.0:
                scaled = f"{self._name}.{param.name}.lr"
                if not block.has_var(scaled):
                    block.create_var(name=scaled, shape=[1],
                                     dtype="float32", stop_gradient=True)
                block.append_op("scale", {"X": [lr.name]},
                                {"Out": [scaled]}, {"scale": float(plr)})
                lr_name = scaled
            if gate is None:
                self._append_optimize_op(block, param, grad.name, lr_name)
            else:
                self._append_gated_optimize_op(block, param, grad.name,
                                               lr_name, gate)
        if gate is not None:
            # clear the accumulators on boundary steps: acc *= (1 - gate)
            inv = unique_name.generate(f"{self._name}.not_boundary")
            block.create_var(name=inv, dtype="float32", stop_gradient=True)
            block.append_op("scale", {"X": [gate]}, {"Out": [inv]},
                            {"scale": -1.0, "bias": 1.0})
            for acc in acc_names:
                block.append_op("elementwise_mul", {"X": [acc], "Y": [inv]},
                                {"Out": [acc]})
        return None, param_grads


class SGD(Optimizer):
    def _append_optimize_op(self, block, param, grad_name, lr_name):
        block.append_op("sgd",
                        {"Param": [param.name], "Grad": [grad_name],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name]}, {})


SGDOptimizer = SGD


class Momentum(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        vel = self._accumulators["velocity"][param.name]
        block.append_op("momentum",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Velocity": [vel], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "VelocityOut": [vel]},
                        {"mu": self._momentum,
                         "use_nesterov": self._use_nesterov})


MomentumOptimizer = Momentum


class LarsMomentum(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=1e-3,
                 lars_weight_decay=5e-4, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        vel = self._accumulators["velocity"][param.name]
        block.append_op("lars_momentum",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Velocity": [vel], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "VelocityOut": [vel]},
                        {"mu": self._momentum,
                         "lars_coeff": self._lars_coeff,
                         "lars_weight_decay": self._lars_weight_decay})


LarsMomentumOptimizer = LarsMomentum


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p, block)
            self._add_accumulator("moment2", p, block)
            self._add_accumulator("beta1_pow", p, block,
                                  fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, block,
                                  fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        m1 = a["moment1"][param.name]
        m2 = a["moment2"][param.name]
        b1 = a["beta1_pow"][param.name]
        b2 = a["beta2_pow"][param.name]
        block.append_op("adam",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment1": [m1], "Moment2": [m2],
                         "Beta1Pow": [b1], "Beta2Pow": [b2],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "Moment1Out": [m1],
                         "Moment2Out": [m2], "Beta1PowOut": [b1],
                         "Beta2PowOut": [b2]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon})


AdamOptimizer = Adam


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        m1 = a["moment1"][param.name]
        m2 = a["moment2"][param.name]
        b1 = a["beta1_pow"][param.name]
        b2 = a["beta2_pow"][param.name]
        block.append_op("adamw",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment1": [m1], "Moment2": [m2],
                         "Beta1Pow": [b1], "Beta2Pow": [b2],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "Moment1Out": [m1],
                         "Moment2Out": [m2], "Beta1PowOut": [b1],
                         "Beta2PowOut": [b2]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon, "coeff": self._coeff})


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, block)
            self._add_accumulator("inf_norm", p, block)
            self._add_accumulator("beta1_pow", p, block,
                                  fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        m = a["moment"][param.name]
        inf = a["inf_norm"][param.name]
        b1 = a["beta1_pow"][param.name]
        block.append_op("adamax",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment": [m], "InfNorm": [inf], "Beta1Pow": [b1],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "MomentOut": [m],
                         "InfNormOut": [inf], "Beta1PowOut": [b1]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon})


AdamaxOptimizer = Adamax


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        m = self._accumulators["moment"][param.name]
        block.append_op("adagrad",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment": [m], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "MomentOut": [m]},
                        {"epsilon": self._epsilon})


AdagradOptimizer = Adagrad


class DecayedAdagrad(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        m = self._accumulators["moment"][param.name]
        block.append_op("decayed_adagrad",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment": [m], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "MomentOut": [m]},
                        {"decay": self._decay, "epsilon": self._epsilon})


DecayedAdagradOptimizer = DecayedAdagrad


class Adadelta(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("avg_squared_grad", p, block)
            self._add_accumulator("avg_squared_update", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        g = a["avg_squared_grad"][param.name]
        u = a["avg_squared_update"][param.name]
        block.append_op("adadelta",
                        {"Param": [param.name], "Grad": [grad_name],
                         "AvgSquaredGrad": [g], "AvgSquaredUpdate": [u]},
                        {"ParamOut": [param.name], "AvgSquaredGradOut": [g],
                         "AvgSquaredUpdateOut": [u]},
                        {"rho": self._rho, "epsilon": self._epsilon})


AdadeltaOptimizer = Adadelta


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("mean_square", p, block)
            self._add_accumulator("moment", p, block)
            if self._centered:
                self._add_accumulator("mean_grad", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        ms = a["mean_square"][param.name]
        m = a["moment"][param.name]
        ins = {"Param": [param.name], "Grad": [grad_name],
               "MeanSquare": [ms], "Moment": [m], "LearningRate": [lr_name]}
        outs = {"ParamOut": [param.name], "MeanSquareOut": [ms],
                "MomentOut": [m]}
        if self._centered:
            mg = a["mean_grad"][param.name]
            ins["MeanGrad"] = [mg]
            outs["MeanGradOut"] = [mg]
        block.append_op("rmsprop", ins, outs,
                        {"decay": self._rho, "epsilon": self._epsilon,
                         "momentum": self._momentum,
                         "centered": self._centered})


RMSPropOptimizer = RMSProp


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("squared", p, block)
            self._add_accumulator("linear", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        sq = a["squared"][param.name]
        lin = a["linear"][param.name]
        block.append_op("ftrl",
                        {"Param": [param.name], "Grad": [grad_name],
                         "SquaredAccumulator": [sq],
                         "LinearAccumulator": [lin],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "SquaredAccumOut": [sq],
                         "LinearAccumOut": [lin]},
                        {"l1": self._l1, "l2": self._l2,
                         "lr_power": self._lr_power})


FtrlOptimizer = Ftrl


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p, block)
            self._add_accumulator("moment2", p, block)
            self._add_accumulator("beta1_pow", p, block,
                                  fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, block,
                                  fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        a = self._accumulators
        block.append_op("lamb",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment1": [a["moment1"][param.name]],
                         "Moment2": [a["moment2"][param.name]],
                         "Beta1Pow": [a["beta1_pow"][param.name]],
                         "Beta2Pow": [a["beta2_pow"][param.name]],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name],
                         "Moment1Out": [a["moment1"][param.name]],
                         "Moment2Out": [a["moment2"][param.name]],
                         "Beta1PowOut": [a["beta1_pow"][param.name]],
                         "Beta2PowOut": [a["beta2_pow"][param.name]]},
                        {"beta1": self._beta1, "beta2": self._beta2,
                         "epsilon": self._epsilon,
                         "weight_decay": self._wd})


LambOptimizer = Lamb


class ProximalGD(Optimizer):
    """Proximal gradient descent with l1/l2 (ref proximal_gd_op.cc and
    optimizer use of the registered op)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2 = l1, l2

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        block.append_op("proximal_gd",
                        {"Param": [param.name], "Grad": [grad_name],
                         "LearningRate": [lr_name]},
                        {"ParamOut": [param.name]},
                        {"l1": self._l1, "l2": self._l2})


ProximalGDOptimizer = ProximalGD


class ProximalAdagrad(Optimizer):
    """Adagrad with proximal l1/l2 regularization
    (ref proximal_adagrad_op.cc)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2 = l1, l2

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, block)

    def _append_optimize_op(self, block, param, grad_name, lr_name):
        m = self._accumulators["moment"][param.name]
        block.append_op("proximal_adagrad",
                        {"Param": [param.name], "Grad": [grad_name],
                         "Moment": [m], "LearningRate": [lr_name]},
                        {"ParamOut": [param.name], "MomentOut": [m]},
                        {"l1": self._l1, "l2": self._l2})


ProximalAdagradOptimizer = ProximalAdagrad


class ModelAverage(Optimizer):
    """Running average of parameter values with apply/restore swap
    (ref /root/reference/python/paddle/fluid/optimizer.py:1373).

    Construct AFTER optimizer.minimize(): appends an
    `average_accumulates` op per parameter to the main program so every
    training step folds the freshly-updated params into the running sums.
    `with ma.apply(exe):` swaps params for their averages (evaluation /
    export); `restore` (automatic on context exit) puts the trained
    values back.  The windowing knobs are accepted for API parity; the
    TPU lowering keeps a single running sum since the last reset — the
    simplification is noted in docs/PARITY.md."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, program=None,
                 startup_program=None, **kw):
        super().__init__(0.0, **kw)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        main = program or default_main_program()
        block = main.global_block()
        self._params = [v for v in main.list_vars()
                        if isinstance(v, Parameter)]

        def _append_accumulation():
            for p in self._params:
                s1 = self._add_accumulator("sum_1", p, block)
                num = self._add_accumulator("num_accumulates", p, block,
                                            shape=[1])
                block.append_op("average_accumulates",
                                {"param": [p.name], "in_sum_1": [s1],
                                 "in_num_accumulates": [num]},
                                {"out_sum_1": [s1],
                                 "out_num_accumulates": [num]},
                                {"max_average_window":
                                 float(self.max_average_window)})

        if startup_program is not None:
            # _add_accumulator writes its fill_constant init ops into the
            # *default* startup program; when constructed outside the
            # original program_guard, route them to the caller's startup.
            from .framework.program import program_guard
            with program_guard(main, startup_program):
                _append_accumulation()
        else:
            _append_accumulation()
        self._build_swap_programs()

    def _declare(self, block, name, shape, dtype):
        if not block.has_var(name):
            block.create_var(name=name, shape=list(shape or [1]),
                             dtype=dtype, persistable=True,
                             stop_gradient=True)

    def _build_swap_programs(self):
        self.apply_program = Program()
        self.restore_program = Program()
        ab = self.apply_program.global_block()
        rb = self.restore_program.global_block()
        for p in self._params:
            s1 = self._accumulators["sum_1"][p.name]
            num = self._accumulators["num_accumulates"][p.name]
            backup = f"{self._name}.{p.name}.backup"
            for blk in (ab, rb):
                self._declare(blk, p.name, p.shape, p.dtype)
                self._declare(blk, backup, p.shape, p.dtype)
            self._declare(ab, s1, p.shape, p.dtype)
            self._declare(ab, num, [1], "float32")
            ab.append_op("assign", {"X": [p.name]}, {"Out": [backup]})
            one = f"{self._name}.{p.name}.one"
            denom = f"{self._name}.{p.name}.denom"
            avg = f"{self._name}.{p.name}.avg"
            has = f"{self._name}.{p.name}.has_acc"
            hasf = f"{self._name}.{p.name}.has_acc_f"
            delta = f"{self._name}.{p.name}.avg_delta"
            for n in (one, denom, avg, hasf, delta):
                ab.create_var(name=n, dtype="float32", stop_gradient=True)
            ab.create_var(name=has, dtype="bool", stop_gradient=True)
            ab.append_op("fill_constant", outputs={"Out": [one]},
                         attrs={"shape": [1], "dtype": "float32",
                                "value": 1.0})
            ab.append_op("elementwise_max", {"X": [num], "Y": [one]},
                         {"Out": [denom]})
            ab.append_op("elementwise_div", {"X": [s1], "Y": [denom]},
                         {"Out": [avg]})
            # keep the live params when nothing has been accumulated yet
            # (apply() right after startup/checkpoint load must be a no-op,
            # not an all-zeros swap):
            # param += (num >= 1) * (avg - param)
            ab.append_op("greater_equal", {"X": [num], "Y": [one]},
                         {"Out": [has]})
            ab.append_op("cast", {"X": [has]}, {"Out": [hasf]},
                         {"out_dtype": "float32"})
            ab.append_op("elementwise_sub", {"X": [avg], "Y": [p.name]},
                         {"Out": [delta]})
            ab.append_op("elementwise_mul", {"X": [delta], "Y": [hasf]},
                         {"Out": [delta]})
            ab.append_op("elementwise_add", {"X": [p.name], "Y": [delta]},
                         {"Out": [p.name]})
            rb.append_op("assign", {"X": [backup]}, {"Out": [p.name]})

    def apply(self, executor, need_restore=True):
        """Context manager: params hold averaged values inside the block."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            executor.run(self.apply_program)
            try:
                yield self
            finally:
                if need_restore:
                    self.restore(executor)

        return _ctx()

    def restore(self, executor):
        executor.run(self.restore_program)
