"""Program IR: the serializable graph-program representation.

Capability parity with the reference's ProgramDesc/BlockDesc/OpDesc/VarDesc
protobuf plane (/root/reference/paddle/fluid/framework/framework.proto:43,165,
171,184) and its Python mirrors (python/paddle/fluid/framework.py: Variable:224,
Operator:529, Block:972, Program:1477, Parameter:2071).

TPU-first difference: the program is *not* interpreted op-by-op.  It is a
build-time artifact — the Executor lowers an entire (program, feed, fetch)
triple into ONE jitted XLA function (see framework/executor.py).  The IR exists
for the capabilities that need program-as-data: serialization
(save/load_inference_model), source-to-source autodiff bookkeeping, program
transformation passes (quantization, pruning), and introspection.

Nested blocks encode control flow (while/cond) exactly like the reference's
BLOCK attributes; they lower to lax.while_loop / lax.cond.
"""
from __future__ import annotations

import contextlib
import itertools
import copy
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.dtypes import convert_dtype
from ..core.enforce import check_arg, enforce
from . import unique_name

GRAD_SUFFIX = "@GRAD"  # ref framework: core.grad_var_suffix()


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


# the paddle_tpu package directory: frames under it are framework
# internals, the first frame OUTSIDE it is the user's layer call site
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_callsite() -> Optional[str]:
    """``file:line`` of the first stack frame outside the paddle_tpu
    package — the layer call that appended the current op.  The
    verifier (paddle_tpu/analysis) reports it with every finding, the
    analogue of the reference's op_callstack attribute
    (framework.py Operator attrs['op_callstack']).  Best-effort: None
    when every frame is internal (e.g. Program.from_dict round-trips
    driven by the framework itself)."""
    try:
        f = sys._getframe(2)
    except ValueError:          # shallow stack
        return None
    depth = 0
    while f is not None and depth < 32:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR) and "importlib" not in fn:
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
        depth += 1
    return None


class Variable:
    """A named tensor slot in a Block (ref framework.py:224).

    shape may contain -1 for data-dependent dims (batch); persistable vars
    live in the Scope across runs (parameters, optimizer state, BN stats).
    """

    def __init__(self, block: "Block", name: str, shape=None, dtype="float32",
                 persistable: bool = False, stop_gradient: bool = False,
                 is_data: bool = False, lod_level: int = 0):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        # LoD survives only as metadata at the data edge; ragged batches are
        # represented densely (padding + masks/segment-ids) on TPU.
        self.lod_level = lod_level

    @property
    def grad_name(self) -> str:
        return grad_var_name(self.name)

    def astype(self, dtype):
        from ..layers import tensor as tensor_layers
        return tensor_layers.cast(self, dtype)

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, persistable={self.persistable})")

    def to_dict(self):
        d = {
            "name": self.name, "shape": list(self.shape or ()),
            "dtype": self.dtype, "persistable": self.persistable,
            "stop_gradient": self.stop_gradient, "is_data": self.is_data,
            "lod_level": self.lod_level,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", None),
        }
        if getattr(self, "sharding", None) is not None:
            # PartitionSpec annotations (tensor/context-parallel
            # transpilers) must survive clone/save/load
            d["sharding"] = list(self.sharding)
        return d


class Parameter(Variable):
    """A trainable persistable Variable (ref framework.py:2071)."""

    def __init__(self, block, name, shape, dtype="float32", trainable=True,
                 regularizer=None, sharding=None, **kw):
        check_arg(shape is not None and all(int(s) > 0 for s in shape),
                  f"Parameter {name!r} needs a fully-static shape, got {shape}")
        super().__init__(block, name, shape=shape, dtype=dtype,
                         persistable=True, **kw)
        self.trainable = trainable
        self.regularizer = regularizer
        # PartitionSpec-style tuple for SPMD placement of this parameter
        # (replaces pserver param-shard placement, transpiler VarBlock:65).
        self.sharding = sharding


class Operator:
    """One op invocation: (type, input/output var-name slots, attrs)
    (ref framework.py:529, framework.proto OpDesc:43)."""

    def __init__(self, block: "Block", type: str,
                 inputs: Optional[Dict[str, Sequence[str]]] = None,
                 outputs: Optional[Dict[str, Sequence[str]]] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        from .registry import get_op_def  # late import
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        # user-code origin for verifier diagnostics; NOT serialized
        # (to_dict/clone outputs stay byte-identical to pre-analysis
        # builds) — deserialized programs report callsite=None
        self.callsite = _user_callsite()
        get_op_def(type)  # validates the op exists

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        return f"Op({self.type}, in={self.inputs}, out={self.outputs})"

    def to_dict(self):
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs, "attrs": _jsonable_attrs(self.attrs)}


def _jsonable_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


def _attrs_from_json(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
        else:
            out[k] = v
    return out


class Block:
    """Ordered op list + var map; nested via parent_idx (ref framework.py:972,
    framework.proto BlockDesc:171)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent(self) -> Optional["Block"]:
        return (self.program.blocks[self.parent_idx]
                if self.parent_idx >= 0 else None)

    def create_var(self, name=None, **kw) -> Variable:
        name = name or unique_name.generate("tmp")
        v = Variable(self, name, **kw)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name, shape, dtype="float32", **kw) -> Parameter:
        p = Parameter(self, name, shape, dtype=dtype, **kw)
        self.vars[name] = p
        self.program._bump()
        return p

    def var(self, name: str) -> Variable:
        """Find var here or in ancestor blocks (ref Scope parent walk)."""
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise KeyError(f"Variable {name!r} not found in block {self.idx}")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None,
                  index: Optional[int] = None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        if index is None:
            self.ops.append(op)
        else:
            self.ops.insert(index, op)
        self.program._bump()
        return op

    def to_dict(self):
        return {"idx": self.idx, "parent_idx": self.parent_idx,
                "vars": [v.to_dict() for v in self.vars.values()],
                "ops": [op.to_dict() for op in self.ops]}


class Program:
    """A whole trainable/serializable program (ref framework.py:1477)."""

    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self.random_seed: Optional[int] = None
        # version bumps on any mutation -> executor cache invalidation
        self._version = 0
        # process-unique id: executor cache keys use this instead of
        # id(program), whose value a GC'd program can bequeath to a new one
        self._uid = next(Program._uid_counter)

    # -- structure ---------------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        self._bump()
        return b

    def rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    def _bump(self):
        self._version += 1

    # -- introspection -----------------------------------------------------
    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.list_vars() if isinstance(v, Parameter)]

    def __repr__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"Block {b.idx} (parent {b.parent_idx}):")
            for op in b.ops:
                lines.append(f"  {op!r}")
        return "\n".join(lines)

    # -- transforms --------------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep copy; for_test flips is_test attrs (dropout/BN switch to
        inference behaviour) — ref framework.py Program.clone."""
        p = Program.from_dict(self.to_dict())
        p.random_seed = self.random_seed
        if for_test:
            for b in p.blocks:
                for op in b.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
        return p

    def prune(self, feed_names: Sequence[str],
              fetch_names: Sequence[str]) -> "Program":
        """Backward-slice the program to the ops needed to compute
        fetch_names from feed_names (+persistables).  This is the core of
        save_inference_model (ref python io.py:570)."""
        src = self.global_block()
        needed = set(fetch_names)
        keep: List[Operator] = []
        for op in reversed(src.ops):
            outs = set(op.output_names())
            if outs & needed:
                keep.append(op)
                needed |= set(op.input_names())
        keep.reverse()

        used = set(feed_names) | set(fetch_names)
        for op in keep:
            used |= set(op.input_names()) | set(op.output_names())
        # control-flow ops pull in whole sub-blocks: keep those blocks (and
        # the global vars their ops touch) intact
        for b in self.blocks[1:]:
            for op in b.ops:
                used |= set(op.input_names()) | set(op.output_names())

        # clone the full program (preserving sub-block structure), then
        # rewrite block 0 down to the kept slice
        p = self.clone()
        dst = p.global_block()
        dst.ops = []
        dst.vars = {name: v for name, v in dst.vars.items() if name in used}
        for v in dst.vars.values():
            v.block = dst
        for op in keep:
            dst.append_op(op.type, copy.deepcopy(op.inputs),
                          copy.deepcopy(op.outputs),
                          copy.deepcopy(op.attrs))
        return p

    # -- serialization (ref ProgramDesc proto; JSON here) ------------------
    def to_dict(self):
        d = {"version": 1, "random_seed": self.random_seed,
             "blocks": [b.to_dict() for b in self.blocks]}
        # DistributeTranspiler markers must survive clone/save/load —
        # the inserted c_allreduce ops are meaningless without them
        if getattr(self, "_dist_spmd_axis", None) is not None:
            d["dist_spmd_axis"] = self._dist_spmd_axis
            d["dist_trainers"] = getattr(self, "_dist_trainers", None)
        if getattr(self, "_dist_feed_shard_dim", 0):
            d["dist_feed_shard_dim"] = self._dist_feed_shard_dim
        if getattr(self, "_dist_cp_axis", None) is not None:
            d["dist_cp_axis"] = self._dist_cp_axis
        if getattr(self, "_dist_pp_axis", None) is not None:
            d["dist_pp_axis"] = self._dist_pp_axis
            d["pp_degree"] = getattr(self, "_pp_degree", None)
            d["pp_microbatches"] = getattr(self, "_pp_microbatches", None)
            d["pp_schedule"] = getattr(self, "_pp_schedule", "gpipe")
        return d

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program()
        p.random_seed = d.get("random_seed")
        if d.get("dist_spmd_axis") is not None:
            p._dist_spmd_axis = d["dist_spmd_axis"]
            p._dist_trainers = d.get("dist_trainers")
        if d.get("dist_feed_shard_dim"):
            p._dist_feed_shard_dim = d["dist_feed_shard_dim"]
        if d.get("dist_cp_axis") is not None:
            p._dist_cp_axis = d["dist_cp_axis"]
        if d.get("dist_pp_axis") is not None:
            p._dist_pp_axis = d["dist_pp_axis"]
            p._pp_degree = d.get("pp_degree")
            p._pp_microbatches = d.get("pp_microbatches")
            p._pp_schedule = d.get("pp_schedule", "gpipe")
        # recreate blocks
        for bd in d["blocks"][1:]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            p.blocks.append(b)
        for bd in d["blocks"]:
            b = p.blocks[bd["idx"]]
            for vd in bd["vars"]:
                if vd.get("is_parameter"):
                    v = b.create_parameter(
                        vd["name"], vd["shape"], vd["dtype"],
                        trainable=bool(vd.get("trainable", True)))
                else:
                    v = b.create_var(vd["name"],
                                     shape=vd["shape"] or None,
                                     dtype=vd["dtype"],
                                     persistable=vd["persistable"],
                                     stop_gradient=vd["stop_gradient"],
                                     is_data=vd["is_data"],
                                     lod_level=vd.get("lod_level", 0))
                if vd.get("sharding") is not None:
                    v.sharding = tuple(vd["sharding"])
            for od in bd["ops"]:
                b.append_op(od["type"], od["inputs"], od["outputs"],
                            _attrs_from_json(od["attrs"]))
        p._current_block_idx = 0
        return p

    def serialize_to_string(self) -> bytes:
        return json.dumps(self.to_dict()).encode("utf-8")

    @staticmethod
    def parse_from_string(s: bytes) -> "Program":
        return Program.from_dict(json.loads(s.decode("utf-8")))


# --- default program plumbing (ref framework.py:2155,2173,2223) -----------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program,
                  startup_program: Optional[Program] = None):
    global _main_program, _startup_program
    prev_main, prev_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev_main, prev_startup


def reset_default_programs():
    global _main_program, _startup_program
    _main_program = Program()
    _startup_program = Program()
    unique_name.reset()
