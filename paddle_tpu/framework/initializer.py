"""Parameter initializers — realized as startup-program ops.

Capability parity with /root/reference/python/paddle/fluid/initializer.py
(Constant/Uniform/Normal/TruncatedNormal/Xavier/MSRA/Bilinear/NumpyArray).
Each initializer appends a fill/random op to the *startup program*, exactly
like the reference; running the startup program materialises parameters.
"""
from __future__ import annotations

import math

import numpy as np

from .program import Variable


class Initializer:
    def __call__(self, var: Variable, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = float(value)

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "value": self.value})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "min": float(self.low), "max": float(self.high),
                               "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc),
                               "std": float(self.scale), "seed": self.seed})


class TruncatedNormalInitializer(NormalInitializer):
    def __call__(self, var, block):
        block.append_op("truncated_gaussian_random",
                        outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc),
                               "std": float(self.scale), "seed": self.seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return int(shape[0]), int(shape[0])
    if len(shape) == 2:
        # fc weights are (in, out)
        return int(shape[0]), int(shape[1])
    # conv kernels are OIHW (out, in, *receptive) — ref initializer.py
    # _compute_fans: fan_in = in * prod(receptive), fan_out = out * prod
    receptive = int(np.prod(shape[2:]))
    return int(shape[1]) * receptive, int(shape[0]) * receptive


class XavierInitializer(Initializer):
    """Glorot init (ref initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = (
            uniform, fan_in, fan_out, seed)

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He/Kaiming init (ref initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            NormalInitializer(0.0, math.sqrt(2.0 / fi), self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "values": self.value})


# convenient aliases matching the reference's public names
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
