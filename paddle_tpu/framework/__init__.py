from . import program, registry, executor, backward  # noqa: F401
