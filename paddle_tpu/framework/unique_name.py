"""Unique name generator (ref python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import contextlib
from collections import defaultdict

_counters = defaultdict(int)


def generate(key: str) -> str:
    _counters[key] += 1
    return f"{key}_{_counters[key] - 1}"


def reset():
    _counters.clear()


@contextlib.contextmanager
def guard():
    """Fresh namespace scope (used by tests to get deterministic names)."""
    global _counters
    saved = _counters
    _counters = defaultdict(int)
    try:
        yield
    finally:
        _counters = saved
