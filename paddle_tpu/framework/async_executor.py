"""AsyncExecutor — high-throughput multithread trainer over sharded
text files.

Capability parity with the reference's AsyncExecutor stack
(framework/async_executor.h:60 RunFromFile, executor_thread_worker.h:136,
data_feed.h:49 MultiSlotDataFeed + data_feed.proto, Python
async_executor.py:33): N worker threads decouple file reading/parsing
from training, each pulling file shards from a queue, batching
MultiSlot-format text lines, and stepping the model.

TPU-first redesign, not a thread-per-scope interpreter:
  * the program is compiled ONCE (whole-program XLA jit via the shared
    Executor cache); every worker calls the same compiled step — XLA
    executables are thread-safe and release the GIL, so parsing/batching
    genuinely overlaps device compute;
  * the reference's Hogwild-style racy in-place updates (each thread's
    op list writes the shared Scope) become atomic step-granular updates:
    workers snapshot params, compute, and a lock applies the state
    update.  Same async-CTR capability, no torn reads;
  * pslib pull/push (executor_thread_worker.h:195 AsyncExecutorThreadWorker)
    is out of scope for TPU — the sharded-embedding path
    (parallel/sharded_embedding.py) carries the big-table capability.

File format (MultiSlotDataFeed, data_feed.h:224): per line, for each
slot in order: `<n> v1 ... vn`; uint64 slots hold ids, float slots hold
dense values.
"""
from __future__ import annotations

import os
import queue
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.enforce import EnforceNotMet
from ..core.place import CPUPlace, Place
from .executor import Executor
from .program import Program


class Slot:
    """One slot of a DataFeedDesc (ref data_feed.proto Slot)."""

    def __init__(self, name: str, type: str = "uint64",
                 is_dense: bool = False, is_used: bool = True,
                 dim: int = 1):
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used
        self.dim = dim        # fixed width the batch is padded/trimmed to


class DataFeedDesc:
    """MultiSlot text-feed description (ref python/paddle/fluid/
    data_feed_desc.py over data_feed.proto).  Built programmatically
    instead of via a .proto text file."""

    def __init__(self, slots: Sequence[Slot], batch_size: int = 32,
                 name: str = "multi_slot"):
        self.slots = list(slots)
        self.batch_size = int(batch_size)
        self.name = name

    def set_batch_size(self, bs: int):
        self.batch_size = int(bs)

    def set_use_slots(self, use_slots_name: Sequence[str]):
        used = set(use_slots_name)
        for s in self.slots:
            s.is_used = s.name in used

    def parse_line(self, line: str):
        """One MultiSlot line -> {slot: np.ndarray(dim)} for used slots."""
        parts = line.split()
        out, i = {}, 0
        for slot in self.slots:
            if i >= len(parts):
                raise EnforceNotMet(
                    f"MultiSlot parse error: line ended before slot "
                    f"{slot.name!r}: {line[:80]!r}")
            n = int(parts[i])
            if n < 0 or i + 1 + n > len(parts):
                raise EnforceNotMet(
                    f"MultiSlot parse error: slot {slot.name!r} declares "
                    f"{n} values but the line ends early: {line[:80]!r}")
            vals = parts[i + 1:i + 1 + n]
            i += 1 + n
            if not slot.is_used:
                continue
            dtype = np.int64 if slot.type == "uint64" else np.float32
            arr = np.asarray(vals, dtype=dtype)
            if arr.shape[0] < slot.dim:        # pad (ids with 0)
                arr = np.pad(arr, (0, slot.dim - arr.shape[0]))
            out[slot.name] = arr[:slot.dim]
        return out


class AsyncExecutor:
    """ref async_executor.py:33 / async_executor.h:60.

    run(program, data_feed, filelist, thread_num, fetch) trains over all
    files once (one 'epoch' in reference terms) and returns per-fetch
    running means.  Metrics from every worker step are folded into the
    totals under the update lock.
    """

    def __init__(self, place: Optional[Place] = None):
        self.place = place or CPUPlace()
        self.executor = Executor(self.place)

    def run_startup_program(self, program: Program):
        self.executor.run(program)

    def run(self, program: Program, data_feed: DataFeedDesc,
            filelist: Sequence[str], thread_num: int,
            fetch: Sequence[str], mode: str = "", debug: bool = False):
        if thread_num <= 0:
            raise EnforceNotMet("AsyncExecutor: thread_num must be > 0")
        missing = [f for f in filelist if not os.path.exists(f)]
        if missing:
            raise EnforceNotMet(f"AsyncExecutor: missing files {missing}")
        file_q: "queue.Queue[str]" = queue.Queue()
        for f in filelist:
            file_q.put(f)

        fetch = list(fetch)
        update_lock = threading.Lock()
        totals = {n: 0.0 for n in fetch}
        counts = {n: 0 for n in fetch}
        errors: List[BaseException] = []

        def batches_from(fname):
            batch: List[Dict[str, np.ndarray]] = []
            with open(fname) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    batch.append(data_feed.parse_line(line))
                    if len(batch) == data_feed.batch_size:
                        yield _collate(batch)
                        batch = []
            if batch:
                yield _collate(batch)

        def _collate(batch):
            return {k: np.stack([b[k] for b in batch])
                    for k in batch[0]}

        def step(feed):
            # Executor.run mutates program state (params); serialize the
            # state transition — XLA compute inside still overlaps with
            # other threads' parsing (GIL released during execution).
            with update_lock:
                outs = self.executor.run(program, feed=feed,
                                         fetch_list=fetch)
                for n, v in zip(fetch, outs):
                    totals[n] += float(np.mean(v))
                    counts[n] += 1

        def worker():
            try:
                while True:
                    try:
                        fname = file_q.get_nowait()
                    except queue.Empty:
                        return
                    for feed in batches_from(fname):
                        step(feed)
                    if debug:
                        print(f"[async_executor] done {fname}")
            except BaseException as e:   # propagate like exception_holder.h
                errors.append(e)

        # no separate warm-up pass: step() serializes under update_lock,
        # so the first worker to arrive compiles while the rest parse —
        # and every batch is consumed exactly once per run() (one epoch)
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(thread_num)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        if fetch and all(c == 0 for c in counts.values()):
            raise EnforceNotMet("AsyncExecutor: filelist has no samples")
        return {n: totals[n] / max(counts[n], 1) for n in fetch}
