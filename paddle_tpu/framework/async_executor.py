"""AsyncExecutor — streaming multithread trainer over sharded text
files.

Capability parity with the reference's AsyncExecutor stack
(framework/async_executor.h:60 RunFromFile, executor_thread_worker.h:136,
data_feed.h:49 MultiSlotDataFeed + data_feed.proto, Python
async_executor.py:33): worker threads decouple file reading/parsing
from training, batching MultiSlot-format text lines and stepping the
model.

TPU-first redesign, not a thread-per-scope interpreter:
  * the program is compiled ONCE (whole-program XLA jit via the shared
    Executor cache); every worker calls the same compiled step — XLA
    executables are thread-safe and release the GIL, so parsing/batching
    genuinely overlaps device compute;
  * the reference's Hogwild-style racy in-place updates (each thread's
    op list writes the shared Scope) become atomic step-granular
    updates: a lock serializes the state transition.  Same async-CTR
    capability, no torn reads;
  * pslib pull/push (executor_thread_worker.h:195) lives in the sparse
    plane: paddle_tpu/sparse carries the big-table pull_rows/push_grads
    capability, parallel/sharded_embedding.py the in-HBM twin.

Streaming architecture (the sparse-plane rework of the old
one-queue-of-filenames loop):

  * **per-source readers** — every file gets its own producer thread
    parsing lines into its own BOUNDED queue (``queue_depth`` batches),
    so one slow/cold source backpressures only itself; queue depths
    ride the ``reader_buffer_depth`` gauge labeled per source (the
    input-pipeline anatomy the trainer path already publishes);
  * **round-robin consumers** — ``thread_num`` step workers drain the
    source queues round-robin, so a fast source can't starve the rest
    (the reference's MultiSlotDataFeed fairness);
  * **deterministic resume** — ``checkpoint_path`` persists, per
    source, a contiguous watermark of lines whose batch has COMPLETED
    its step (CRC-free JSON, atomic rename; out-of-order completions
    under several step workers park until the gap closes).  A
    restarted run fast-forwards each source past its watermark: no
    line is ever skipped, one step worker gives exactly-once, and with
    N workers the re-trained overlap is bounded by the in-flight
    window;
  * **first-failure propagation** — any step/parse error stops the
    whole pool promptly (producers and consumers observe a stop
    event), and ``run`` re-raises the FIRST error instead of letting a
    poisoned batch kill one thread while the rest train on.

File format (MultiSlotDataFeed, data_feed.h:224): per line, for each
slot in order: ``<n> v1 ... vn``; uint64 slots hold ids, float slots
hold dense values.
"""
from __future__ import annotations

import json
import os
import queue
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.enforce import EnforceNotMet
from ..core.place import CPUPlace, Place
from ..observability import metrics as obs_metrics
from .executor import Executor
from .program import Program

_m_rejected_lines = obs_metrics.counter(
    "datafeed_rejected_lines_total",
    "MultiSlot text lines rejected by DataFeedDesc.parse_line "
    "(short field counts, non-numeric ids, truncated slots).  In "
    "on_bad_line='skip' mode these lines are dropped and counted; in "
    "the default 'raise' mode the first one aborts the run AND "
    "counts.")
_m_buffer_depth = obs_metrics.gauge(
    "reader_buffer_depth",
    "Items queued in a reader.buffered() prefetch queue at its last "
    "consume, labeled per buffered() decorator (name= arg, or "
    "buffered<N> in creation order).",
    ("reader",))


class DataFeedParseError(EnforceNotMet, ValueError):
    """A malformed MultiSlot line: names the source/line/slot so the
    operator can open the offending shard at the offending byte,
    instead of an index error from deep inside a split() list.  Both an
    EnforceNotMet (framework invariant surface) and a ValueError
    (malformed user data)."""


class Slot:
    """One slot of a DataFeedDesc (ref data_feed.proto Slot)."""

    def __init__(self, name: str, type: str = "uint64",
                 is_dense: bool = False, is_used: bool = True,
                 dim: int = 1):
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used
        self.dim = dim        # fixed width the batch is padded/trimmed to


class DataFeedDesc:
    """MultiSlot text-feed description (ref python/paddle/fluid/
    data_feed_desc.py over data_feed.proto).  Built programmatically
    instead of via a .proto text file."""

    def __init__(self, slots: Sequence[Slot], batch_size: int = 32,
                 name: str = "multi_slot"):
        self.slots = list(slots)
        self.batch_size = int(batch_size)
        self.name = name

    def set_batch_size(self, bs: int):
        self.batch_size = int(bs)

    def set_use_slots(self, use_slots_name: Sequence[str]):
        used = set(use_slots_name)
        for s in self.slots:
            s.is_used = s.name in used

    def parse_line(self, line: str, lineno: Optional[int] = None,
                   source: Optional[str] = None):
        """One MultiSlot line -> {slot: np.ndarray(dim)} for used
        slots.  Malformed lines raise DataFeedParseError naming the
        source file, line number, slot and offending token — and bump
        ``datafeed_rejected_lines_total``."""
        where = ""
        if source is not None:
            where += f" in {source!r}"
        if lineno is not None:
            where += f" at line {lineno}"

        def bad(slot_name, why):
            _m_rejected_lines.inc()
            return DataFeedParseError(
                f"MultiSlot parse error{where}: slot {slot_name!r} "
                f"{why}: {line[:80]!r}")

        parts = line.split()
        out, i = {}, 0
        for slot in self.slots:
            if i >= len(parts):
                raise bad(slot.name, "missing (line ended early)")
            try:
                n = int(parts[i])
            except ValueError:
                raise bad(slot.name,
                          f"has non-integer value count {parts[i]!r}")
            if n < 0 or i + 1 + n > len(parts):
                raise bad(slot.name,
                          f"declares {n} values but the line ends "
                          f"early")
            vals = parts[i + 1:i + 1 + n]
            i += 1 + n
            if not slot.is_used:
                continue
            dtype = np.int64 if slot.type == "uint64" else np.float32
            try:
                arr = np.asarray(vals, dtype=dtype)
            except ValueError:
                kind = "id" if slot.type == "uint64" else "value"
                raise bad(slot.name, f"has a non-numeric {kind} among "
                                     f"{vals[:6]!r}")
            if arr.shape[0] < slot.dim:        # pad (ids with 0)
                arr = np.pad(arr, (0, slot.dim - arr.shape[0]))
            out[slot.name] = arr[:slot.dim]
        return out


class _Batch:
    """One collated batch plus its provenance (source, the producer's
    per-source sequence number, and the line count through its last
    line) — what the consumer commits to the stream checkpoint AFTER
    the step lands."""

    __slots__ = ("feed", "source", "seq", "end_line", "size")

    def __init__(self, feed, source, seq, end_line, size):
        self.feed = feed
        self.source = source
        self.seq = seq
        self.end_line = end_line
        self.size = size


class _FirstError:
    """First-failure latch: one error wins, everyone else observes the
    stop event and unwinds."""

    def __init__(self):
        self._lock = threading.Lock()
        self.exc: Optional[BaseException] = None
        self.stop = threading.Event()

    def trip(self, exc: BaseException):
        with self._lock:
            if self.exc is None:
                self.exc = exc
        self.stop.set()

    def raise_if_set(self):
        if self.exc is not None:
            raise self.exc


class StreamCheckpoint:
    """Per-source committed line offsets, atomically persisted.

    ``committed[source] = n`` means lines [0, n) of that source have
    COMPLETED a training step (not merely been parsed).  The persisted
    offset is a **contiguous watermark**: with several step workers,
    batch k+1 of a source can finish before batch k (queue dequeue
    order and step-lock acquisition order can invert), so completions
    park in a per-source pending map and the watermark only advances
    through gap-free sequence numbers — a crash can therefore never
    SKIP a line (the resume-safety contract).  With one step worker
    every line trains exactly once across a crash; with N workers the
    re-trained overlap is bounded by the in-flight window."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._lock = threading.Lock()
        self.committed: Dict[str, int] = {}
        # out-of-order completion parking: source -> {seq: end_line},
        # plus the next sequence number the watermark is waiting on
        self._pending: Dict[str, Dict[int, int]] = {}
        self._next_seq: Dict[str, int] = {}
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    doc = json.load(f)
                self.committed = {str(k): int(v) for k, v in
                                  doc.get("files", {}).items()}
            except (OSError, ValueError) as e:
                raise EnforceNotMet(
                    f"AsyncExecutor: stream checkpoint {path!r} is "
                    f"unreadable ({e}); delete it to restart the "
                    f"stream from zero") from e

    def resume_offset(self, source: str) -> int:
        with self._lock:
            return self.committed.get(source, 0)

    def commit(self, source: str, seq: int, end_line: int):
        """Record that the batch with per-source sequence `seq`
        (covering lines up to `end_line`) completed its step; persist
        the watermark if it advanced."""
        with self._lock:
            self._pending.setdefault(source, {})[seq] = end_line
            pend = self._pending[source]
            nxt = self._next_seq.get(source, 0)
            advanced = False
            while nxt in pend:
                line = pend.pop(nxt)
                nxt += 1
                if line > self.committed.get(source, 0):
                    self.committed[source] = line
                    advanced = True
            self._next_seq[source] = nxt
            if not advanced or not self.path:
                return
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"files": dict(self.committed)}, f)
            os.replace(tmp, self.path)


class AsyncExecutor:
    """ref async_executor.py:33 / async_executor.h:60.

    run(program, data_feed, filelist, thread_num, fetch) streams every
    file once (one 'epoch' in reference terms) through per-source
    bounded queues and returns per-fetch running means.  Metrics from
    every worker step are folded into the totals under the update
    lock."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or CPUPlace()
        self.executor = Executor(self.place)

    def run_startup_program(self, program: Program):
        self.executor.run(program)

    def run(self, program: Program, data_feed: DataFeedDesc,
            filelist: Sequence[str], thread_num: int,
            fetch: Sequence[str], mode: str = "", debug: bool = False,
            queue_depth: int = 8,
            checkpoint_path: Optional[str] = None,
            on_bad_line: str = "raise",
            step_fn=None):
        """Stream ``filelist`` through the compiled program once.

        queue_depth:       bounded batches buffered PER SOURCE — the
                           backpressure window (reader_buffer_depth).
        checkpoint_path:   persist per-source committed line offsets
                           after every step; an existing file resumes
                           the stream past already-trained lines.
        on_bad_line:       "raise" (default) aborts on the first
                           malformed line; "skip" drops it and counts
                           it in datafeed_rejected_lines_total.
        step_fn:           override the executor step (signature
                           ``step_fn(feed) -> {fetch: value}``) — the
                           sparse-plane worker reuses this loop with a
                           pull/compute/push body instead of
                           Executor.run.
        """
        if thread_num <= 0:
            raise EnforceNotMet("AsyncExecutor: thread_num must be > 0")
        if on_bad_line not in ("raise", "skip"):
            raise EnforceNotMet(
                f"AsyncExecutor: on_bad_line must be 'raise' or "
                f"'skip', got {on_bad_line!r}")
        missing = [f for f in filelist if not os.path.exists(f)]
        if missing:
            raise EnforceNotMet(f"AsyncExecutor: missing files {missing}")

        ckpt = StreamCheckpoint(checkpoint_path)
        err = _FirstError()
        fetch = list(fetch)
        update_lock = threading.Lock()
        totals = {n: 0.0 for n in fetch}
        counts = {n: 0 for n in fetch}
        sources = list(filelist)
        queues: Dict[str, "queue.Queue[Optional[_Batch]]"] = {
            s: queue.Queue(maxsize=max(1, int(queue_depth)))
            for s in sources}
        gauges = {s: _m_buffer_depth.labels(
            reader=f"async_executor:{os.path.basename(s)}")
            for s in sources}

        def produce(source: str):
            """Parse one source into its bounded queue; a None sentinel
            marks exhaustion."""
            q = queues[source]
            try:
                skip = ckpt.resume_offset(source)
                batch: List[Dict[str, np.ndarray]] = []
                lineno = 0
                seq = 0
                with open(source) as fh:
                    for raw in fh:
                        if err.stop.is_set():
                            return
                        lineno += 1
                        if lineno <= skip:
                            continue
                        line = raw.strip()
                        if not line:
                            continue
                        try:
                            row = data_feed.parse_line(
                                line, lineno=lineno, source=source)
                        except DataFeedParseError:
                            if on_bad_line == "skip":
                                continue
                            raise
                        batch.append(row)
                        if len(batch) == data_feed.batch_size:
                            _put(q, _Batch(_collate(batch), source,
                                           seq, lineno, len(batch)))
                            seq += 1
                            batch = []
                if batch:
                    _put(q, _Batch(_collate(batch), source, seq,
                                   lineno, len(batch)))
            except BaseException as e:
                err.trip(e)
            finally:
                _put(q, None)

        def _put(q, item):
            """Bounded put that keeps observing the stop event, so a
            failed consumer can't strand a blocked producer forever."""
            while not err.stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def _collate(batch):
            return {k: np.stack([b[k] for b in batch])
                    for k in batch[0]}

        def default_step(feed):
            outs = self.executor.run(program, feed=feed,
                                     fetch_list=fetch)
            return dict(zip(fetch, outs))

        body = step_fn or default_step
        live = {s: True for s in sources}
        live_lock = threading.Lock()

        def consume(wid: int):
            """Round-robin over the live source queues: step each
            batch, fold metrics, commit the source offset."""
            my = sources[wid % len(sources):] + \
                sources[:wid % len(sources)]   # stagger start points
            try:
                while not err.stop.is_set():
                    with live_lock:
                        alive = [s for s in my if live[s]]
                    if not alive:
                        return
                    for s in alive:
                        try:
                            item = queues[s].get(timeout=0.02)
                        except queue.Empty:
                            continue
                        gauges[s].set(queues[s].qsize())
                        if item is None:
                            with live_lock:
                                live[s] = False
                            continue
                        # serialize the state transition (XLA compute
                        # inside still overlaps other threads' parsing:
                        # the GIL drops during execution)
                        with update_lock:
                            outs = body(item.feed)
                            for n, v in outs.items():
                                totals[n] += float(np.mean(v))
                                counts[n] += 1
                            ckpt.commit(item.source, item.seq,
                                        item.end_line)
                        if debug:
                            print(f"[async_executor] w{wid} stepped "
                                  f"{item.source}:{item.end_line}")
            except BaseException as e:   # first failure wins
                err.trip(e)

        producers = [threading.Thread(target=produce, args=(s,),
                                      daemon=True,
                                      name=f"feed-{os.path.basename(s)}")
                     for s in sources]
        consumers = [threading.Thread(target=consume, args=(i,),
                                      daemon=True,
                                      name=f"async-step-{i}")
                     for i in range(thread_num)]
        for t in producers + consumers:
            t.start()
        for t in consumers:
            t.join()
        # consumers are done (drained or tripped); producers unwind on
        # the same stop event or have already sent their sentinel
        err.stop.set()
        for t in producers:
            t.join(timeout=5.0)
        err.raise_if_set()
        if fetch and all(c == 0 for c in counts.values()):
            resumed = any(ckpt.resume_offset(s) > 0 for s in sources)
            if not resumed:
                raise EnforceNotMet(
                    "AsyncExecutor: filelist has no samples")
        return {n: totals[n] / max(counts[n], 1) for n in fetch}
