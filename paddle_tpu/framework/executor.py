"""Executor: lowers a Program to ONE jitted XLA function and runs it.

Capability parity with the reference's Executor (/root/reference/paddle/fluid/
framework/executor.h:47, run loop executor.cc:413-472) + its Python wrapper
(python/paddle/fluid/executor.py:256, program cache :207), and the Scope
(framework/scope.h:42).

TPU-first design — the key architectural departure from the reference:

  reference:  for op in program: dispatch kernel; GC dead tensors   (interpreter)
  here:       trace ALL ops into one function -> jax.jit -> XLA     (compiler)

Consequences, mapped to reference machinery this replaces:
  * per-op kernel dispatch + data transform  -> XLA op fusion/layout
  * garbage collector / eager deletion       -> XLA liveness + buffer donation
    (donate_argnums on the persistable state: params are updated "in place"
    in HBM, the analogue of scope-buffered reuse, executor.cc:433-455)
  * feed/fetch ops (executor.cc:299-370)     -> function inputs/outputs
  * program cache keyed by feed/fetch        -> jit cache keyed by
    (program version, feed shapes/dtypes, fetch names, state signature)

The `autodiff` pseudo-op (inserted by framework/backward.py) is handled here:
the forward segment is re-traced under jax.vjp so every `X@GRAD` var becomes a
real array in the environment — optimizer update ops then consume them exactly
like the reference's in-program optimizer ops (operators/optimizers/).
"""
from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags, jax_compat
from ..core.dtypes import to_jnp_dtype
from ..core.enforce import EnforceNotMet, check_arg
from ..core.place import Place, default_place
from ..core.profiler import RecordEvent
from ..observability import costmodel as obs_cost
from ..observability import flight as obs_flight
from ..observability import forensics as obs_forensics
from ..observability import metrics as obs_metrics
from ..observability import tensorstats as obs_tensorstats
from ..observability import trace as obs_trace
from ..observability import tracectx as obs_tracectx
from ..resilience import chaos
from .program import Program, Variable, default_main_program
from .registry import LowerContext, get_op_def

# --- telemetry (observability/metrics.py): the executor is the hottest ---
# --- producer; every perf PR regresses against these series             ---
_m_compile = obs_metrics.counter(
    "executor_compile_total",
    "Program compilations (jit cache misses) in the executor.", ("kind",))
_m_cache_hit = obs_metrics.counter(
    "executor_cache_hit_total",
    "Executor compiled-program cache hits.")
_m_cache_miss = obs_metrics.counter(
    "executor_cache_miss_total",
    "Executor compiled-program cache misses (each one compiles).")
_m_multi_hit = obs_metrics.counter(
    "executor_multi_cache_hit_total",
    "run_steps device-loop (_multi_cache) hits.")
_m_multi_miss = obs_metrics.counter(
    "executor_multi_cache_miss_total",
    "run_steps device-loop (_multi_cache) misses (each one compiles).")
_m_recompile_storm = obs_metrics.counter(
    "executor_recompile_storm_total",
    "Times a (program, fetch-list) key crossed the recompile-warn "
    "threshold (PTPU_RECOMPILE_WARN_THRESHOLD), by the dominant "
    "diagnosed drift cause (observability/forensics.py).", ("cause",))
_m_step_seconds = obs_metrics.histogram(
    "executor_step_seconds",
    "Host wall time of one executor step dispatch (async: excludes "
    "device completion; first call per cache key includes compile).",
    ("mode",))
_m_op_seconds = obs_metrics.histogram(
    "executor_op_seconds",
    "Per-op wall time in interpreted (eager) mode; enable with "
    "PTPU_PROFILE_OPS=1.", ("op",))
_m_cached_programs = obs_metrics.gauge(
    "executor_cached_programs",
    "Compiled programs resident across this process's executor caches.")

# True only inside an eager (un-jitted) _step with PTPU_PROFILE_OPS on —
# per-op wall timings are meaningful only there (traced values have no
# runtime; the jitted path is one fused XLA computation).  Thread-local:
# AsyncExecutor feeder threads run concurrently and must not see another
# thread's profiling window.
_profile_state = threading.local()


def _profiling_ops() -> bool:
    return getattr(_profile_state, "active", False)

def _pp_micro_split(env, data_names, M, stage_ops, axis):
    """Shared pipeline prologue: stage-count check + reshape every data
    feed to [M, B/M, ...] microbatch slabs (popped out of env)."""
    Pn = jax_compat.axis_size(axis)
    check_arg(len(stage_ops) == Pn,
              f"program has {len(stage_ops)} pipeline stages but mesh "
              f"axis {axis!r} has {Pn} devices")
    micro = {}
    for n in data_names:
        a = env.pop(n)
        check_arg(a.shape[0] % M == 0,
                  f"feed {n!r} batch {a.shape[0]} not divisible by "
                  f"n_microbatches {M}")
        micro[n] = a.reshape((M, a.shape[0] // M) + a.shape[1:])
    return Pn, micro


def _pp_stage_fn(ctx, env, stage_ops, b_names, loss_name, Pn, s):
    """The per-stage forward both pipeline schedules share:
    g(x_act, extra_env, mfeeds, fold_idx) -> (payload_out, loss).
    fold_idx keys the per-(stage, microbatch) RNG root — without it
    every microbatch would reuse the single trace-time dropout mask
    (ops draw keys from a trace-side counter).  Outputs DEPEND on
    traced values even when dummy (constant zeros give cond branches
    different known/unknown partitions and jax's partial-eval asserts,
    seen with dropout active on the gpipe plane)."""
    def g(x_act, extra_env, mfeeds, fold_idx):
        tctx = LowerContext(jax.random.fold_in(ctx._root_key, fold_idx),
                            is_test=ctx.is_test, mesh=ctx.mesh)
        tctx.place = ctx.place
        tctx.program = getattr(ctx, "program", None)
        tctx.cp_axis = getattr(ctx, "cp_axis", None)
        tctx.ep_axis = getattr(ctx, "ep_axis", None)
        senv = dict(env)
        senv.update(extra_env)
        senv.update(mfeeds)
        if s > 0:
            for nm, a in zip(b_names[s - 1], x_act):
                senv[nm] = a
        senv = run_ops_in_env(tctx, senv, stage_ops[s])
        if s < Pn - 1:
            out = tuple(senv[nm] for nm in b_names[s])
            zloss = (out[0].ravel()[0] * 0.0).astype(jnp.float32)
            return out, zloss
        loss = senv[loss_name].reshape(()).astype(jnp.float32)
        return (jax.tree.map(
            lambda a: a * jnp.zeros((), a.dtype), x_act), loss)
    return g


def _pp_probe_act(ctx, env, stage_ops, b_names, micro, extra_env=None):
    """Payload shape/dtype structure of the boundary, via eval_shape of
    stage 0."""
    def probe(mfeeds):
        senv = dict(env)
        senv.update(extra_env or {})
        senv.update(mfeeds)
        senv = run_ops_in_env(ctx, senv, stage_ops[0])
        return tuple(senv[nm] for nm in b_names[0])
    return jax.eval_shape(probe, {n: micro[n][0] for n in micro})


def _pp_forward(ctx, env, stage_ops, b_names, loss_name, axis, M,
                data_names):
    """GPipe schedule over the `axis` mesh axis (PipelineTranspiler
    plane): M microbatches tick through a lax.scan; each device runs its
    own stage (lax.switch on its axis index) over the forward sub-op
    lists and ppermutes the boundary activation onward.  Bubble ticks
    are masked from the loss.  Differentiating through the scan yields
    the reversed-pipeline backward for free; the per-stage gradients
    are disjoint and summed by the transpiler's c_allreduce_sum ops."""
    Pn, micro = _pp_micro_split(env, data_names, M, stage_ops, axis)

    def branch(s):
        g = _pp_stage_fn(ctx, env, stage_ops, b_names, loss_name, Pn, s)

        def f(x_act, mfeeds, t):
            return g(x_act, {}, mfeeds, t)
        # GPipe memory contract: per tick only the boundary payload
        # is saved; stage internals rematerialize in the backward
        return jax.checkpoint(f)

    act = _pp_probe_act(ctx, env, stage_ops, b_names, micro)
    branches = [branch(s) for s in range(Pn)]
    pp_r = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % Pn) for i in range(Pn)]

    def tick(carry, t):
        state, loss_acc = carry
        # stage s processes microbatch t - s at tick t
        my_idx = jnp.clip(t - pp_r, 0, M - 1)
        mfeeds = {n: jax.lax.dynamic_index_in_dim(micro[n], my_idx, 0,
                                                  keepdims=False)
                  for n in micro}
        out, lval = jax.lax.switch(pp_r, branches, state, mfeeds, t)
        o_idx = t - (Pn - 1)
        valid = (pp_r == Pn - 1) & (o_idx >= 0) & (o_idx < M)
        loss_acc = loss_acc + jnp.where(valid, lval, 0.0)
        nxt = jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis, perm), out)
        return (nxt, loss_acc), None

    state0 = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), act)
    (_, loss_acc), _ = jax.lax.scan(
        tick, (state0, jnp.zeros((), jnp.float32)),
        jnp.arange(M + Pn - 1))
    # LOCAL per-device loss (nonzero on the last stage only).  Keeping
    # the psum OUT of the differentiated region matters: differentiating
    # through psum under shard_map seeds every device's cotangent with
    # the axis-summed value (Pn x too large); with a local loss the
    # ppermute transposes alone carry the cotangents back along the
    # ring, giving each stage exactly its own gradient.  The caller
    # psums the returned value for the (replicated) fetch.
    return loss_acc / M


def _pp_1f1b(ctx, env, stage_ops, b_names, loss_name, axis, M,
             data_names, params):
    """Non-interleaved 1F1B (PipeDream-Flush) schedule: same math as
    _pp_forward's GPipe, but the backward of microbatch m runs at tick
    2P-1-s+m — right behind its forward — so each device buffers at
    most ~2P boundary INPUTS instead of the scan-vjp's M-tick carry
    history.  The backward is explicit: each tick's B-phase re-runs the
    stage under jax.vjp from the buffered input (stages rematerialize
    anyway) with the cotangent that just arrived on the reverse ring;
    masking the vjp SEED by schedule validity makes inactive ticks
    contribute exact zeros (cotangent-linearity), so no buffer-wide
    masking of gradients is needed.

    Returns (local mean loss, {param: grad}) — grads are the stage's
    own contributions; the transpiler's pipe-axis allreduce assembles
    the full gradient exactly as in the GPipe plane."""
    Pn, micro = _pp_micro_split(env, data_names, M, stage_ops, axis)
    param_names = set(params)
    stage_pnames = []
    for ops in stage_ops:
        used = {n for op in ops for ns in op.inputs.values() for n in ns}
        stage_pnames.append(sorted(used & param_names))

    act = _pp_probe_act(ctx, env, stage_ops, b_names, micro,
                        extra_env={n: params[n]
                                   for n in stage_pnames[0]})
    zeros_of = lambda tree: jax.tree.map(
        lambda a: jnp.zeros(a.shape, a.dtype), tree)
    # integer/bool payload leaves (e.g. token ids riding the cut) are
    # not differentiable: their vjp cotangents are float0 (which cannot
    # ride the scan carry or ppermute) — seed them with float0 zeros
    # and carry plain int zeros in their ct slots
    act_leaves = jax.tree.leaves(act)
    _inexact = [jnp.issubdtype(a.dtype, jnp.inexact) for a in act_leaves]

    def ct_seed(ct_tree, scale):
        return jax.tree.unflatten(
            jax.tree.structure(act),
            [c * scale.astype(c.dtype) if ok
             else np.zeros(a.shape, jax.dtypes.float0)
             for c, a, ok in zip(jax.tree.leaves(ct_tree), act_leaves,
                                 _inexact)])

    def ct_carryable(ct_tree):
        return jax.tree.unflatten(
            jax.tree.structure(act),
            [c if ok else jnp.zeros(a.shape, a.dtype)
             for c, a, ok in zip(jax.tree.leaves(ct_tree), act_leaves,
                                 _inexact)])
    BUF = 2 * Pn
    pp_r = jax.lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % Pn) for i in range(Pn)]
    bwd_perm = [(i, (i - 1) % Pn) for i in range(Pn)]
    grads0 = {n: jnp.zeros(jnp.shape(params[n]),
                           jax.dtypes.result_type(params[n]))
              for n in params}

    def branch(s):
        g = _pp_stage_fn(ctx, env, stage_ops, b_names, loss_name, Pn, s)
        pn_s = stage_pnames[s]

        def tickwork(fwd_state, ct_state, buf, grads, loss_acc, t):
            p_sub = {n: params[n] for n in pn_s}
            # ---- F phase: microbatch m_f = t - s -------------------
            m_f = t - s
            f_valid = (m_f >= 0) & (m_f < M)
            m_fc = jnp.clip(m_f, 0, M - 1)
            feeds_f = {n: jax.lax.dynamic_index_in_dim(
                micro[n], m_fc, 0, keepdims=False) for n in micro}
            y, loss = g(fwd_state, p_sub, feeds_f, s + m_fc)
            loss_acc = loss_acc + jnp.where(
                f_valid & (s == Pn - 1), loss, 0.0)
            # buffer this microbatch's stage INPUT for its backward
            slot = m_fc % BUF
            buf = jax.tree.map(
                lambda b, x: jnp.where(
                    f_valid,
                    jax.lax.dynamic_update_index_in_dim(b, x, slot, 0),
                    b),
                buf, fwd_state)
            # ---- B phase: microbatch m_b = t - (2P-1-s) ------------
            m_b = t - (2 * Pn - 1 - s)
            b_valid = (m_b >= 0) & (m_b < M)
            m_bc = jnp.clip(m_b, 0, M - 1)
            feeds_b = {n: jax.lax.dynamic_index_in_dim(
                micro[n], m_bc, 0, keepdims=False) for n in micro}
            x_in = jax.tree.map(
                lambda b: jax.lax.dynamic_index_in_dim(
                    b, m_bc % BUF, 0, keepdims=False), buf)
            _, vjp_fn = jax.vjp(
                lambda x, p: g(x, p, feeds_b, s + m_bc), x_in, p_sub)
            scale = b_valid.astype(jnp.float32)
            if s == Pn - 1:
                seed = (ct_seed(zeros_of(act), scale), scale / M)
            else:
                seed = (ct_seed(ct_state, scale),
                        jnp.zeros((), jnp.float32))
            ct_x, g_sub = vjp_fn(seed)
            # the zero seed gives zero cotangents only for FINITE
            # Jacobians; an op like log/rsqrt evaluated on the zero
            # warm-up buffer yields 0 * inf = NaN, so mask the results
            # by validity too (0-cost: select fuses)
            ct_x = jax.tree.map(
                lambda c: jnp.where(b_valid, c, jnp.zeros_like(c)),
                ct_carryable(ct_x))
            gd = dict(grads)
            for n in pn_s:
                gd[n] = gd[n] + jnp.where(
                    b_valid, g_sub[n], jnp.zeros_like(g_sub[n])
                ).astype(gd[n].dtype)
            return y, ct_x, buf, gd, loss_acc

        return tickwork

    branches = [branch(s) for s in range(Pn)]

    def tick(carry, t):
        fwd_state, ct_state, buf, grads, loss_acc = carry
        y, ct_x, buf, grads, loss_acc = jax.lax.switch(
            pp_r, branches, fwd_state, ct_state, buf, grads, loss_acc,
            t)
        nxt_f = jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis, fwd_perm), y)
        nxt_b = jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis, bwd_perm), ct_x)
        return (nxt_f, nxt_b, buf, grads, loss_acc), None

    state0 = zeros_of(act)
    buf0 = jax.tree.map(
        lambda a: jnp.zeros((BUF,) + a.shape, a.dtype), act)
    (_, _, _, grads, loss_acc), _ = jax.lax.scan(
        tick, (state0, zeros_of(act), buf0, grads0,
               jnp.zeros((), jnp.float32)),
        jnp.arange(M + 2 * Pn - 1))
    # LOCAL loss (nonzero on the last stage) — the caller psums, same
    # contract as _pp_forward
    return loss_acc / M, grads


def _data_feed_spec(program, var, axis):
    """PartitionSpec for a data-var feed on a transpiled program: shard
    dim `_dist_feed_shard_dim` (0 = batch; context-parallel programs set
    1 = sequence) over `axis`.  Pipeline-ONLY programs (pp axis, no
    spmd axis) replicate feeds — each pipe rank micro-splits the full
    batch itself.  Feeds of lower rank (per-example aux vars) stay
    replicated.  Single source of truth for the compiled step's
    in_specs AND the multi-process feed globalization — the two must
    agree or in_shardings mismatch."""
    P = jax.sharding.PartitionSpec
    if (axis is None
            or (getattr(program, "_dist_spmd_axis", None) is None
                and getattr(program, "_dist_pp_axis", None) is not None)):
        return P()
    feed_dim = int(getattr(program, "_dist_feed_shard_dim", 0))
    rank = len(var.shape) if var.shape else 0
    if feed_dim >= rank:
        return P()
    return P(*([None] * feed_dim + [axis]))


# Ops that are pure bookkeeping at the program level; the executor itself
# implements their semantics (or they have none at run time).
_STRUCTURAL_OPS = ("feed", "fetch", "data")


class Scope:
    """name -> device array store for persistable vars (ref scope.h:42).
    Hierarchical: child scopes see parent vars (used by Trainer/tests)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self.parent = parent

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def set_var(self, name: str, value):
        self._vars[name] = value

    def has_var(self, name):
        return self.find_var(name) is not None

    def drop_var(self, name: str):
        self._vars.pop(name, None)

    def var_names(self) -> List[str]:
        names = set(self._vars)
        if self.parent:
            names |= set(self.parent.var_names())
        return sorted(names)

    def new_scope(self) -> "Scope":
        return Scope(parent=self)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _as_device_array(value, var: Optional[Variable], device):
    if isinstance(value, jax.Array):
        return value
    arr = np.asarray(value)
    if var is not None and var.dtype is not None:
        arr = arr.astype(to_jnp_dtype(var.dtype))
    return jax.device_put(arr, device)


def run_ops_in_env(ctx, env: Dict[str, Any], ops) -> Dict[str, Any]:
    """Shared lowering loop: trace `ops` against env, writing outputs back.
    Control-flow ops (ops/control_flow.py) recurse into this for their
    sub-blocks.  ctx.env always points at the innermost live env."""
    for op in ops:
        if op.type in _STRUCTURAL_OPS:
            continue
        opdef = get_op_def(op.type)
        ins = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                if n not in env:
                    raise EnforceNotMet(
                        f"op {op.type!r} input {slot}:{n!r} is not "
                        f"materialised; feed it or run its producer")
                vals.append(env[n])
            ins[slot] = vals
        prev_env = getattr(ctx, "env", None)
        ctx.env = env
        if _profiling_ops():
            t_op = time.perf_counter()
            outs = opdef.lower(ctx, ins, op.attrs)
            jax.block_until_ready(outs)
            dt = time.perf_counter() - t_op
            _m_op_seconds.labels(op=op.type).observe(dt)
            obs_trace.add_span(f"op:{op.type}", t_op, dt,
                               tid=obs_trace.OP_TID, cat="op")
        else:
            outs = opdef.lower(ctx, ins, op.attrs)
        ctx.env = prev_env
        for slot, names in op.outputs.items():
            produced = outs.get(slot, [])
            for n, v in zip(names, produced):
                if n:
                    env[n] = v
        if chaos.var_sites_armed():
            # chaos site family executor.var.<name>: NaN/Inf-poison a
            # NAMED variable inside the step — the deterministic "this
            # layer went bad" injection first-bad-layer attribution is
            # tested against.  On the jitted path the decision lands at
            # trace time (baked into the executable); eager/per-op
            # modes decide per step.
            for slot, names in op.outputs.items():
                produced = list(outs.get(slot, []))
                poisoned = False
                for i, n in enumerate(names[:len(produced)]):
                    if n and n in env:
                        pv = chaos.poison_value(
                            f"executor.var.{n}", env[n])
                        if pv is not env[n]:
                            env[n] = pv
                            # keep `outs` in sync: the per-op NaN
                            # localizer below inspects outs, and it
                            # must blame the poisoned PRODUCER, not
                            # the first downstream consumer
                            produced[i] = pv
                            poisoned = True
                if poisoned:
                    outs[slot] = produced
        if flags.get_flag("check_nan_inf_per_op"):
            _check_op_outputs_finite(op, outs)
    return env


def _check_op_outputs_finite(op, outs):
    """Per-op NaN/Inf localization (ref operator.cc:829) — only effective
    when the values are concrete (the executor runs the program eagerly
    under FLAGS_check_nan_inf_per_op; traced values are skipped).
    NaNs born inside the backward re-trace surface at the `autodiff`
    pseudo-op, whose outputs (the named grad vars) are concrete here —
    so a gradient NaN is attributed to autodiff + the grad var name, not
    to a forward op."""
    for slot, vals in outs.items():
        for name, v in zip(op.outputs.get(slot, []), vals):
            if isinstance(v, jax.core.Tracer):
                continue
            try:
                arr = np.asarray(v)
            except Exception:
                continue
            if (np.issubdtype(arr.dtype, np.floating)
                    and not np.isfinite(arr).all()):
                raise EnforceNotMet(
                    f"NaN/Inf produced by op {op.type!r} in output "
                    f"{slot}:{name!r} (FLAGS_check_nan_inf_per_op)")


class _CompiledProgram:
    """One (program-version, feed-sig, fetch-list, state-sig) -> jitted fn."""

    def __init__(self, program: Program, feed_names, fetch_names,
                 in_state_names, persist_names, place: Place, donate: bool,
                 mesh=None, batch_axis: str = "data",
                 collect_stats: bool = False):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.in_state_names = list(in_state_names)
        self.place = place
        self.mesh = mesh
        ops = program.global_block().ops
        self._ops = [op for op in ops if op.type not in _STRUCTURAL_OPS]
        # tensorstats variant (observability/tensorstats.py): the step
        # additionally packs per-variable fused-reduction statistics and
        # fetches them under a reserved name Executor.run pops back off.
        # A separate cache key (the tensor_stats flags entry) selects
        # this variant, so the plain executable stays byte-identical.
        self.collect_stats = bool(collect_stats)
        self._stats_order: List[str] = []
        self._stats_names: List[str] = []
        if self.collect_stats:
            self._stats_order = obs_tensorstats.stats_order(
                self._ops, self.feed_names, self.in_state_names)
            self.fetch_names.append(obs_tensorstats.FETCH_NAME)
        # persistables that will exist in env after the run: inputs plus
        # anything an op writes — fixed at compile time so the output pytree
        # (and its shardings) are static.
        written = {n for op in self._ops
                   for names in op.outputs.values() for n in names}
        self.out_state_names = [n for n in persist_names
                                if n in set(in_state_names) or n in written]
        ad_idx = [i for i, op in enumerate(self._ops) if op.type == "autodiff"]
        check_arg(len(ad_idx) <= 1,
                  "at most one autodiff op per program is supported")
        self._ad_idx = ad_idx[0] if ad_idx else None
        if getattr(program, "_dist_pp_axis", None) is not None \
                and self._ad_idx is not None:
            # pipeline plane: stage internals live INSIDE the microbatch
            # scan — validate up front instead of a raw KeyError deep in
            # tracing (only the loss and persistable state are visible
            # downstream, transpiler/pipeline.py module docstring)
            loss = self._ops[self._ad_idx].attrs["loss"]
            persist = set(persist_names)
            for n in self.fetch_names:
                if n != loss and n not in persist:
                    raise EnforceNotMet(
                        f"fetch {n!r} is not available under the "
                        f"pipeline plane: stage internals live inside "
                        f"the microbatch scan; fetch the loss "
                        f"({loss!r}) or persistable state instead")
        jit_kwargs = {"donate_argnums": (0,) if donate else ()}
        # donate-feeds twin executable (trainer prefetch path: fresh
        # device feed buffers every step are safe to donate) — built
        # lazily from the same step fn + jit kwargs
        self._jitted_donate = None
        self._multi_cache: Dict[tuple, Any] = {}
        # persistent executable cache (framework/jit_cache.py): when
        # the jit_cache_dir flag is set, dispatch goes through an AOT
        # jax.stages.Compiled — deserialized from disk on a warm start
        # (zero XLA work), or lower().compile()d + stored on a cold
        # one.  _persist_meta = (key components, entry hash) of the
        # step entry; _multi_jit keeps the lowerable jit twin of a
        # deserialized run_steps loop for the cost model.
        self._aot = None
        self._persist_meta: Optional[tuple] = None
        self._persist_pending = False
        self._persist_verified = False
        self._persist_source: Optional[str] = None
        # donate-feeds twin (trainer prefetch path): its own persistent
        # entry — key = step components + {"donate_feeds": True} — so a
        # warm prefetch restart deserializes BOTH executables and
        # records zero compiles (PR 12 follow-up)
        self._aot_donate = None
        self._persist_pending_donate = False
        # _prepare's probes already MISSED these keys (don't re-probe
        # and double-count the miss in the _materialize_* resolvers)
        self._donate_probe_missed = False
        self._plain_probe_missed = False
        self._donate_source: Optional[str] = None
        self._multi_jit: Dict[tuple, Any] = {}
        # cost-model plane (observability/costmodel.py): abstract args
        # are noted at first dispatch (ShapeDtypeStructs — no device
        # buffers pinned), analysis is lazy and cached
        self._abs_args: Optional[tuple] = None
        self._cost = None
        self._tried_analytic = False
        self._tried_xla = False
        self._multi_abs: Dict[tuple, tuple] = {}
        self._multi_cost: Dict[tuple, Any] = {}
        self._state_sharding_fn = None
        self._feed_sharding_fn = None
        spmd_axis = getattr(program, "_dist_spmd_axis", None)
        pp_axis = getattr(program, "_dist_pp_axis", None)
        # implicit-SPMD plane only (jit + out_shardings, no shard_map):
        # random-generation ops constrain their draw to REPLICATED
        # before GSPMD reshards it, because the legacy threefry lowering
        # produces DIFFERENT values when the partitioner splits the
        # generation (a ("model", None)-sharded Parameter's
        # uniform_random init would diverge from the single-device run
        # and break every single-vs-mesh parity contract).  Inside
        # shard_map the axes are manual and per-device draws are
        # deliberate — no constraint there.
        self._implicit_mesh = mesh if (spmd_axis is None
                                       and pp_axis is None) else None
        if (spmd_axis is not None or pp_axis is not None) and mesh is None:
            raise EnforceNotMet(
                f"this program was rewritten by DistributeTranspiler/"
                f"PipelineTranspiler (collectives over axis "
                f"{spmd_axis if spmd_axis is not None else pp_axis!r}); "
                f"run it with Executor(place, mesh=...) so the axis is "
                f"in scope")
        if mesh is not None and (spmd_axis is not None
                                 or pp_axis is not None):
            # Explicit-collective SPMD (the DistributeTranspiler /
            # PipelineTranspiler plane): the program carries its own
            # c_allreduce/scale ops (the reference's nccl2-mode
            # transformation), so run the step under shard_map with the
            # axes in scope instead of leaving collective insertion to
            # XLA sharding propagation.
            P = jax.sharding.PartitionSpec
            for ax in (spmd_axis, pp_axis):
                if ax is not None and ax not in mesh.shape:
                    raise EnforceNotMet(
                        f"program was transpiled over axis {ax!r} but "
                        f"the mesh axes are {tuple(mesh.shape)}; build "
                        f"the mesh with that axis name (or transpile "
                        f"with axis_name matching the mesh)")
            n_expect = getattr(program, "_dist_trainers", None)
            if spmd_axis is not None:
                axis_size = int(mesh.shape[spmd_axis])
                if n_expect is not None and n_expect != axis_size:
                    raise EnforceNotMet(
                        f"program was transpiled for {n_expect} trainers "
                        f"but mesh axis {spmd_axis!r} has {axis_size} "
                        f"devices")
            if pp_axis is not None:
                deg = getattr(program, "_pp_degree", None)
                if deg and deg != int(mesh.shape[pp_axis]):
                    raise EnforceNotMet(
                        f"program was pipelined for {deg} stages but "
                        f"mesh axis {pp_axis!r} has "
                        f"{int(mesh.shape[pp_axis])} devices")
            block = program.global_block()

            def feed_spec(name):
                # context-parallel programs shard feeds along the
                # SEQUENCE dim; pipeline-only programs replicate feeds
                # (the shared rule lives in _data_feed_spec)
                if block.has_var(name) and block.var(name).is_data:
                    return _data_feed_spec(program, block.var(name),
                                           spmd_axis)
                return P()

            def state_spec(name):
                # params annotated by the tp/cp transpilers shard over
                # the mesh; everything else is replicated
                if block.has_var(name):
                    s = getattr(block.var(name), "sharding", None)
                    if s is not None:
                        return P(*s)
                return P()

            inner = self._step

            def spmd_step(state, feeds, key):
                # distinct randomness per shard (dropout etc.), like the
                # single-trace path where each example draws its own mask
                for ax in (spmd_axis, pp_axis):
                    if ax is not None:
                        key = jax.random.fold_in(
                            key, jax.lax.axis_index(ax))
                fetches, new_state = inner(state, feeds, key)
                # per-shard fetches gain a leading shard axis on the host
                return [jnp.asarray(f)[None] for f in fetches], new_state

            fetch_axis = spmd_axis if spmd_axis is not None else pp_axis
            sm_kwargs = dict(
                mesh=mesh,
                in_specs=({n: state_spec(n) for n in self.in_state_names},
                          {n: feed_spec(n) for n in self.feed_names},
                          P()),
                out_specs=([P(fetch_axis)] * len(self.fetch_names),
                           {n: state_spec(n)
                            for n in self.out_state_names}))
            sm = jax_compat.shard_map(spmd_step, check_rep=False,
                                      **sm_kwargs)
            self._step_fn = sm
            self._jit_kwargs = jit_kwargs
            self._jitted = jax.jit(sm, **jit_kwargs)
            return
        if mesh is not None:
            # SPMD plane: feeds shard along the batch axis, persistable
            # state follows each Parameter's PartitionSpec (replicated by
            # default).  XLA inserts the gradient psum/collectives — this
            # is the whole of the reference's ParallelExecutor SSA-graph +
            # NCCL machinery (multi_devices_graph_pass.cc,
            # all_reduce_op_handle.cc).
            P = jax.sharding.PartitionSpec
            ns = lambda spec: jax.sharding.NamedSharding(mesh, spec)
            block = program.global_block()

            def state_spec(name):
                if block.has_var(name):
                    spec = getattr(block.var(name), "sharding", None)
                    if spec is not None:
                        return ns(P(*spec))
                return ns(P())

            def feed_spec(name):
                if block.has_var(name):
                    v = block.var(name)
                    if getattr(v, "sharding", None) is not None:
                        return ns(P(*v.sharding))
                    if v.is_data:
                        return ns(P(batch_axis))
                return ns(P())

            jit_kwargs["in_shardings"] = (
                {n: state_spec(n) for n in self.in_state_names},
                {n: feed_spec(n) for n in self.feed_names},
                ns(P()))
            jit_kwargs["out_shardings"] = (
                None, {n: state_spec(n) for n in self.out_state_names})
            self._state_sharding_fn = state_spec
            self._feed_sharding_fn = feed_spec
        self._step_fn = self._step
        self._jit_kwargs = jit_kwargs
        self._jitted = jax.jit(self._step, **jit_kwargs)

    def jitted(self, donate_feeds: bool = False):
        """The compiled step; with donate_feeds=True a twin executable
        that ALSO donates the feed dict (argnum 1) — callers must hand
        over fresh per-step device buffers (the reader.device_prefetch
        path), never a staged batch they intend to re-feed.

        Persistent cache: a deserialized/stored AOT executable takes
        over BOTH dispatch paths — cold and warm starts then run the
        LITERAL same executable.  The donate-feeds twin has its own
        entry (step key + ``donate_feeds: True``, loaded in _prepare /
        materialized here), so a warm prefetch restart deserializes it
        instead of paying a silent per-process jit compile."""
        if not donate_feeds:
            if self._aot is None and self._persist_pending \
                    and self._abs_args is not None:
                self._materialize_persistent()
            if self._aot is not None:
                return self._aot
            return self._jitted
        if self._aot_donate is None and self._persist_pending_donate \
                and self._abs_args is not None:
            self._materialize_donate()
        if self._aot_donate is not None:
            return self._aot_donate
        if self._jitted_donate is None:
            self._jitted_donate = jax.jit(self._step_fn,
                                          **self._donate_kwargs())
        return self._jitted_donate

    def _donate_kwargs(self) -> Dict[str, Any]:
        kwargs = dict(self._jit_kwargs)
        kwargs["donate_argnums"] = tuple(
            sorted(set(kwargs.get("donate_argnums", ())) | {0, 1}))
        return kwargs

    def _materialize_persistent(self):
        """First plain dispatch of a not-yet-resolved step under the
        persistent cache: try the disk entry (unless _prepare's probe
        already missed it — e.g. the key was resolved via the
        donate-twin entry and this is the first NON-donating
        dispatch), else AOT-compile (the compile that was about to
        happen anyway) and store it — only if the program passed the
        verify_program gate at _prepare time.  Any failure degrades to
        the plain jit path (record_error), never to a failed run."""
        from . import jit_cache as pjit_cache
        self._persist_pending = False
        comps, khash = self._persist_meta
        if not self._plain_probe_missed:
            loaded = pjit_cache.load("executor_step", khash, comps)
            if loaded is not None:
                self._aot = loaded
                self._persist_source = "disk"
                return
            # the key was resolved warm via the donate twin, but the
            # plain entry is genuinely absent: the AOT below is real
            # XLA work on a "warm" key.  Deliberately NOT booked in
            # executor_compile_total/forensics (the key's compile was
            # accounted when the twin was — same accounting the
            # pre-persistence donate twin had); the jit_cache miss +
            # store events above/below make it visible in flight
            obs_flight.record("jit_cache", "lazy_twin_compile",
                              twin="plain", key=khash[:16])
        t_c = time.perf_counter()
        try:
            exe = self._jitted.lower(*self._abs_args).compile()
        except Exception as e:
            pjit_cache.record_error("aot", repr(e))
            return
        finally:
            # Timecard (observability/goodput.py): the explicit AOT
            # compile span — a boundary with its own start/end, never
            # a hot-loop timer
            from ..observability import goodput as obs_goodput
            obs_goodput.note_span("compile",
                                  time.perf_counter() - t_c)
        self._aot = exe
        self._persist_source = "compiled"
        if self._persist_verified:
            pjit_cache.store("executor_step", khash, comps, exe)

    @staticmethod
    def _donate_components(comps: dict) -> dict:
        """The donate-feeds twin's key: the step components plus a
        ``donate_feeds`` marker — added ONLY on the twin, so every
        pre-existing plain-step key (and cached entry) stays valid."""
        out = dict(comps)
        out["donate_feeds"] = True
        return out

    def _materialize_donate(self):
        """First donating dispatch under the persistent cache: resolve
        the donate-feeds twin from disk (unless _prepare's probe
        already missed — e.g. the key was first prepared by a
        non-donating dispatch and this one arrived via the in-memory
        cache), else AOT-compile it (the compile the plain-jit twin
        was about to pay anyway) and store it under the donate key —
        verified programs only, any failure degrades to the plain jit
        path (PR 12 discipline)."""
        from . import jit_cache as pjit_cache
        self._persist_pending_donate = False
        comps, _ = self._persist_meta
        dcomps = self._donate_components(comps)
        dhash = pjit_cache.entry_key("executor_step", dcomps)
        if not self._donate_probe_missed:
            loaded = pjit_cache.load("executor_step", dhash, dcomps)
            if loaded is not None:
                self._aot_donate = loaded
                self._donate_source = "disk"
                return
            obs_flight.record("jit_cache", "lazy_twin_compile",
                              twin="donate", key=dhash[:16])
        t_c = time.perf_counter()
        try:
            exe = jax.jit(self._step_fn, **self._donate_kwargs()) \
                .lower(*self._abs_args).compile()
        except Exception as e:
            pjit_cache.record_error("aot", repr(e))
            return
        finally:
            from ..observability import goodput as obs_goodput
            obs_goodput.note_span("compile",
                                  time.perf_counter() - t_c)
        self._aot_donate = exe
        self._donate_source = "compiled"
        if self._persist_verified:
            pjit_cache.store("executor_step", dhash, dcomps, exe)

    def jitted_steps(self, steps: int, seq_names: tuple):
        """A device-side training loop: `steps` iterations of the
        compiled step under ONE dispatch (lax.scan), the TPU analogue of
        the reference's repeated-exe.run train loops with
        num_iteration_per_drop_scope (parallel_executor.cc:191) / TF's
        steps_per_run.  Feeds named in `seq_names` carry a leading
        [steps] dim and are sliced per iteration; the rest are
        broadcast.  RNG folds per-iteration so the result is bit-equal
        to `steps` sequential Executor.run calls."""
        from . import jit_cache as pjit_cache
        key = (steps, seq_names)
        fn = self._multi_cache.get(key)
        if fn is not None:
            _m_multi_hit.inc()
            return fn
        # persistent cache: the device loop gets its own entry — step
        # key components + (steps, seq_names).  A warm process
        # deserializes the WHOLE scan executable; multi-miss/compile
        # counters stay frozen on a disk hit.
        mcomps = mhash = loaded = None
        persist = self._persist_meta is not None and pjit_cache.enabled()
        if persist:
            mcomps = dict(self._persist_meta[0])
            mcomps["steps"] = int(steps)
            mcomps["seq_names"] = list(seq_names)
            mhash = pjit_cache.entry_key("executor_multi", mcomps)
            loaded = pjit_cache.load("executor_multi", mhash, mcomps)
            # a hit still falls through to BUILD (not compile) the jit
            # twin below: the cost model needs a lowerable fn and a
            # deserialized Compiled has no .lower()
        if loaded is None:
            _m_multi_miss.inc()
            _m_compile.labels(kind="multi_step").inc()
        step_fn = self._step_fn
        fold = self.program.random_seed is None

        def multi(state, const_feeds, seq_feeds, root, counter):
            def body(st, x):
                i, sf = x
                feeds = dict(const_feeds)
                feeds.update(sf)
                k = jax.random.fold_in(root, counter + i) if fold else root
                fetches, st2 = step_fn(st, feeds, k)
                return st2, fetches

            idx = jnp.arange(steps, dtype=jnp.int32)
            st_out, ys = jax.lax.scan(body, state, (idx, seq_feeds))
            return ys, st_out

        jit_kwargs: Dict[str, Any] = {"donate_argnums": (0,)}
        if self._state_sharding_fn is not None:
            # implicit-SPMD mesh plane: reuse the per-name shardings;
            # per-step feeds gain a replicated leading steps dim
            P = jax.sharding.PartitionSpec
            ns = lambda spec: jax.sharding.NamedSharding(self.mesh, spec)

            def seq_spec(name):
                base = self._feed_sharding_fn(name).spec
                return ns(P(*((None,) + tuple(base))))

            jit_kwargs["in_shardings"] = (
                {n: self._state_sharding_fn(n)
                 for n in self.in_state_names},
                {n: self._feed_sharding_fn(n) for n in self.feed_names
                 if n not in seq_names},
                {n: seq_spec(n) for n in seq_names},
                ns(P()), ns(P()))
            jit_kwargs["out_shardings"] = (
                None, {n: self._state_sharding_fn(n)
                       for n in self.out_state_names})
        fn = jax.jit(multi, **jit_kwargs)
        if loaded is not None:
            self._multi_jit[key] = fn       # cost model needs .lower()
            self._multi_cache[key] = loaded
            return loaded
        if persist and self._persist_verified and key in self._multi_abs:
            # AOT-compile now (the compile the first dispatch was about
            # to pay) so the stored artifact IS the dispatched one
            exe = None
            t_c = time.perf_counter()
            try:
                exe = fn.lower(*self._multi_abs[key]).compile()
            except Exception as e:
                pjit_cache.record_error("aot", repr(e))
            finally:
                from ..observability import goodput as obs_goodput
                obs_goodput.note_span("compile",
                                      time.perf_counter() - t_c)
            if exe is not None:
                pjit_cache.store("executor_multi", mhash, mcomps, exe)
                self._multi_jit[key] = fn
                self._multi_cache[key] = exe
                return exe
        self._multi_cache[key] = fn
        return fn

    # --- cost model (observability/costmodel.py) ----------------------
    def note_abs_args(self, state, feeds, key):
        """Remember the abstract (shape/dtype) argument skeleton of the
        step — called once, just before the first dispatch, while the
        (soon-donated) buffers are still valid."""
        if self._abs_args is None:
            self._abs_args = (obs_cost.abstractify(state),
                              obs_cost.abstractify(feeds),
                              obs_cost.abstractify(key))

    def note_multi_abs_args(self, mkey, args):
        if mkey not in self._multi_abs:
            self._multi_abs[mkey] = obs_cost.abstractify(args)

    def _cost_label(self, kind: str, abs_args) -> str:
        return obs_cost.args_label(self.program._uid,
                                   self.program._version, abs_args, kind)

    def cost(self, prefer_analytic: bool = False):
        """Lazy, cached cost/memory analysis of the compiled step.  The
        XLA path costs one extra AOT lower+compile on first call;
        ``prefer_analytic=True`` settles for the (cheap) jaxpr walk.
        A cached XLA result is always reused; a cached analytic result
        is upgraded when a caller later asks for the XLA view.  None
        when the cost_model flag is off, the program never ran, or
        analysis failed."""
        if self._abs_args is None or not obs_cost.enabled():
            return self._cost
        have = self._cost
        if have is not None and (have.source == "xla" or prefer_analytic):
            return have
        # each path gets ONE attempt (callers like the trainer may ask
        # every step, so a failing trace must not be retried per step);
        # a failed analytic try never blocks a later XLA request
        if self._tried_analytic if prefer_analytic else self._tried_xla:
            return have
        got = obs_cost.analyze_jitted(
            self._jitted, self._abs_args,
            self._cost_label("step", self._abs_args),
            prefer_analytic=prefer_analytic)
        if prefer_analytic:
            self._tried_analytic = True
        else:
            # the XLA path internally falls back to the jaxpr walk, so
            # a full attempt exhausts both
            self._tried_xla = self._tried_analytic = True
        if got is not None:
            self._cost = got
        return self._cost

    def multi_cost(self, mkey):
        """Cost analysis of one run_steps device loop (a _multi_cache
        entry), keyed like the cache: (steps, seq_names)."""
        if mkey in self._multi_cost:
            return self._multi_cost[mkey]
        abs_args = self._multi_abs.get(mkey)
        # a persisted loop's cache slot holds a jax.stages.Compiled
        # (no .lower()); analyze its lowerable jit twin instead
        fn = self._multi_jit.get(mkey) or self._multi_cache.get(mkey)
        if abs_args is None or fn is None or not obs_cost.enabled():
            return None
        steps = mkey[0]
        cost = obs_cost.analyze_jitted(
            fn, abs_args, self._cost_label(f"multi{steps}", abs_args))
        self._multi_cost[mkey] = cost
        return cost

    def _pp_partition(self):
        """Split the forward op list at pipeline_boundary markers into
        stage sub-programs; returns (stage_ops, boundary_var_names).

        Vars consumed by a stage but produced OUTSIDE it (and not
        arriving as its boundary activation) are rematerialized: the
        transitive producer ops are prepended to the stage, in program
        order — e.g. the shared causal-bias iota chain every layer
        consumes.  A badly-placed cut degrades to recomputation, never
        to wrong results."""
        fw = self._ops[:self._ad_idx]
        stages, cur, b_names = [], [], []
        for op in fw:
            cur.append(op)
            if op.type == "pipeline_boundary":
                b_names.append(list(op.outputs["Out"]))
                stages.append(cur)
                cur = []
        stages.append(cur)

        produced_by = {}
        for i, op in enumerate(fw):
            for names in op.outputs.values():
                for n in names:
                    produced_by.setdefault(n, i)

        out = []
        for s, ops in enumerate(stages):
            own = set(id(op) for op in ops)
            incoming = set(b_names[s - 1]) if s > 0 else set()
            extra: List[int] = []
            seen = set()

            def resolve(n):
                if n in seen or n in incoming:
                    return
                seen.add(n)
                i = produced_by.get(n)
                if i is None or id(fw[i]) in own:
                    return          # feed/param/state or stage-internal
                for names in fw[i].inputs.values():
                    for m in names:
                        resolve(m)
                extra.append(i)

            for op in ops:
                for names in op.inputs.values():
                    for n in names:
                        resolve(n)
            prologue = [fw[i] for i in sorted(set(extra))]
            out.append(prologue + ops)
        return out, b_names

    # --- tracing ----------------------------------------------------------
    def _step(self, state: Dict[str, Any], feeds: Dict[str, Any], key):
        env: Dict[str, Any] = dict(state)
        env.update(feeds)
        ctx = LowerContext(key)
        ctx.program = self.program
        ctx.env = env
        ctx.place = self.place
        # see _implicit_mesh above: ops/creation.py random ops consult
        # this to pin their generation replicated under implicit SPMD
        ctx.spmd_mesh = self._implicit_mesh
        # context-parallel plane: sequence-aware ops (fused_attention)
        # read this to run their ring variant with the axis in scope
        ctx.cp_axis = getattr(self.program, "_dist_cp_axis", None)
        # expert-parallel plane: moe_ffn dispatches via all_to_all when
        # the expert axis is in scope
        ctx.ep_axis = getattr(self.program, "_dist_ep_axis", None)

        if self._ad_idx is None:
            env = run_ops_in_env(ctx, env, self._ops)
        else:
            ad_op = self._ops[self._ad_idx]
            loss_name = ad_op.attrs["loss"]
            param_names = list(ad_op.attrs["params"])
            grad_names = list(ad_op.attrs["grads"])
            base_env = {k: v for k, v in env.items()
                        if k not in param_names}
            params = {k: env[k] for k in param_names}
            pp_axis = getattr(self.program, "_dist_pp_axis", None)
            if pp_axis is not None:
                stage_ops, b_names = self._pp_partition()
                M = int(getattr(self.program, "_pp_microbatches", 1))
                block = self.program.global_block()
                data_names = [n for n in self.feed_names
                              if block.has_var(n) and block.var(n).is_data]

            if pp_axis is not None and getattr(
                    self.program, "_pp_schedule", "gpipe") == "1f1b":
                # explicit-backward 1F1B plane: grads come from the
                # per-tick vjp, not from differentiating a forward
                loss_val, grads = _pp_1f1b(
                    ctx, dict(base_env), stage_ops, b_names, loss_name,
                    pp_axis, M, data_names, params)
                env = dict(base_env)
                env.update(params)
                env[loss_name] = jax.lax.psum(loss_val, pp_axis)
                for pname, gname in zip(param_names, grad_names):
                    env[gname] = grads[pname]
                env = run_ops_in_env(ctx, env,
                                     self._ops[self._ad_idx + 1:])
                new_state = {n: env[n] for n in self.out_state_names}
                fetches = [env[n] for n in self.fetch_names]
                return fetches, new_state

            if pp_axis is not None:
                def forward(p):
                    fenv = dict(base_env)
                    fenv.update(p)
                    loss = _pp_forward(ctx, fenv, stage_ops, b_names,
                                       loss_name, pp_axis, M, data_names)
                    # stage internals live inside the scan: only the
                    # loss (plus params/state) is available downstream
                    out_env = dict(base_env)
                    out_env.update(p)
                    out_env[loss_name] = loss
                    return loss, out_env
            else:
                def forward(p):
                    fenv = dict(base_env)
                    fenv.update(p)
                    fenv = run_ops_in_env(ctx, fenv,
                                          self._ops[:self._ad_idx])
                    return fenv[loss_name], fenv

            loss_val, vjp_fn, fwd_env = jax.vjp(forward, params,
                                                has_aux=True)
            check_arg(int(np.prod(loss_val.shape)) == 1,
                      f"autodiff loss {loss_name!r} must be scalar, "
                      f"got shape {loss_val.shape}")
            grads = vjp_fn(jnp.ones_like(loss_val))[0]
            env = fwd_env
            if pp_axis is not None:
                # replicate the (stage-local) pipelined loss for fetch,
                # OUTSIDE the differentiated region (see _pp_forward)
                env[loss_name] = jax.lax.psum(loss_val, pp_axis)
            for pname, gname in zip(param_names, grad_names):
                env[gname] = grads[pname]
            env = run_ops_in_env(ctx, env, self._ops[self._ad_idx + 1:])

        if self.collect_stats:
            # fused in-graph reductions over the final environment; the
            # packed array rides the fetch list (reserved name) so no
            # step plumbing changes shape
            names, packed = obs_tensorstats.pack(self._stats_order, env,
                                                 state)
            self._stats_names = names
            env[obs_tensorstats.FETCH_NAME] = packed
        new_state = {n: env[n] for n in self.out_state_names}
        fetches = [env[n] for n in self.fetch_names]
        return fetches, new_state


class Executor:
    """User-facing executor (ref python executor.py:256).

    exe = Executor(TPUPlace(0))
    exe.run(startup_program)
    loss, = exe.run(main_program, feed={...}, fetch_list=[loss_var])
    """

    def __init__(self, place: Optional[Place] = None,
                 scope: Optional[Scope] = None, mesh=None,
                 batch_axis: str = "data"):
        self.place = place or default_place()
        self.scope = scope or global_scope()
        self.mesh = mesh
        self.batch_axis = batch_axis
        self._cache: Dict[tuple, _CompiledProgram] = {}
        self._root_keys: Dict[int, Any] = {}
        self._run_counter = 0
        # recompile-storm detection: compiles per (program, fetch-list)
        self._compiles_by_fetch_key: Dict[tuple, int] = {}
        self._storm_warned: set = set()
        self._last_compiled: Optional[_CompiledProgram] = None
        # verify_program=warn warns once per (program, fetch-list) key
        self._verify_warned: set = set()
        # forensics scope: this executor's jit cache (NOT id(self) —
        # ids are reused after GC and would inherit dead keys)
        self._forensics_owner = obs_forensics.new_owner()

    def _note_compile(self, program, fetch_names, key_parts,
                      jit_cache: str = ""):
        """Recompile-storm detector + forensics: every miss is diffed
        against the retained key for its (program, fetch-list), so the
        warning names WHICH component churned (feed shapes vs dtypes vs
        scope-state signature vs program version vs flags) instead of
        guessing.  Warns once per key.  ``jit_cache`` marks the
        persistent-cache disposition ("miss" = this compile will be
        serialized; a disk HIT never reaches here — the compile log
        stays silent on warm starts)."""
        rec = obs_forensics.note_compile(key_parts, jit_cache=jit_cache)
        n = int(flags.get_flag("recompile_warn_threshold"))
        fkey = (program._uid, tuple(fetch_names))
        count = self._compiles_by_fetch_key.get(fkey, 0) + 1
        self._compiles_by_fetch_key[fkey] = count
        if n > 0 and count > n and fkey not in self._storm_warned:
            self._storm_warned.add(fkey)
            cause = obs_forensics.dominant_cause(
                program._uid, tuple(fetch_names),
                owner=self._forensics_owner)
            _m_recompile_storm.labels(cause=cause).inc()
            detail = "; ".join(rec.details[:3]) or "no drift recorded"
            hist = obs_forensics.describe_causes(
                program._uid, tuple(fetch_names),
                owner=self._forensics_owner)
            warnings.warn(
                f"executor recompile storm: program v{program._version} "
                f"fetches {list(fetch_names)} compiled {count} distinct "
                f"executables (> threshold {n}); drifting component(s): "
                f"{hist} — latest: {detail}",
                RuntimeWarning, stacklevel=3)

    # ------------------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            donate_feeds: bool = False):
        program = program or default_main_program()
        scope = scope or self.scope
        # chaos site: a raise/delay here models a failed/slow device
        # dispatch before any state mutates (docs/RESILIENCE.md catalog)
        chaos.trigger("executor.run")
        # model-health sampling (observability/tensorstats.py): every
        # Nth dispatch of a TRAIN program runs the stats variant — a
        # separate cached executable; the off/non-sampled path is
        # byte-identical to the stats-less executor.  Single-device
        # only: under a mesh feeds/fetches are sharded and the stats
        # fetch is not wired through pjit, so the flag is inert there —
        # note_mesh_skipped warns once rather than staying silent.
        if self.mesh is None:
            want_stats = obs_tensorstats.want_sample(program)
        else:
            want_stats = False
            obs_tensorstats.note_mesh_skipped(program)
        compiled, dev_feeds, state, fetch_names = self._prepare(
            program, feed or {}, list(fetch_list or []), scope,
            collect_stats=want_stats, donate_feeds=donate_feeds)

        root, counter = self._root_and_counter(program, 1)
        if program.random_seed is None:
            root = jax.random.fold_in(root, counter)
        compiled.note_abs_args(state, dev_feeds, root)

        # chaos site: a simulated RESOURCE_EXHAUSTED at the dispatch
        # allocation (docs/RESILIENCE.md catalog).  Memscope, when on,
        # freezes the census + the triggering program's cost row into
        # a flight bundle before the fault propagates to the caller.
        try:
            chaos.trigger("memory.alloc")
        except chaos.InjectedFault:
            from ..observability import memscope as obs_memscope
            if obs_memscope.enabled():
                mcost = compiled.cost(prefer_analytic=True)
                obs_memscope.note_alloc_failure(
                    "executor.run",
                    label=(mcost.label if mcost is not None else
                           f"p{program._uid}.v{program._version}.step"),
                    cost=mcost)
            raise

        profile_ops = bool(flags.get_flag("profile_ops"))
        with RecordEvent(f"executor.run#{len(compiled.fetch_names)}f"):
            t0 = time.perf_counter()
            if flags.get_flag("check_nan_inf_per_op") or profile_ops:
                # eager (un-jitted) run so every op's outputs are concrete
                # — the first NaN/Inf source is named, and per-op wall
                # timings are real
                _profile_state.active = profile_ops
                try:
                    fetches, new_state = compiled._step(state, dev_feeds,
                                                        root)
                finally:
                    _profile_state.active = False
                mode = "eager"
            else:
                fn = compiled.jitted(donate_feeds)
                if donate_feeds:
                    # feed buffers rarely alias an output shape; jax
                    # warns per unusable donation — the donation is
                    # intentional (frees the prefetch buffers early),
                    # the per-step warning is noise
                    with warnings.catch_warnings():
                        warnings.filterwarnings(
                            "ignore",
                            message=".*donated buffers were not usable.*")
                        fetches, new_state = fn(state, dev_feeds, root)
                else:
                    fetches, new_state = fn(state, dev_feeds, root)
                mode = "jit"
            dt = time.perf_counter() - t0
        _m_step_seconds.labels(mode=mode).observe(dt)
        # lazy import: perfscope has a `python -m` CLI, and eager
        # package-graph imports trip runpy's sys.modules warning
        from ..observability import perfscope as obs_perfscope
        if obs_perfscope.enabled():
            # roofline sink accounting per compiled program; the cost
            # is the cached analytic view (a jaxpr trace at most once
            # per program — never an XLA compile on the step path)
            pcost = compiled.cost(prefer_analytic=True)
            obs_perfscope.note_dispatch(
                pcost.label if pcost is not None
                else f"p{program._uid}.v{program._version}.step",
                dt, pcost)
        obs_trace.add_span("executor.step", t0, dt,
                           tid=obs_trace.EXECUTOR_TID, cat="executor",
                           args={"mode": mode,
                                 "fetches": len(fetch_names)})
        xctx = obs_tracectx.current()
        if xctx is not None:
            # request X-ray: the dispatch as a child span of whatever
            # request/step is ambient (trainer per-step traces, a
            # predictor request) — compile misses above already left
            # their marker via forensics
            obs_tracectx.record_span(
                "executor.step", xctx.trace_id,
                obs_tracectx.new_span_id(), xctx.span_id,
                time.time() - dt, t0, dt, kind="dispatch",
                attrs={"mode": mode, "program": program._uid})
        obs_flight.record("span", "executor.step", mode=mode, dur=dt)

        for n, v in new_state.items():
            scope.set_var(n, v)

        from ..observability import memscope as obs_memscope
        if obs_memscope.enabled():
            # dispatch-boundary census (AFTER the scope write-back, so
            # the live new-state arrays attribute to params/optimizer
            # planes, not "other") + predicted-vs-measured peak
            # reconciliation off the same cached analytic cost view
            mcost = compiled.cost(prefer_analytic=True)
            try:
                feed_b = sum(int(getattr(v, "nbytes", 0) or 0)
                             for v in dev_feeds.values())
            except Exception:
                feed_b = 0
            obs_memscope.note_dispatch(
                mcost.label if mcost is not None
                else f"p{program._uid}.v{program._version}.step",
                mcost, feed_bytes=feed_b, scope=scope)

        if want_stats:
            # pop the reserved stats fetch back off before the caller
            # sees the list; ingestion blocks on the (sampled) step's
            # stats array — the every-Nth cost the flag buys
            stats_val, fetches = fetches[-1], fetches[:-1]
            obs_tensorstats.note_sample(program, compiled._stats_names,
                                        stats_val)

        if flags.get_flag("check_nan_inf"):
            for n, v in zip(fetch_names, fetches):
                a = self._fetch_numpy(v)
                if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
                    raise EnforceNotMet(f"NaN/Inf detected in fetch {n!r}")

        if return_numpy:
            return [self._fetch_numpy(v) for v in fetches]
        return fetches

    def run_steps(self, program: Optional[Program] = None,
                  feed: Optional[Dict[str, Any]] = None,
                  fetch_list: Optional[Sequence] = None,
                  steps: int = 1,
                  per_step_feeds: Sequence[str] = (),
                  scope: Optional[Scope] = None,
                  return_numpy: bool = True):
        """Run `steps` training iterations in ONE device dispatch.

        The compiled step is wrapped in lax.scan, so host<->device
        latency is paid once per `steps` iterations instead of per
        iteration — the device-side train loop the reference approximates
        with num_iteration_per_drop_scope (parallel_executor.cc:191).

        Feeds named in `per_step_feeds` must carry a leading [steps]
        dimension and are sliced one slab per iteration; all other feeds
        are repeated every iteration.  Fetches come back stacked with a
        leading [steps] axis.  Parameter state advances exactly as
        `steps` sequential run() calls would (including per-step RNG
        folding), and ends up written back to the scope once.
        """
        program = program or default_main_program()
        scope = scope or self.scope
        check_arg(steps >= 1, f"steps must be >= 1, got {steps}")
        seq = frozenset(per_step_feeds)
        feed = feed or {}
        missing = seq - set(feed)
        check_arg(not missing,
                  f"per_step_feeds {sorted(missing)} not in feed")
        for name in seq:
            n0 = np.asarray(feed[name]).shape[0]
            check_arg(n0 == steps,
                      f"per-step feed {name!r} leading dim {n0} != "
                      f"steps {steps}")
        if flags.get_flag("check_nan_inf_per_op") or \
                flags.get_flag("check_nan_inf") or \
                flags.get_flag("profile_ops") or \
                (self.mesh is not None and jax.process_count() > 1):
            # debug/profiling planes want per-step visibility, and the
            # multi-process feed globalization is per-step shaped:
            # degrade to the sequential path (same results)
            outs = []
            for i in range(steps):
                f_i = {k: (v[i] if k in seq else v)
                       for k, v in feed.items()}
                outs.append(self.run(program, f_i, fetch_list, scope,
                                     return_numpy=return_numpy))
            stack = np.stack if return_numpy else jnp.stack
            return [stack([o[j] for o in outs])
                    for j in range(len(outs[0]))]
        dev_feed = {k: v for k, v in feed.items() if k not in seq}
        compiled, dev_feeds, state, fetch_names = self._prepare(
            program, dev_feed, list(fetch_list or []), scope,
            extra_feeds={k: feed[k] for k in seq})
        const_feeds = {k: v for k, v in dev_feeds.items() if k not in seq}
        seq_feeds = {k: v for k, v in dev_feeds.items() if k in seq}

        root, counter = self._root_and_counter(program, steps)
        mkey = (int(steps), tuple(sorted(seq)))
        counter_arr = jnp.int32(counter)
        # abs args BEFORE jitted_steps: the persistent cache AOT-lowers
        # the loop from them to serialize the exact dispatched artifact
        compiled.note_multi_abs_args(
            mkey, (state, const_feeds, seq_feeds, root, counter_arr))
        fn = compiled.jitted_steps(int(steps), tuple(sorted(seq)))
        with RecordEvent(f"executor.run_steps#{steps}"):
            t0 = time.perf_counter()
            ys, new_state = fn(state, const_feeds, seq_feeds, root,
                               counter_arr)
            dt = time.perf_counter() - t0
        _m_step_seconds.labels(mode="multi").observe(dt)
        obs_trace.add_span("executor.step", t0, dt,
                           tid=obs_trace.EXECUTOR_TID, cat="executor",
                           args={"mode": "multi", "steps": int(steps)})
        obs_flight.record("span", "executor.run_steps", steps=int(steps),
                          dur=dt)

        for n, v in new_state.items():
            scope.set_var(n, v)
        if return_numpy:
            return [self._fetch_numpy(v) for v in ys]
        return ys

    def _verify_before_compile(self, program, dev_feeds, fetch_names,
                               scope, donate_feeds, seq_names=()):
        """Pre-dispatch static verification (paddle_tpu/analysis),
        gated by the verify_program flag.  Runs only on a cache miss,
        BEFORE anything compiles or any counter moves, so an 'error'
        -mode rejection leaves executor_compile_total untouched and the
        user gets findings naming ops/vars/call sites instead of an
        XLA trace.  'warn' runs the cheap O(ops) lints and warns once
        per (program, fetch-list); 'error' adds abstract shape
        inference and raises."""
        mode = str(flags.get_flag("verify_program"))
        if mode not in ("warn", "error"):
            return
        from .. import analysis
        # run_steps per-step slabs carry a leading [steps] dim the
        # program never sees — the compiled scan slices it off before
        # any op runs, so shape inference must too
        feed_shapes = {n: (tuple(np.shape(a))[1:] if n in seq_names
                           else tuple(np.shape(a)))
                       for n, a in dev_feeds.items()}
        if mode == "error":
            result = analysis.verify_program(
                program, feed=set(dev_feeds), fetch_list=fetch_names,
                scope=scope, donate_feeds=donate_feeds,
                feed_shapes=feed_shapes)
        else:
            result = analysis.quick_lints(
                program, feed=set(dev_feeds), fetch_list=fetch_names,
                scope=scope, donate_feeds=donate_feeds)
        errs = result.errors
        if not errs:
            return
        if mode == "error":
            raise analysis.ProgramVerificationError(
                f"program v{program._version} failed verification "
                f"(verify_program=error); nothing was compiled.  "
                f"Findings:\n" + result.report(), result)
        wkey = (program._uid, tuple(fetch_names))
        if wkey not in self._verify_warned:
            self._verify_warned.add(wkey)
            warnings.warn(
                f"program verification found {len(errs)} error(s) "
                f"(verify_program=warn; the compile proceeds):\n"
                + result.report(max_findings=10),
                RuntimeWarning, stacklevel=4)

    def _prepare(self, program, feed, fetch_list, scope,
                 extra_feeds=None, collect_stats=False,
                 donate_feeds=False):
        """Shared run()/run_steps() prologue: materialise feeds, gather
        persistable state, and fetch (or build) the compiled program.
        `extra_feeds` are run_steps' per-step slabs (leading [steps]
        dim); they go through the same materialisation as other feeds
        and their names become part of the compiled feed set.
        `collect_stats` selects the tensorstats variant executable (its
        key differs by the tensor_stats flags entry only)."""
        if extra_feeds:
            feed = {**feed, **extra_feeds}
        device = self.place.jax_device()
        block = program.global_block()

        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]

        # materialise feeds: single-device -> device_put; mesh -> leave as
        # host arrays, jit's in_shardings scatters them across devices.
        # Multi-process mesh (jax.distributed world): every process feeds
        # the same GLOBAL batch and each materialises only its addressable
        # shards (the reference's trainers each feed a slice; here the
        # deterministic global batch keeps loss parity with 1-process runs)
        multiproc = self.mesh is not None and jax.process_count() > 1
        dev_feeds = {}
        for name, val in feed.items():
            var = block.var(name) if block.has_var(name) else None
            if self.mesh is not None:
                if isinstance(val, jax.Array):
                    dev_feeds[name] = val    # already device/global-laid
                    continue
                arr = np.asarray(val)
                if var is not None and var.dtype is not None:
                    arr = arr.astype(to_jnp_dtype(var.dtype))
                if multiproc:
                    arr = self._globalize_feed(program, name, var, arr)
                dev_feeds[name] = arr
            else:
                dev_feeds[name] = _as_device_array(val, var, device)

        # persistable state visible to this program
        persist = sorted({v.name for v in program.list_vars() if v.persistable})
        state = {n: scope.find_var(n) for n in persist if scope.has_var(n)}

        feeds_sig = tuple(sorted((n, tuple(a.shape), str(a.dtype))
                                 for n, a in dev_feeds.items()))
        state_sig = tuple(sorted((n, tuple(a.shape), str(a.dtype))
                                 for n, a in state.items()))
        # numerics-affecting flags are baked in at trace time, so a
        # runtime toggle must compile a fresh executable — and because
        # they are part of the key (and of forensics' KeyParts), a
        # quantize_dtype/fuse_block flip is diagnosed as "flags" drift
        # instead of reading as a recompile storm
        flags_sig = (("amp_bf16", bool(flags.get_flag("amp_bf16"))),
                     ("use_pallas_kernels",
                      bool(flags.get_flag("use_pallas_kernels"))),
                     ("quantize_dtype",
                      str(flags.get_flag("quantize_dtype"))),
                     ("fuse_block", bool(flags.get_flag("fuse_block"))))
        if collect_stats:
            # the stats variant ONLY: appended (never a False entry) so
            # the tensor_stats=off key stays byte-identical to the
            # stats-less executor, and the sampled/non-sampled pair
            # diagnoses as "flags" drift in forensics — two cached
            # executables, no storm
            flags_sig += (("tensor_stats", True),)
        key = (program._uid, program._version, feeds_sig,
               tuple(fetch_names), state_sig) \
            + tuple(v for _, v in flags_sig)
        compiled = self._cache.get(key)
        if compiled is None:
            seq_names = frozenset(extra_feeds or ())
            # persistent executable cache (framework/jit_cache.py):
            # before compiling anything, try to deserialize this key's
            # executable from disk.  A hit records NO compile counters
            # and NO forensics (nothing compiled — jit_cache_hits_total
            # + flight carry the event), so a warm restart's metrics
            # read exactly like an in-memory-cached process.  Mesh
            # executors participate too (ISSUE 14): their keys carry
            # the full mesh/sharding identity, so a resized
            # incarnation under a different mesh is a clean MISS and a
            # same-mesh warm start deserializes the sharded executable.
            from . import jit_cache as pjit_cache
            use_pc = pjit_cache.enabled()
            ploaded = dloaded = pmeta = None
            if use_pc:
                # NOTE: no program._version here — it is a process-
                # local mutation counter; a program reaching the same
                # topology via a different build path must still HIT
                # (the fingerprint hashes the full serialized content)
                pcomponents = {
                    "program": pjit_cache.program_fingerprint(program),
                    "feeds": feeds_sig, "fetch": list(fetch_names),
                    "state": state_sig, "flags": flags_sig,
                    "random_seed_none": program.random_seed is None,
                }
                if self.mesh is not None:
                    # added ONLY under a mesh so every pre-existing
                    # single-device key (and cached entry) stays valid
                    pcomponents["mesh"] = self._mesh_components(program)
                pkhash = pjit_cache.entry_key("executor_step",
                                              pcomponents)
                pmeta = (pcomponents, pkhash)
                if donate_feeds:
                    # the donate-feeds twin has its own entry (key +
                    # donate marker); probe it FIRST — a prefetch-path
                    # warm restart may only ever have stored the twin,
                    # and a twin hit means zero XLA work this dispatch
                    dcomps = _CompiledProgram._donate_components(
                        pcomponents)
                    dloaded = pjit_cache.load(
                        "executor_step",
                        pjit_cache.entry_key("executor_step", dcomps),
                        dcomps)
                if dloaded is None:
                    ploaded = pjit_cache.load("executor_step", pkhash,
                                              pcomponents)
            verified = False
            disk_hit = ploaded is not None or dloaded is not None
            if disk_hit and donate_feeds:
                # a stored entry was verified with donate_feeds=False
                # semantics; a donating first dispatch still needs the
                # donated_fetch hazard gate (the _jitted_donate twin
                # compiles ungated otherwise)
                self._verify_before_compile(
                    program, dev_feeds, fetch_names, scope,
                    donate_feeds, seq_names=seq_names)
            if not disk_hit:
                # static verification gate: BEFORE any counter/compile
                # so a rejection leaves the compile metrics untouched
                self._verify_before_compile(
                    program, dev_feeds, fetch_names, scope,
                    donate_feeds, seq_names=seq_names)
                if use_pc:
                    # only verified programs are persisted (PR 10
                    # gate); error mode just proved it above, other
                    # modes run the full verifier once here
                    if str(flags.get_flag("verify_program")) == "error":
                        verified = True
                    else:
                        feed_shapes = {
                            n: (tuple(np.shape(a))[1:]
                                if n in seq_names
                                else tuple(np.shape(a)))
                            for n, a in dev_feeds.items()}
                        verified = pjit_cache.program_verified(
                            program, set(dev_feeds), fetch_names,
                            scope=scope, feed_shapes=feed_shapes)
                if flags.get_flag("executor_log_compiles"):
                    print(f"[executor] compiling program "
                          f"v{program._version} "
                          f"feeds={sorted(dev_feeds)} "
                          f"fetches={fetch_names}")
                _m_cache_miss.inc()
                _m_compile.labels(kind="step").inc()
                self._note_compile(program, fetch_names,
                                   obs_forensics.KeyParts(
                                       program_uid=program._uid,
                                       program_version=program._version,
                                       feeds=feeds_sig,
                                       fetch_names=tuple(fetch_names),
                                       state=state_sig, flags=flags_sig,
                                       owner=self._forensics_owner),
                                   jit_cache="miss" if use_pc else "")
                chaos.trigger("executor.compile")   # chaos: OOM/XLA-crash
            compiled = _CompiledProgram(
                program, sorted(dev_feeds), fetch_names, sorted(state),
                persist, self.place, donate=True, mesh=self.mesh,
                batch_axis=self.batch_axis, collect_stats=collect_stats)
            if use_pc:
                compiled._persist_meta = pmeta
                if dloaded is not None:
                    # donate twin off disk: zero XLA work for the
                    # prefetch path; the plain entry (if ever needed by
                    # a non-donating dispatch) resolves lazily — disk
                    # first, since its probe never ran here — and a
                    # stored twin implies the program verified
                    compiled._aot_donate = dloaded
                    compiled._donate_source = "disk"
                    compiled._persist_pending = True
                    compiled._persist_verified = True
                elif ploaded is not None:
                    compiled._aot = ploaded
                    compiled._persist_source = "disk"
                    compiled._persist_verified = True
                else:
                    compiled._persist_pending = True
                    compiled._persist_verified = verified
                    # both keys were probed and missed: the resolvers
                    # must not re-probe (and re-count the miss)
                    compiled._plain_probe_missed = True
                # the twin resolves lazily on the first donating
                # dispatch (disk load, else AOT+store) — also for keys
                # first prepared by a NON-donating dispatch
                compiled._persist_pending_donate = dloaded is None
                compiled._donate_probe_missed = (donate_feeds
                                                 and dloaded is None)
            self._cache[key] = compiled
            _m_cached_programs.set(len(self._cache))
        else:
            _m_cache_hit.inc()

        if self.mesh is not None:
            # committed arrays must match in_shardings exactly (strict in
            # jax>=0.6); reshard any state var laid out differently (e.g.
            # produced by a program that didn't know this var's spec)
            P = jax.sharding.PartitionSpec
            for n in list(state):
                a = state[n]
                if not isinstance(a, jax.Array):
                    continue
                spec = P()
                if block.has_var(n):
                    s = getattr(block.var(n), "sharding", None)
                    if s is not None:
                        spec = P(*s)
                want = jax.sharding.NamedSharding(self.mesh, spec)
                if not a.sharding.is_equivalent_to(want, a.ndim):
                    state[n] = jax.device_put(a, want)

        self._last_compiled = compiled
        return compiled, dev_feeds, state, fetch_names

    def _mesh_components(self, program) -> dict:
        """Mesh/sharding identity for persistent-cache keys (ISSUE 14):
        axis names+sizes, the exact device assignment (a serialized
        executable bakes its devices in — a mesh over different ids
        must not HIT), the batch axis, the transpiler axes, and every
        var's PartitionSpec.  A resized incarnation with a different
        mesh gets a clean MISS; the same mesh, a warm HIT."""
        mesh = self.mesh
        block = program.global_block()
        var_shardings = sorted(
            (name, [None if s is None else str(s) for s in v.sharding])
            for name, v in block.vars.items()
            if getattr(v, "sharding", None) is not None)
        spmd_axis = getattr(program, "_dist_spmd_axis", None)
        pp_axis = getattr(program, "_dist_pp_axis", None)
        return {
            "axes": [[str(a), int(s)] for a, s in mesh.shape.items()],
            "device_ids": [int(d.id) for d in mesh.devices.flat],
            "batch_axis": str(self.batch_axis),
            "spmd_axis": None if spmd_axis is None else str(spmd_axis),
            "pp_axis": None if pp_axis is None else str(pp_axis),
            "var_shardings": var_shardings,
        }

    def _root_and_counter(self, program, n):
        """Root PRNG key (unfolded) plus the run-counter window
        [counter, counter+n) this call consumes — run() folds on the
        host, run_steps folds per-iteration inside the scan, both
        producing the identical key sequence."""
        root = self._peek_root(program)
        counter = self._run_counter
        self._run_counter += n
        return root, counter

    def _peek_root(self, program):
        """The root key WITHOUT consuming a run-counter slot (explain()
        must not perturb the RNG sequence of subsequent runs)."""
        seed = (program.random_seed if program.random_seed is not None
                else flags.get_flag("rng_seed"))
        root = self._root_keys.get(seed)
        if root is None:        # cache: PRNGKey is a device computation
            root = self._root_keys[seed] = jax.random.PRNGKey(seed)
        return root

    # --- compiled-program introspection (observability plane) ---------
    def explain(self, program: Optional[Program] = None,
                feed: Optional[Dict[str, Any]] = None,
                fetch_list: Optional[Sequence] = None,
                scope: Optional[Scope] = None,
                perf: bool = False,
                memory: bool = False) -> dict:
        """Cost/memory report for the compiled program this
        (program, feed, fetch_list) resolves to — compiling it if
        needed, WITHOUT running it or consuming RNG state.

        Returns per-program FLOPs, bytes accessed, peak HBM and the
        argument-vs-temp footprint split (XLA cost model, or the jaxpr
        analytic fallback — see ``cost.source``), plus the program's op
        histogram and the executor's cache view of the key."""
        program = program or default_main_program()
        scope = scope or self.scope
        compiled, dev_feeds, state, fetch_names = self._prepare(
            program, feed or {}, list(fetch_list or []), scope)
        compiled.note_abs_args(state, dev_feeds,
                               self._peek_root(program))
        cost = compiled.cost()
        op_hist: Dict[str, int] = {}
        for op in compiled._ops:
            op_hist[op.type] = op_hist.get(op.type, 0) + 1
        fkey = (program._uid, tuple(fetch_names))
        # static-analysis section (paddle_tpu/analysis): full verifier
        # view of this (program, feed, fetch) triple.  Present ONLY
        # when verify_program is on, so the flag-off explain() report
        # stays byte-identical to the pre-analysis executor
        # (regression-tested, the PR 7 tensor_stats idiom).
        verify_mode = str(flags.get_flag("verify_program"))
        analysis_doc = {}
        if verify_mode in ("warn", "error"):
            from .. import analysis
            res = analysis.verify_program(
                program, feed=set(dev_feeds), fetch_list=fetch_names,
                scope=scope,
                feed_shapes={n: tuple(np.shape(a))
                             for n, a in dev_feeds.items()},
                # a read-only report: do NOT count these findings into
                # analysis_findings_total (explain may be polled)
                record_metrics=False)
            analysis_doc = {"analysis": {
                "mode": verify_mode,
                "counts": res.counts(),
                "findings": [f.to_dict() for f in res.sorted()[:20]],
            }}
        # persistent-cache section: present ONLY when jit_cache_dir is
        # set, so the flag-off explain() report stays byte-identical to
        # the pre-cache executor (the PR 7/10 idiom).  "source" says
        # whether THIS key's executable came off disk ("disk"),
        # compiled-and-stored ("compiled"), or has not dispatched yet.
        from . import jit_cache as pjit_cache
        jc_doc = {}
        if pjit_cache.enabled():
            jc_doc = {"jit_cache": {
                **pjit_cache.stats(),
                "entry": (compiled._persist_meta[1]
                          if compiled._persist_meta else None),
                "source": (compiled._persist_source
                           or compiled._donate_source),
            }}
        # perf section: present ONLY when the caller asked AND the
        # perfscope flag is on — the default explain() report stays
        # byte-identical to the pre-perfscope executor
        perf_doc = {}
        from ..observability import perfscope as obs_perfscope
        if perf and obs_perfscope.enabled() and cost is not None:
            prior = obs_perfscope.status_doc()["programs"].get(
                cost.label) or {}
            perf_doc = {"perf": {
                **obs_perfscope.explain_section(
                    cost, seconds=prior.get("last_s", 0.0)),
                "dispatches": prior.get("count", 0),
                "total_seconds": prior.get("total_s", 0.0),
            }}
        # memory section: same contract — present ONLY when the caller
        # asked AND the memscope flag is on (predicted-vs-measured peak
        # reconciliation + the current plane census)
        mem_doc = {}
        from ..observability import memscope as obs_memscope
        if memory and obs_memscope.enabled() and cost is not None:
            mem_doc = {"memory": obs_memscope.explain_section(cost)}
        return {
            "schema": "paddle_tpu.explain.v1",
            **analysis_doc,
            **jc_doc,
            **perf_doc,
            **mem_doc,
            "program": {"uid": program._uid,
                        "version": program._version,
                        "ops": len(compiled._ops),
                        "op_histogram": op_hist},
            "feeds": {n: {"shape": list(a.shape),
                          "dtype": str(a.dtype)}
                      for n, a in sorted(dev_feeds.items())},
            "fetches": list(fetch_names),
            "state": {"vars": len(state),
                      "bytes": int(sum(
                          getattr(a, "nbytes", 0) for a in
                          state.values()))},
            "cost": cost.to_dict() if cost else None,
            "cache": {
                "cached_programs": len(self._cache),
                "compiles_for_key":
                    self._compiles_by_fetch_key.get(fkey, 0),
                "recent_causes": obs_forensics.cause_histogram(
                    program._uid, tuple(fetch_names),
                    owner=self._forensics_owner),
            },
            "flags": {k: flags.get_flag(k) for k in
                      (("amp_bf16", "use_pallas_kernels", "cost_model",
                        "quantize_dtype", "fuse_block")
                       # reported only when ON: the stats-off explain()
                       # report stays byte-identical to the stats-less
                       # executor (regression-tested)
                       + (("tensor_stats", "tensor_stats_interval")
                          if flags.get_flag("tensor_stats") else ()))},
        }

    def last_run_cost(self, prefer_analytic: bool = False):
        """ProgramCost of the most recently prepared/run program (lazy
        analysis on first call) — the trainer's MFU source.
        ``prefer_analytic=True`` avoids the extra AOT compile (the
        trainer's default: one cheap abstract trace instead)."""
        c = self._last_compiled
        return c.cost(prefer_analytic=prefer_analytic) \
            if c is not None else None

    def compile_log(self, program: Optional[Program] = None):
        """The forensics compile log (diagnosed causes per compile),
        optionally filtered to one program."""
        return obs_forensics.compile_log(
            program._uid if program is not None else None)

    def cache_report(self, compute_costs: bool = True) -> dict:
        """Compile-cache explorer: every cached executable (step and
        run_steps device loops) with its cost/memory summary."""
        return obs_forensics.cache_report(self, compute_costs)

    def _globalize_feed(self, program, name, var, arr):
        """Build a global jax.Array for `arr` (the full global batch,
        identical on every process) matching the spec the compiled step
        expects — data vars shard over the batch/SPMD axis, everything
        else is replicated."""
        P = jax.sharding.PartitionSpec
        spec = P()
        if var is not None:
            if getattr(var, "sharding", None) is not None:
                spec = P(*var.sharding)
            elif var.is_data:
                spmd_axis = getattr(program, "_dist_spmd_axis", None)
                spec = _data_feed_spec(program, var,
                                       spmd_axis or self.batch_axis)
        sharding = jax.sharding.NamedSharding(self.mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    def fetch_numpy(self, v):
        """np.asarray, gathering shards first when the fetch is not fully
        addressable (multi-process mesh) — a collective, so every process
        must fetch in lockstep (they run the same program loop).  Public:
        the trainer and ParallelExecutor use it to convert fetches they
        obtained via run(return_numpy=False) after timing the device
        block separately (step anatomy)."""
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(
                v, tiled=True))
        return np.asarray(v)

    _fetch_numpy = fetch_numpy      # internal call sites / back-compat

    def close(self):
        self._cache.clear()
