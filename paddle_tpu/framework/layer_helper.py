"""LayerHelper: shared machinery for layer functions.

Capability parity with /root/reference/python/paddle/fluid/layer_helper.py:
creates parameters (wiring their initializer into the startup program),
creates output vars, appends ops, and applies activations / bias.

TPU-first addition: output shapes/dtypes are inferred by abstract evaluation
of the op's own lowering function (jax.eval_shape) — one source of truth
instead of the reference's separate C++ InferShape functions
(framework/shape_inference.h).  Dynamic (batch) dims use -1 and are restored
after abstract eval.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core import flags
from ..core.dtypes import to_jnp_dtype
from .program import (Block, Parameter, Variable, default_main_program,
                      default_startup_program)
from . import unique_name
from .initializer import Initializer, XavierInitializer, ConstantInitializer
from .registry import LowerContext, get_op_def

_DYN_SUBST = 97  # prime sentinel substituted for -1 dims during abstract eval


class ParamAttr:
    """Parameter attribute bundle (ref python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer: Optional[Initializer] = None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, sharding=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.sharding = sharding

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return None
        raise ValueError(f"bad param_attr: {attr!r}")


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        self.main_program = kwargs.get("main_program") or default_main_program()
        self.startup_program = (kwargs.get("startup_program")
                                or default_startup_program())

    @property
    def block(self) -> Block:
        return self.main_program.current_block()

    def name(self, suffix: str = "") -> str:
        base = self.kwargs.get("name") or unique_name.generate(self.layer_type)
        return f"{base}.{suffix}" if suffix else base

    # -- vars/params -------------------------------------------------------
    def create_variable_for_type_inference(self, dtype="float32",
                                           stop_gradient=False) -> Variable:
        return self.block.create_var(
            name=unique_name.generate(self.layer_type + ".tmp"),
            dtype=dtype, stop_gradient=stop_gradient)

    create_tmp_variable = create_variable_for_type_inference

    def create_parameter(self, attr, shape, dtype="float32",
                         is_bias: bool = False,
                         default_initializer: Optional[Initializer] = None
                         ) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        name = attr.name or unique_name.generate(
            self.kwargs.get("name") or self.layer_type
        ) + (".b_0" if is_bias else ".w_0")
        init = attr.initializer or default_initializer or (
            ConstantInitializer(0.0) if is_bias else XavierInitializer())
        shape = [int(s) for s in shape]
        # main-program parameter
        p = self.main_program.global_block().create_parameter(
            name, shape, dtype=dtype, trainable=attr.trainable,
            regularizer=attr.regularizer, sharding=attr.sharding)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        # startup-program twin + init op (ref layer_helper set_variable_initializer)
        sb = self.startup_program.global_block()
        if not sb.has_var(name):
            sp = sb.create_parameter(name, shape, dtype=dtype,
                                     trainable=attr.trainable,
                                     sharding=attr.sharding)
            init(sp, sb)
        return p

    # -- op append with abstract-eval shape inference ----------------------
    def append_op(self, type: str, inputs: Dict[str, Sequence[Variable]],
                  outputs: Dict[str, Sequence[Variable]],
                  attrs: Optional[Dict[str, Any]] = None):
        attrs = attrs or {}
        in_names = {k: [v.name for v in vs] for k, vs in inputs.items()}
        out_names = {k: [v.name for v in vs] for k, vs in outputs.items()}
        op = self.block.append_op(type, in_names, out_names, attrs)
        self._infer_shapes(type, inputs, outputs, attrs)
        return op

    def _infer_shapes(self, type, inputs, outputs, attrs):
        from ..core.dtypes import convert_dtype
        opdef = get_op_def(type)

        def abstract(v: Variable):
            shape = tuple(_DYN_SUBST if s == -1 else int(s)
                          for s in (v.shape or ()))
            return jax.ShapeDtypeStruct(shape, to_jnp_dtype(v.dtype))

        ins_abs = {k: [abstract(v) for v in vs] for k, vs in inputs.items()}
        flat_in = [a for vs in ins_abs.values() for a in vs]
        slots = [k for k, vs in ins_abs.items() for _ in vs]

        def g(*arrs):
            d: Dict[str, List[Any]] = {}
            for slot, a in zip(slots, arrs):
                d.setdefault(slot, []).append(a)
            ctx = LowerContext(jax.random.PRNGKey(0))
            return {k: list(v) for k, v in opdef.lower(ctx, d, attrs).items()}

        try:
            out_abs = jax.eval_shape(g, *flat_in)
        except Exception:
            return  # shape inference is best-effort build-time metadata

        had_dyn = any(-1 in (v.shape or ())
                      for vs in inputs.values() for v in vs)
        for slot, vars_ in outputs.items():
            for v, sd in zip(vars_, out_abs.get(slot, [])):
                shape = list(sd.shape)
                if had_dyn:
                    # restore -1 where the sentinel survived (possibly folded
                    # into a product by reshape/flatten — sentinel is prime)
                    shape = [-1 if s != 0 and s % _DYN_SUBST == 0 else s
                             for s in shape]
                v.shape = tuple(shape)
                v.dtype = convert_dtype(sd.dtype)

    # -- activation/bias sugar (ref layer_helper.py) -----------------------
    def append_bias_op(self, input_var: Variable, bias: Optional[Parameter],
                       dim_start: int = 1) -> Variable:
        if bias is None:
            return input_var
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op("elementwise_add",
                       {"X": [input_var], "Y": [bias]}, {"Out": [out]},
                       {"axis": dim_start})
        return out

    def append_activation(self, input_var: Variable,
                          act: Optional[str]) -> Variable:
        if act is None:
            return input_var
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(act, {"X": [input_var]}, {"Out": [out]}, {})
        return out
