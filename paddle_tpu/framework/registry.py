"""Op registry: the kernel-dispatch plane.

Capability parity with the reference's OpRegistry/OpInfoMap + REGISTER_OPERATOR
/ REGISTER_OP_*_KERNEL macros (/root/reference/paddle/fluid/framework/
op_registry.h:65,196) and OperatorWithKernel dispatch (operator.cc:764-817).

TPU-first difference: an op registers ONE `lower` function that emits jax/XLA
(or Pallas) computation for all devices — XLA owns per-backend kernel
selection, layout, and fusion, so the reference's (place, dtype, layout,
library) OpKernelType dispatch and implicit data-transform machinery
(framework/data_transform.cc) are unnecessary.  Dtype promotion/casting is
explicit in lowering code.

The reference's per-op GradOpDescMaker (grad_op_desc_maker.h:34) is subsumed
by jax.vjp over lowered forward segments (see framework/backward.py), so ops
get exact gradients for free; ops may still override with a custom VJP (e.g.
Pallas flash-attention).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax

from ..core.enforce import EnforceNotMet

# lower(ctx, ins: {slot: [jax.Array]}, attrs) -> {slot: [jax.Array]}
LowerFn = Callable[["LowerContext", Dict[str, List[Any]], Dict[str, Any]],
                   Dict[str, List[Any]]]


@dataclasses.dataclass
class OpDef:
    type: str
    lower: LowerFn
    # ops whose outputs must NOT be differentiated through even if reached
    # (metrics, assigns of ints, etc.)
    stop_gradient: bool = False
    # doc string for introspection (ref OpProtoMaker comments)
    doc: str = ""


_REGISTRY: Dict[str, OpDef] = {}
# compile-time shape/dtype inference rules (the reference's per-op
# InferShape, framework/shape_inference.h), registered alongside the
# OpDef via register_shape_infer and consumed by paddle_tpu/analysis.
# A separate map because rules may register before OR after their op
# (analysis imports lazily; ops register lazily on first get_op_def);
# get_shape_infer is the single source of truth.  Ops without a rule
# fall back to abstract evaluation of `lower`; ops where neither
# applies degrade to "unknown shape", never a crash.
_INFER_RULES: Dict[str, Callable] = {}


def register_op(type: str, stop_gradient: bool = False, doc: str = ""):
    """Decorator: @register_op("relu") def _(ctx, ins, attrs): ..."""
    def deco(fn: LowerFn):
        if type in _REGISTRY:
            raise EnforceNotMet(f"op {type!r} registered twice")
        _REGISTRY[type] = OpDef(type, fn, stop_gradient=stop_gradient,
                                doc=doc or (fn.__doc__ or ""))
        return fn
    return deco


def get_op_def(type: str) -> OpDef:
    if type not in _REGISTRY:
        # ops/__init__ registers everything lazily on first touch
        from .. import ops as _ops  # noqa: F401
        if type not in _REGISTRY:
            raise EnforceNotMet(f"Operator {type!r} is not registered. "
                                f"Known: {sorted(_REGISTRY)[:20]}...")
    return _REGISTRY[type]


def registered_ops() -> List[str]:
    from .. import ops as _ops  # noqa: F401
    return sorted(_REGISTRY)


def register_shape_infer(type: str, allow_override: bool = False):
    """Decorator: register a compile-time shape/dtype inference rule
    alongside the op's OpDef (the reference's REGISTER_OPERATOR
    InferShape slot).

    Rule signature (see analysis/shape_inference.py for the driver):

        rule(op, ins, attrs) -> {slot: [(shape, dtype)]} | None

    where ``ins`` maps input slots to [(shape, dtype)] with shape a
    tuple (-1 = dynamic dim) or None (unknown) and dtype a canonical
    string or None.  Raise analysis.InferError on a provable mismatch;
    return None to defer to the generic abstract-eval fallback.
    """
    def deco(fn: Callable):
        if type in _INFER_RULES and not allow_override:
            raise EnforceNotMet(f"shape-infer rule for {type!r} "
                                f"registered twice")
        _INFER_RULES[type] = fn
        return fn
    return deco


def get_shape_infer(type: str) -> Optional[Callable]:
    """The registered infer rule for an op type, or None."""
    return _INFER_RULES.get(type)


def unregister_shape_infer(type: str):
    """Test hook: drop a rule registered by a test (analysis.reset())."""
    _INFER_RULES.pop(type, None)


class LowerContext:
    """Per-trace lowering context handed to every op's lower().

    Carries what the reference's ExecutionContext (operator.h:166) carried —
    minus scope/stream, plus functional RNG: ops draw keys via ctx.rng(),
    derived deterministically from the program seed and an op counter.
    """

    def __init__(self, root_key, is_test: bool = False, mesh=None):
        self._root_key = root_key
        self._counter = 0
        self.is_test = is_test
        self.mesh = mesh
        self.place = None      # executor fills in; ops may consult

    def rng(self):
        self._counter += 1
        return jax.random.fold_in(self._root_key, self._counter)

    def pallas_interpret(self):
        """Whether Pallas kernels must run in interpret mode: True off-TPU.
        Uses the executing place when known (an Executor(CPUPlace()) in a
        TPU-enabled process must NOT compile Pallas for TPU); falls back
        to the default backend platform."""
        if self.place is not None:
            return self.place.jax_device().platform != "tpu"
        return jax.devices()[0].platform != "tpu"


def single_input(ins: Dict[str, List[Any]], slot: str = "X"):
    vs = ins.get(slot, [])
    if len(vs) != 1:
        raise EnforceNotMet(f"expected exactly one input in slot {slot!r}, "
                            f"got {len(vs)}")
    return vs[0]
