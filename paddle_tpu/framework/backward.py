"""Source-level autodiff: append_backward.

Capability parity with the reference's program-to-program backward pass
(/root/reference/python/paddle/fluid/backward.py:394 append_backward, which
calls per-op C++ GradOpDescMakers via core.get_grad_op_desc).

TPU-first design: instead of appending one grad op per forward op, a single
`autodiff` op is appended that records (loss, params, grad names).  At trace
time the Executor runs jax.vjp over the forward segment — XLA differentiates
every op exactly, including Pallas kernels with custom VJPs — and binds each
`param@GRAD` name to a real array.  Downstream optimizer ops consume those
grad vars exactly as in the reference, so the user-visible contract
(param_grads list, X@GRAD naming) is identical while the gradient computation
itself is compiler-generated rather than interpreter-replayed.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..core.enforce import check_arg
from .program import Parameter, Variable, grad_var_name


def append_backward(loss: Variable,
                    parameter_list: Optional[Sequence] = None,
                    no_grad_set=None) -> List[Tuple[Parameter, Variable]]:
    """Append gradient computation for `loss` w.r.t. trainable parameters.

    Returns [(param, grad_var)] like the reference (backward.py:394).
    """
    block = loss.block
    program = block.program
    check_arg(block.idx == 0,
              "append_backward must be called on the main (global) block")

    no_grad = {v.name if isinstance(v, Variable) else str(v)
               for v in (no_grad_set or ())}

    if parameter_list is not None:
        params = []
        for p in parameter_list:
            name = p.name if isinstance(p, Variable) else str(p)
            params.append(block.var(name))
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    params = [p for p in params if p.name not in no_grad]
    check_arg(len(params) > 0, "no trainable parameters to differentiate")

    param_grads: List[Tuple[Parameter, Variable]] = []
    grad_names = []
    for p in params:
        gname = grad_var_name(p.name)
        if not block.has_var(gname):
            gvar = block.create_var(name=gname, shape=p.shape, dtype=p.dtype,
                                    stop_gradient=True)
        else:
            gvar = block.var(gname)
        grad_names.append(gname)
        param_grads.append((p, gvar))

    # loss@GRAD exists for API parity (always ones_like(loss)).
    if not block.has_var(grad_var_name(loss.name)):
        block.create_var(name=grad_var_name(loss.name), shape=loss.shape,
                         dtype=loss.dtype, stop_gradient=True)

    block.append_op(
        "autodiff",
        inputs={"Loss": [loss.name], "Params": [p.name for p in params]},
        outputs={"Grads": grad_names},
        attrs={"loss": loss.name,
               "params": [p.name for p in params],
               "grads": grad_names})
    return param_grads
