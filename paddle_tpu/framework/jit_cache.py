"""Persistent executable cache: warm restarts skip XLA entirely.

Every compile site in the stack — the Executor's jit-cache miss path
and its ``run_steps`` device loops, the Predictor's AOT grid, the
serving engine's prefill-bucket grid and decode step — serializes its
compiled executable to disk (``jax.experimental.serialize_executable``)
keyed by a STABLE content hash, so a restarted process deserializes
instead of recompiling.  This is ROADMAP item 1: the elastic fleet
(PR 5) made worker restarts routine and the serving plane (PR 8)
re-AOTs its whole bucket grid per replica start; the before/after
gauges (``restart_to_first_step_seconds``, ``serving_ready_seconds``,
PR 11) measure exactly the cost this module removes.

Key anatomy (sha256 over canonical JSON; one entry file per key):

  * ``schema``     — on-disk format version (bump = fleet-wide miss)
  * ``env``        — jax/jaxlib versions + backend platform + device
                     kind: artifacts from a different build NEVER load
  * ``kind``       — executor_step | executor_multi | predictor |
                     serving_prefill | serving_decode
  * ``components`` — the forensics ``KeyParts`` vocabulary, made
                     process-independent: program TOPOLOGY hash
                     (``Program.serialize_to_string``, not the
                     process-local uid), feed shapes/dtypes, fetch
                     names, persistable-state signature, numerics
                     flags — plus per-site extras (bucket, steps, ...)

Entry file layout (``<hash>.jc``)::

  MAGIC(8) | header_len u32 | header JSON | body sha256(32) | body

The header is readable without unpickling the (large) body — the CLI's
``--ls`` and the stale-build check read it alone.  The body sha256
catches truncation and bit flips.  Loads are crash-proof by contract:
ANY failure (bad magic, torn write, flipped bit, foreign build, pickle
drift) warns loudly, counts ``jit_cache_errors_total{reason}``, drops
the entry, and the caller recompiles — a poisoned cache dir can never
brick a start.  Writes go to a unique temp file then ``os.replace``,
so a mid-write SIGKILL leaves only a ``*.tmp.*`` turd (swept by GC)
and two ranks storing the same key concurrently both land valid files
(last replace wins) — a shared fleet cache dir needs no lock.

Only VERIFIED programs are cached (the PR 10 ``verify_program`` gate):
the executor/predictor run full static verification before a store, so
a cached artifact is one the analysis plane vouched for.  The serving
engine's executables are built from framework code, not user programs
— no gate applies.

Metrics: ``jit_cache_{hits,misses,errors,evictions}_total`` (+kind /
reason labels) and ``jit_cache_bytes``.  Flags: ``jit_cache_dir``
("" = off, byte-identical behavior) and ``jit_cache_limit_bytes``
(LRU-by-mtime GC; hits touch mtime).

CLI: ``python -m paddle_tpu.framework.jit_cache --dir D --ls | --gc |
--purge | --warm SRC [DST] [--dry-run] | --self-test |
--restart-probe lm`` (exit 0 ok / 1 failure / 2 bad usage; the probe
is the bench driver's cold/warm child).  ``--warm`` pre-seeds the
cache dir (or an explicit DST dir) from another run's (or a shared
fleet dir's) entries — each candidate is fully validated (magic,
schema, THIS build's env, body checksum) before the copy, so a new
replica's first compile sites all hit without ever having compiled
here; ``--dry-run`` lists what would be copied and writes nothing.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import metrics as obs_metrics

_MAGIC = b"PTPUJC01"
_SCHEMA = 1
_SUFFIX = ".jc"

_m_hits = obs_metrics.counter(
    "jit_cache_hits_total",
    "Persistent executable cache: entries deserialized instead of "
    "compiled, by compile site.", ("kind",))
_m_misses = obs_metrics.counter(
    "jit_cache_misses_total",
    "Persistent executable cache: lookups that found no usable entry "
    "(the caller compiles and stores), by compile site.", ("kind",))
_m_errors = obs_metrics.counter(
    "jit_cache_errors_total",
    "Persistent executable cache: corrupt/stale/unwritable entries "
    "(magic, checksum, stale_env, deserialize, store, aot).  Every one "
    "degrades to a recompile, never a failed start.", ("reason",))
_m_evictions = obs_metrics.counter(
    "jit_cache_evictions_total",
    "Persistent executable cache entries deleted by the LRU byte-limit "
    "GC (jit_cache_limit_bytes).")
_m_unverified = obs_metrics.counter(
    "jit_cache_unverified_total",
    "Store attempts skipped because the program did not pass the "
    "verify_program static gate — only verified programs are cached.")
_m_bytes = obs_metrics.gauge(
    "jit_cache_bytes",
    "Total bytes of persistent executable cache entries on disk "
    "(refreshed on store/GC/CLI).")


# --- enablement --------------------------------------------------------------

def enabled() -> bool:
    return bool(str(flags.get_flag("jit_cache_dir")))


def cache_dir() -> str:
    return str(flags.get_flag("jit_cache_dir"))


def numerics_flags() -> Tuple[Tuple[str, Any], ...]:
    """The lowering-affecting flags every persistent key carries — the
    same set the Executor bakes into its in-memory jit key, so a flag
    flip is a clean MISS (new key), never a corrupt-entry error."""
    return (("amp_bf16", bool(flags.get_flag("amp_bf16"))),
            ("use_pallas_kernels",
             bool(flags.get_flag("use_pallas_kernels"))),
            ("quantize_dtype", str(flags.get_flag("quantize_dtype"))),
            ("fuse_block", bool(flags.get_flag("fuse_block"))))


def build_env() -> Dict[str, str]:
    """The build/backend identity stamped into every entry: an artifact
    serialized under a different jax/jaxlib/backend never loads."""
    import jax
    import jaxlib
    try:
        dev = jax.devices()[0]
        platform, kind = dev.platform, dev.device_kind
    except Exception:       # backend not initializable: identity only
        platform, kind = "unknown", "unknown"
    return {"jax": jax.__version__,
            "jaxlib": getattr(jaxlib, "__version__", "unknown"),
            "platform": platform, "device_kind": kind}


def program_fingerprint(program) -> str:
    """Process-independent topology hash of a Program — the persistent
    twin of the forensics KeyParts (program_uid, program_version) pair,
    which are process-local counters and would never match across a
    restart."""
    return hashlib.sha256(program.serialize_to_string()).hexdigest()


def entry_key(kind: str, components: Dict[str, Any]) -> str:
    """Stable content hash for one executable: schema + build env +
    site kind + the site's key components, canonically JSON-encoded."""
    doc = {"schema": _SCHEMA, "env": build_env(), "kind": kind,
           "components": components}
    blob = json.dumps(doc, sort_keys=True, default=repr).encode()
    return hashlib.sha256(blob).hexdigest()


# --- entry I/O ---------------------------------------------------------------

def _entry_path(key_hash: str) -> str:
    return os.path.join(cache_dir(), key_hash + _SUFFIX)


def _hits_path(key_hash: str) -> str:
    return os.path.join(cache_dir(), key_hash + ".hits")


def _bump_hits(key_hash: str):
    """Advisory per-entry hit count for --ls; atomic replace, lossy
    under concurrent ranks (acceptable: it is telemetry, not truth)."""
    path = _hits_path(key_hash)
    try:
        n = 0
        if os.path.exists(path):
            with open(path) as f:
                n = int(f.read().strip() or 0)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(n + 1))
        os.replace(tmp, path)
    except (OSError, ValueError):
        pass


def _atomic_write(path: str, data: bytes):
    """Unique temp file + os.replace: a mid-write SIGKILL cannot leave
    a half-entry under the final name, and two ranks racing the same
    key each land a complete file (last replace wins)."""
    tmp = f"{path}.tmp.{os.getpid()}.{time.monotonic_ns()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _fail_load(key_hash: str, reason: str, detail: str = "",
               drop: bool = True):
    _m_errors.labels(reason=reason).inc()
    obs_flight.record("jit_cache", "load_error", key=key_hash[:16],
                      reason=reason, detail=detail[:160])
    verb = "dropping" if drop else "skipping"
    warnings.warn(
        f"jit_cache: {verb} unusable entry {key_hash[:16]}… "
        f"({reason}{': ' + detail[:160] if detail else ''}); "
        f"recompiling instead — a corrupt cache never fails a start",
        RuntimeWarning, stacklevel=4)
    if drop:
        for p in (_entry_path(key_hash), _hits_path(key_hash)):
            try:
                os.remove(p)
            except OSError:
                pass


def record_error(reason: str, detail: str = ""):
    """Count a persistence failure that happened OUTSIDE entry I/O
    (e.g. an AOT lower+compile for serialization failing) — callers
    degrade to the plain jit path, never to a failed run."""
    _m_errors.labels(reason=reason).inc()
    obs_flight.record("jit_cache", "error", reason=reason,
                      detail=detail[:160])


def read_header(path: str) -> Optional[dict]:
    """Entry header (env/kind/components/created) without touching the
    pickled body; None when the header itself is unreadable."""
    try:
        with open(path, "rb") as f:
            if f.read(len(_MAGIC)) != _MAGIC:
                return None
            (hlen,) = struct.unpack("<I", f.read(4))
            if hlen > 1 << 20:
                return None
            return json.loads(f.read(hlen).decode())
    except (OSError, ValueError, struct.error):
        return None


def load(kind: str, key_hash: str, components: Dict[str, Any]):
    """Deserialize one entry into a callable ``jax.stages.Compiled``.

    Returns None on any miss or failure (counted + warned; the caller
    compiles).  A hit touches the entry's mtime (the LRU clock) and
    bumps its advisory hit counter."""
    path = _entry_path(key_hash)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        _m_misses.labels(kind=kind).inc()
        return None
    except OSError as e:
        _fail_load(key_hash, "io", repr(e), drop=False)
        _m_misses.labels(kind=kind).inc()
        return None
    fixed = len(_MAGIC) + 4
    if len(raw) < fixed or raw[:len(_MAGIC)] != _MAGIC:
        _fail_load(key_hash, "magic")
        _m_misses.labels(kind=kind).inc()
        return None
    (hlen,) = struct.unpack("<I", raw[len(_MAGIC):fixed])
    body_at = fixed + hlen + 32
    if len(raw) < body_at:
        _fail_load(key_hash, "truncated")
        _m_misses.labels(kind=kind).inc()
        return None
    try:
        header = json.loads(raw[fixed:fixed + hlen].decode())
    except ValueError:
        _fail_load(key_hash, "header")
        _m_misses.labels(kind=kind).inc()
        return None
    digest, body = raw[fixed + hlen:body_at], raw[body_at:]
    # stale-build guard: the env rides the header OUTSIDE the hash
    # preimage check so a hand-copied dir from another machine (same
    # path, different jaxlib) is rejected here, loudly, not at
    # deserialize time deep inside PJRT
    # stale entries are INTACT artifacts of another build — reject but
    # do NOT delete: in a briefly-mixed fleet (rolling jax upgrade)
    # each side would otherwise destroy the other side's valid cache
    if header.get("schema") != _SCHEMA:
        _fail_load(key_hash, "stale_schema", str(header.get("schema")),
                   drop=False)
        _m_misses.labels(kind=kind).inc()
        return None
    if header.get("env") != build_env():
        _fail_load(key_hash, "stale_env",
                   f"{header.get('env')} != {build_env()}", drop=False)
        _m_misses.labels(kind=kind).inc()
        return None
    if hashlib.sha256(body).digest() != digest:
        _fail_load(key_hash, "checksum")
        _m_misses.labels(kind=kind).inc()
        return None
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = pickle.loads(body)
        compiled = se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:      # pickle drift, PJRT refusal, anything
        _fail_load(key_hash, "deserialize", repr(e))
        _m_misses.labels(kind=kind).inc()
        return None
    try:
        os.utime(path)          # LRU clock
    except OSError:
        pass
    _bump_hits(key_hash)
    _m_hits.labels(kind=kind).inc()
    obs_flight.record("jit_cache", "hit", site=kind,
                      key=key_hash[:16])
    return compiled


def store(kind: str, key_hash: str, components: Dict[str, Any],
          compiled) -> bool:
    """Serialize ``compiled`` (a jax.stages.Compiled) under the key.
    Failures warn + count (reason=store) and return False — persistence
    is an optimization, never a correctness dependency."""
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
        body = pickle.dumps((payload, in_tree, out_tree))
        header = json.dumps(
            {"schema": _SCHEMA, "env": build_env(), "kind": kind,
             "components": components, "created": time.time()},
            sort_keys=True, default=repr).encode()
        blob = (_MAGIC + struct.pack("<I", len(header)) + header
                + hashlib.sha256(body).digest() + body)
        os.makedirs(cache_dir(), exist_ok=True)
        _atomic_write(_entry_path(key_hash), blob)
    except Exception as e:
        _m_errors.labels(reason="store").inc()
        warnings.warn(
            f"jit_cache: failed to persist {kind} entry "
            f"{key_hash[:16]}… ({repr(e)[:160]}); the compiled "
            f"executable still runs, only the NEXT restart pays",
            RuntimeWarning, stacklevel=3)
        return False
    obs_flight.record("jit_cache", "store", site=kind,
                      key=key_hash[:16], bytes=len(blob))
    gc()
    return True


# --- GC / inventory ----------------------------------------------------------

def _entries(dirpath: Optional[str] = None) -> List[dict]:
    d = dirpath or cache_dir()
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not name.endswith(_SUFFIX):
            continue
        path = os.path.join(d, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        out.append({"hash": name[:-len(_SUFFIX)], "path": path,
                    "bytes": st.st_size, "mtime": st.st_mtime})
    return out


def total_bytes(dirpath: Optional[str] = None) -> int:
    return sum(e["bytes"] for e in _entries(dirpath))


def gc(limit_bytes: Optional[int] = None) -> int:
    """LRU (oldest mtime first) eviction down to the byte limit; also
    sweeps ``*.tmp.*`` turds from killed writers.  Returns the number
    of entries evicted and refreshes jit_cache_bytes."""
    d = cache_dir()
    if not d:
        return 0
    limit = int(flags.get_flag("jit_cache_limit_bytes")
                if limit_bytes is None else limit_bytes)
    evicted = 0
    try:
        for name in os.listdir(d):
            if ".tmp." in name:
                # sweep only STALE temp files (a killed writer's turd);
                # a fresh one may be another rank's in-flight store in
                # a shared dir — deleting it would break the atomic
                # write it is about to os.replace
                path = os.path.join(d, name)
                try:
                    if time.time() - os.stat(path).st_mtime > 3600:
                        os.remove(path)
                except OSError:
                    pass
    except OSError:
        pass
    entries = sorted(_entries(d), key=lambda e: e["mtime"])
    total = sum(e["bytes"] for e in entries)
    if limit > 0:
        for e in entries:
            if total <= limit:
                break
            try:
                os.remove(e["path"])
            except OSError:
                continue
            try:
                os.remove(os.path.join(d, e["hash"] + ".hits"))
            except OSError:
                pass
            total -= e["bytes"]
            evicted += 1
            _m_evictions.inc()
    _m_bytes.set(total)
    return evicted


def warm(src_dir: str, dst_dir: Optional[str] = None,
         dry_run: bool = False) -> dict:
    """Pre-seed ``dst_dir`` (default: the active cache dir) from the
    entries in ``src_dir`` — a previous run's dir, or a shared fleet
    dir a new replica copies from before its first compile.

    Every candidate is validated BEFORE the copy with the same checks
    ``load`` applies (magic, header JSON, schema, env == this build,
    body sha256), so warming from a poisoned or foreign-build dir
    seeds nothing bad: stale/corrupt entries are counted and skipped,
    never copied and never deleted from the source.  Entries already
    present in the destination are left alone (their mtime is their
    LRU clock).  Copies use the atomic-write path, so a concurrent
    reader in the destination dir never sees a torn entry.

    ``dry_run`` validates and counts but writes nothing: ``copied``
    becomes would-copy and ``entries`` names each candidate."""
    dst = dst_dir or cache_dir()
    env = build_env()
    fixed = len(_MAGIC) + 4
    out = {"src": src_dir, "dst": dst, "copied": 0, "present": 0,
           "stale": 0, "corrupt": 0, "bytes": 0,
           "dry_run": bool(dry_run), "entries": []}
    for e in _entries(src_dir):
        dst_path = os.path.join(dst, os.path.basename(e["path"]))
        if os.path.exists(dst_path):
            out["present"] += 1
            continue
        try:
            with open(e["path"], "rb") as f:
                raw = f.read()
        except OSError:
            out["corrupt"] += 1
            continue
        if len(raw) < fixed or raw[:len(_MAGIC)] != _MAGIC:
            out["corrupt"] += 1
            continue
        (hlen,) = struct.unpack("<I", raw[len(_MAGIC):fixed])
        body_at = fixed + hlen + 32
        if len(raw) < body_at:
            out["corrupt"] += 1
            continue
        try:
            header = json.loads(raw[fixed:fixed + hlen].decode())
        except ValueError:
            out["corrupt"] += 1
            continue
        if (header.get("schema") != _SCHEMA
                or header.get("env") != env):
            out["stale"] += 1
            continue
        digest, body = raw[fixed + hlen:body_at], raw[body_at:]
        if hashlib.sha256(body).digest() != digest:
            out["corrupt"] += 1
            continue
        out["entries"].append(os.path.basename(e["path"]))
        if not dry_run:
            os.makedirs(dst, exist_ok=True)
            _atomic_write(dst_path, raw)
        out["copied"] += 1
        out["bytes"] += len(raw)
    obs_flight.record("jit_cache", "warm", src=src_dir,
                      copied=out["copied"], stale=out["stale"],
                      corrupt=out["corrupt"], dry_run=bool(dry_run))
    if not dry_run and dst == cache_dir():
        gc()                    # respect the byte limit + refresh gauge
    return out


def purge() -> int:
    """Delete every entry (and hit sidecar); returns entries removed."""
    d = cache_dir()
    n = 0
    for e in _entries(d):
        try:
            os.remove(e["path"])
            n += 1
        except OSError:
            pass
        try:
            os.remove(os.path.join(d, e["hash"] + ".hits"))
        except OSError:
            pass
    _m_bytes.set(total_bytes(d))
    return n


def ls() -> List[dict]:
    """Inventory: per entry, key components + size + age + hits."""
    now = time.time()
    out = []
    for e in sorted(_entries(), key=lambda e: -e["mtime"]):
        header = read_header(e["path"]) or {}
        hits = 0
        try:
            with open(_hits_path(e["hash"])) as f:
                hits = int(f.read().strip() or 0)
        except (OSError, ValueError):
            pass
        out.append({"hash": e["hash"], "kind": header.get("kind"),
                    "bytes": e["bytes"],
                    "age_seconds": round(now - e["mtime"], 1),
                    "hits": hits, "env": header.get("env"),
                    "components": header.get("components")})
    return out


def stats() -> dict:
    """Process-wide counters + on-disk totals (the explain() section)."""
    es = _entries()
    return {"dir": cache_dir(), "entries": len(es),
            "bytes": sum(e["bytes"] for e in es),
            "hits": _m_hits.total(), "misses": _m_misses.total(),
            "errors": _m_errors.total(),
            "evictions": _m_evictions.total()}


# --- verified-programs gate (PR 10) -----------------------------------------

def program_verified(program, feed_names, fetch_names, scope=None,
                     feed_shapes=None) -> bool:
    """True when the program passes full static verification — the
    condition for persisting its executable.  When the executor already
    runs in verify_program=error mode the gate has provably passed
    before any compile; callers skip re-running it there.  An analysis
    crash counts as NOT verified (skip persistence, never the run)."""
    try:
        from .. import analysis
        res = analysis.verify_program(
            program, feed=set(feed_names), fetch_list=list(fetch_names),
            scope=scope, feed_shapes=feed_shapes, record_metrics=False)
        ok = not res.errors
    except Exception:
        ok = False
    if not ok:
        _m_unverified.inc()
        obs_flight.record("jit_cache", "store_skipped_unverified",
                          program=getattr(program, "_uid", None))
    return ok


# --- CLI ---------------------------------------------------------------------

def _self_test() -> int:
    """End-to-end round trip in a throwaway subdir of the cache dir:
    compile a tiny fn, store, corrupt-check, reload, call, GC."""
    import tempfile

    import jax
    import jax.numpy as jnp
    old = cache_dir()
    with tempfile.TemporaryDirectory() as td:
        flags.set_flag("jit_cache_dir", td)
        try:
            fn = jax.jit(lambda x: x * 2.0 + 1.0)
            x = jnp.arange(4, dtype=jnp.float32)
            compiled = fn.lower(x).compile()
            comps = {"probe": "self_test"}
            khash = entry_key("executor_step", comps)
            if not store("executor_step", khash, comps, compiled):
                print("self-test: store failed")
                return 1
            back = load("executor_step", khash, comps)
            if back is None:
                print("self-test: reload failed")
                return 1
            import numpy as np
            if not np.allclose(np.asarray(back(x)),
                               np.asarray(x) * 2.0 + 1.0):
                print("self-test: wrong outputs after reload")
                return 1
            # corruption must degrade to a miss, loudly, not raise
            path = _entry_path(khash)
            with open(path, "r+b") as f:
                f.seek(-1, os.SEEK_END)
                f.write(b"\x00")
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                if load("executor_step", khash, comps) is not None:
                    print("self-test: corrupt entry loaded")
                    return 1
            gc()
            print("self-test: ok (store/load/corrupt-fallback/gc)")
            return 0
        finally:
            flags.set_flag("jit_cache_dir", old)


def _restart_probe(workload: str, steps: int = 2) -> int:
    """Bench/test child: build the flagship LM through the Trainer,
    complete ``steps`` steps, and print one RESTART_PROBE JSON line
    with restart_to_first_step_seconds + compile/cache counters.  Run
    it twice against the same PTPU_JIT_CACHE_DIR for cold vs warm."""
    if workload != "lm":
        print(f"unknown --restart-probe workload {workload!r}")
        return 2
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import models

    cfg = models.transformer.TransformerConfig(
        src_vocab_size=211, tgt_vocab_size=211, max_length=32,
        n_layer=2, n_head=2, d_model=32, d_inner=64, dropout=0.0)
    B, T = 2, 16
    batch = models.transformer.make_fake_lm_batch(cfg, B, T)
    order = ["tokens", "labels"]

    def train_func():
        _, cost, _ = models.transformer.build_lm_net(
            cfg, seq_len=T, fused_attention=False, fused_head=False)
        return cost

    def reader():
        yield [tuple(batch[n][i] for n in order) for i in range(B)]

    losses: List[float] = []

    def handler(event):
        if isinstance(event, pt.EndStepEvent):
            losses.append(float(np.asarray(event.metrics[0])))

    trainer = pt.Trainer(train_func,
                         lambda: pt.optimizer.Adam(learning_rate=1e-3),
                         place=pt.CPUPlace())
    trainer.train(num_epochs=int(steps), event_handler=handler,
                  reader=reader, feed_order=order)
    reg = obs_metrics.REGISTRY

    def _total(name):
        m = reg.get(name)
        return 0.0 if m is None else m.total()

    restart = reg.get("restart_to_first_step_seconds")
    print("RESTART_PROBE " + json.dumps({
        "restart_to_first_step_seconds":
            None if restart is None else restart.value,
        "executor_compile_total": _total("executor_compile_total"),
        "jit_cache_hits_total": _total("jit_cache_hits_total"),
        "jit_cache_misses_total": _total("jit_cache_misses_total"),
        "jit_cache_errors_total": _total("jit_cache_errors_total"),
        "losses": [round(v, 6) for v in losses],
    }), flush=True)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.framework.jit_cache",
        description="Persistent executable cache inspector/maintainer.")
    parser.add_argument("--dir", default=None,
                        help="cache dir (default: the jit_cache_dir "
                             "flag / PTPU_JIT_CACHE_DIR)")
    parser.add_argument("--ls", action="store_true",
                        help="list entries (key components, size, age, "
                             "hits)")
    parser.add_argument("--gc", action="store_true",
                        help="apply jit_cache_limit_bytes now")
    parser.add_argument("--purge", action="store_true",
                        help="delete every entry")
    parser.add_argument("--warm", default=None, nargs="+",
                        metavar=("SRC", "DST"),
                        help="pre-seed DST (default: the cache dir) "
                             "from SRC's entries (validated: only "
                             "intact artifacts of THIS build are "
                             "copied)")
    parser.add_argument("--dry-run", action="store_true",
                        help="with --warm: validate and list what WOULD "
                             "be copied, write nothing")
    parser.add_argument("--self-test", action="store_true",
                        help="store/load/corrupt-fallback round trip "
                             "in a temp dir")
    parser.add_argument("--restart-probe", default=None, metavar="WL",
                        help="bench child: run WL ('lm') through the "
                             "Trainer and print cold-start numbers")
    parser.add_argument("--steps", type=int, default=2)
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    if args.self_test:
        return _self_test()
    if args.restart_probe:
        return _restart_probe(args.restart_probe, args.steps)
    old_dir = cache_dir()
    if args.dir is not None:
        flags.set_flag("jit_cache_dir", args.dir)
    try:
        if not (args.ls or args.gc or args.purge or args.warm):
            parser.print_usage()
            return 2
        if args.warm and len(args.warm) > 2:
            print("--warm takes SRC [DST]")
            return 2
        # the two-dir form names its destination explicitly — only the
        # one-dir form (and every other op) needs an active cache dir
        warm_dst = args.warm[1] if args.warm and len(args.warm) == 2 \
            else None
        needs_dir = (args.ls or args.gc or args.purge
                     or (args.warm and warm_dst is None))
        if needs_dir and not cache_dir():
            print("no cache dir: pass --dir or set jit_cache_dir / "
                  "PTPU_JIT_CACHE_DIR")
            return 2
        if args.warm:
            r = warm(args.warm[0], dst_dir=warm_dst,
                     dry_run=args.dry_run)
            verb = "would copy" if args.dry_run else "copied"
            print(f"warm: {verb} {r['copied']} entr(ies) "
                  f"({r['bytes']} bytes) from {args.warm[0]} to "
                  f"{r['dst']}; "
                  f"{r['present']} already present, {r['stale']} stale, "
                  f"{r['corrupt']} corrupt skipped")
            if args.dry_run:
                for nm in r["entries"]:
                    print(f"  would copy {nm}")
        if args.purge:
            print(f"purged {purge()} entr(ies) from {cache_dir()}")
        if args.gc:
            n = gc()
            print(f"gc: evicted {n} entr(ies); {total_bytes()} bytes "
                  f"resident (limit "
                  f"{flags.get_flag('jit_cache_limit_bytes')})")
        if args.ls:
            rows = ls()
            print(json.dumps({"dir": cache_dir(), "entries": len(rows),
                              "bytes": sum(r["bytes"] for r in rows),
                              "rows": rows}, indent=2, default=repr))
        return 0
    finally:
        # in-proc callers (tests) must not inherit the CLI's --dir as
        # ambient process state
        flags.set_flag("jit_cache_dir", old_dir)


if __name__ == "__main__":
    raise SystemExit(main())
