"""Host-side metric accumulators (ref python/paddle/fluid/metrics.py:
MetricBase, Accuracy, ChunkEvaluator, EditDistance, DetectionMAP, Auc)."""
from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("no samples accumulated in Accuracy metric")
        return self.value / self.weight


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args):
        for m, a in zip(self._metrics, args):
            m.update(*a)

    def eval(self):
        return [m.eval() for m in self._metrics]


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def update(self, distances, seq_num):
        self.total += float(np.sum(distances))
        self.count += int(seq_num)

    def eval(self):
        return self.total / max(self.count, 1)


class Auc(MetricBase):
    """Host-side streaming AUC from prediction/label batches."""

    def __init__(self, name=None, num_thresholds=4095):
        super().__init__(name)
        self._n = num_thresholds
        self.reset()

    def reset(self):
        self.stat_pos = np.zeros(self._n + 1)
        self.stat_neg = np.zeros(self._n + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p1 = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else (
            preds.reshape(-1))
        bucket = np.clip((p1 * self._n).astype(int), 0, self._n)
        for b, l in zip(bucket, labels):
            if l > 0:
                self.stat_pos[b] += 1
            else:
                self.stat_neg[b] += 1

    def eval(self):
        tp = np.cumsum(self.stat_pos[::-1])[::-1]
        fp = np.cumsum(self.stat_neg[::-1])[::-1]
        tot_pos, tot_neg = tp[0], fp[0]
        if tot_pos * tot_neg == 0:
            return 0.0
        tpn = np.append(tp[1:], 0.0)
        fpn = np.append(fp[1:], 0.0)
        area = np.sum((fp - fpn) * (tp + tpn) / 2.0)
        return float(area / (tot_pos * tot_neg))


class DetectionMAP(MetricBase):
    """11-point / integral mAP over accumulated detections."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=False, ap_version="integral"):
        super().__init__(name)
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        # per-class: list of (score, tp) + gt count
        self._dets = {}
        self._gts = {}

    def update(self, detections, gt_boxes, gt_labels):
        """detections: (M, 6) [cls, score, x1, y1, x2, y2];
        gt_boxes: (G, 4); gt_labels: (G,)."""
        detections = np.asarray(detections)
        gt_boxes = np.asarray(gt_boxes)
        gt_labels = np.asarray(gt_labels).reshape(-1)
        matched = set()
        for g in gt_labels:
            self._gts[int(g)] = self._gts.get(int(g), 0) + 1
        order = np.argsort(-detections[:, 1])
        for i in order:
            cls, score = int(detections[i, 0]), detections[i, 1]
            if score < 0:
                continue
            box = detections[i, 2:6]
            best_iou, best_j = 0.0, -1
            for j in range(len(gt_boxes)):
                if int(gt_labels[j]) != cls or j in matched:
                    continue
                iou = _iou(box, gt_boxes[j])
                if iou > best_iou:
                    best_iou, best_j = iou, j
            tp = best_iou >= self.overlap_threshold and best_j >= 0
            if tp:
                matched.add(best_j)
            self._dets.setdefault(cls, []).append((float(score), tp))

    def eval(self):
        aps = []
        for cls, dets in self._dets.items():
            npos = self._gts.get(cls, 0)
            if npos == 0:
                continue
            dets = sorted(dets, key=lambda d: -d[0])
            tps = np.cumsum([d[1] for d in dets])
            fps = np.cumsum([not d[1] for d in dets])
            rec = tps / npos
            prec = tps / np.maximum(tps + fps, 1e-12)
            if self.ap_version == "11point":
                ap = np.mean([prec[rec >= t].max() if (rec >= t).any()
                              else 0.0 for t in np.linspace(0, 1, 11)])
            else:
                mrec = np.concatenate([[0], rec, [1]])
                mpre = np.concatenate([[0], prec, [0]])
                for k in range(len(mpre) - 2, -1, -1):
                    mpre[k] = max(mpre[k], mpre[k + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1])
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0


def _iou(a, b):
    ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
    ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
    ua = ((a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1])
          - inter)
    return inter / max(ua, 1e-12)
