"""Contrib utilities (the reference's python/paddle/fluid/contrib tier:
memory_usage_calc.py, op_frequence.py)."""
from .memory_usage_calc import memory_usage
from .op_frequence import op_freq_statistic

__all__ = ["memory_usage", "op_freq_statistic"]
