"""Estimate a Program's device memory usage at a batch size (ref
python/paddle/fluid/contrib/memory_usage_calc.py:1).

The reference sums VarDesc bytes with the batch dim substituted.  Here
the same walk runs over the Program IR, split into the two pools that
matter under XLA:

  * persistable bytes — parameters/optimizer state, resident across
    steps (a hard floor);
  * activation bytes — every non-persistable var with the batch dim
    substituted, an UPPER bound on live activations (XLA's liveness
    frees/fuses aggressively, so the true peak is usually well below).

Returns (min_bytes, max_bytes, unit_str) scaled to a readable unit,
mirroring the reference's (min, max, unit) contract: min = persistable
only, max = persistable + all activations.
"""
from __future__ import annotations

import numpy as np

from ..analysis import traversal
from ..core.dtypes import convert_dtype

__all__ = ["memory_usage"]

_DTYPE_SIZE = {
    "bool": 1, "int8": 1, "uint8": 1, "int16": 2, "float16": 2,
    "bfloat16": 2, "int32": 4, "float32": 4, "int64": 8, "float64": 8,
}

_UNITS = [(1 << 30, "GB"), (1 << 20, "MB"), (1 << 10, "KB"), (1, "B")]


def _var_bytes(var, batch_size: int) -> int:
    shape = getattr(var, "shape", None)
    if not shape:
        return 0
    dims = [batch_size if int(d) == -1 else int(d) for d in shape]
    return int(np.prod(dims)) * _DTYPE_SIZE.get(
        convert_dtype(var.dtype), 4)


def memory_usage(program, batch_size: int):
    """Estimate memory for `program` at `batch_size`.

    Returns (min_usage, max_usage, unit_str): the persistable floor and
    the persistable + total-activation ceiling, in the largest unit
    that keeps max_usage >= 1."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")
    persist = acts = 0
    # the shared IR walk (analysis/traversal.py) — one iterator for the
    # verifier passes AND these contrib estimators
    for _, var in traversal.iter_vars(program):
        b = _var_bytes(var, batch_size)
        if getattr(var, "persistable", False):
            persist += b
        else:
            acts += b
    lo, hi = float(persist), float(persist + acts)
    for scale, unit in _UNITS:
        if hi >= scale:
            return lo / scale, hi / scale, unit
    return lo, hi, "B"
