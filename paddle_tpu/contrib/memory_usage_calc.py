"""Estimate a Program's device memory usage at a batch size (ref
python/paddle/fluid/contrib/memory_usage_calc.py:1).

The reference sums VarDesc bytes with the batch dim substituted.  Here
the same walk runs over the Program IR, split into the two pools that
matter under XLA:

  * persistable bytes — parameters/optimizer state, resident across
    steps (a hard floor);
  * activation bytes — every non-persistable var with the batch dim
    substituted, an UPPER bound on live activations (XLA's liveness
    frees/fuses aggressively, so the true peak is usually well below).

Returns (min_bytes, max_bytes, unit_str) scaled to a readable unit,
mirroring the reference's (min, max, unit) contract: min = persistable
only, max = persistable + all activations.
"""
from __future__ import annotations

import numpy as np

from ..analysis import traversal
from ..core.dtypes import convert_dtype

__all__ = ["memory_usage", "memory_usage_bytes", "cross_check"]

_DTYPE_SIZE = {
    "bool": 1, "int8": 1, "uint8": 1, "int16": 2, "float16": 2,
    "bfloat16": 2, "int32": 4, "float32": 4, "int64": 8, "float64": 8,
}

_UNITS = [(1 << 30, "GB"), (1 << 20, "MB"), (1 << 10, "KB"), (1, "B")]


def _var_bytes(var, batch_size: int) -> int:
    shape = getattr(var, "shape", None)
    if not shape:
        return 0
    dims = [batch_size if int(d) == -1 else int(d) for d in shape]
    return int(np.prod(dims)) * _DTYPE_SIZE.get(
        convert_dtype(var.dtype), 4)


def memory_usage_bytes(program, batch_size: int):
    """Raw-bytes variant of :func:`memory_usage`: returns
    (persistable_bytes, activation_bytes) unscaled — what the memscope
    cross-check joins against the cost model."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")
    persist = acts = 0
    # the shared IR walk (analysis/traversal.py) — one iterator for the
    # verifier passes AND these contrib estimators
    for _, var in traversal.iter_vars(program):
        b = _var_bytes(var, batch_size)
        if getattr(var, "persistable", False):
            persist += b
        else:
            acts += b
    return persist, acts


def memory_usage(program, batch_size: int):
    """Estimate memory for `program` at `batch_size`.

    Returns (min_usage, max_usage, unit_str): the persistable floor and
    the persistable + total-activation ceiling, in the largest unit
    that keeps max_usage >= 1."""
    persist, acts = memory_usage_bytes(program, batch_size)
    lo, hi = float(persist), float(persist + acts)
    for scale, unit in _UNITS:
        if hi >= scale:
            return lo / scale, hi / scale, unit
    return lo, hi, "B"


def cross_check(program, batch_size: int, cost, tolerance: float = 8.0):
    """Join this static walk with the cost model's per-component
    memory_bytes view of the SAME program (Executor.explain's ``cost``
    dict, or a ProgramCost) and verdict each comparison within a
    factor-``tolerance`` band (log-scale: ok iff 1/t <= static/model
    <= t).

    Two comparisons, each a row naming its component:

      * ``persistable_vs_argument``: the persistable floor against the
        cost model's argument_bytes.  Arguments carry the persistable
        state INTO the step (plus feeds, plus donated doubles under
        the analytic fallback), so these agree within a small factor.
      * ``ceiling_vs_peak``: persistable + total activations against
        peak_hbm_bytes.  The static ceiling counts EVERY intermediate
        var while XLA's liveness frees/fuses aggressively, so the band
        absorbs an op-count-shaped gap — the default factor 8 is the
        documented tolerance (tests assert with it).

    Returns {"ok": bool, "rows": [...], "diverging": [component...]}
    — the diverging list names what drifted, for the test failure
    message and the parity table."""
    if hasattr(cost, "to_dict"):
        cost = cost.to_dict()
    persist, acts = memory_usage_bytes(program, batch_size)
    rows = []

    def row(component, static_b, model_b):
        static_b, model_b = float(static_b), float(model_b or 0.0)
        if static_b > 0 and model_b > 0:
            ratio = static_b / model_b
            ok = (1.0 / tolerance) <= ratio <= tolerance
        else:
            # degenerate programs (no persistables / zero-cost): no
            # signal either way — don't fail the check on them
            ratio, ok = None, True
        rows.append({"component": component, "static_bytes": static_b,
                     "model_bytes": model_b, "ratio": ratio, "ok": ok})

    row("persistable_vs_argument", persist,
        (cost or {}).get("argument_bytes"))
    row("ceiling_vs_peak", persist + acts,
        (cost or {}).get("peak_hbm_bytes"))
    diverging = [r["component"] for r in rows if not r["ok"]]
    return {"ok": not diverging, "tolerance": tolerance, "rows": rows,
            "diverging": diverging}
