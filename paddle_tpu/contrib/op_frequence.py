"""Op-frequency histogram over a Program (ref
python/paddle/fluid/contrib/op_frequence.py:1).

Walks the Program IR through the analysis traversal helpers
(paddle_tpu/analysis/traversal.py) — the same iterators every verifier
pass uses — so this module can no longer rot against the IR
independently (it predates the current Block/Operator layout).
"""
from __future__ import annotations

from collections import OrderedDict

from ..analysis import traversal
from ..framework.program import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Counts of each op type and of each adjacent op pair, most
    frequent first.  Returns (uni_op_freq, adj_2_op_freq) OrderedDicts
    — the reference's contract."""
    if not isinstance(program, Program):
        raise TypeError(f"The input type should be Program, got "
                        f"{type(program)}")
    uni: "OrderedDict[str, int]" = OrderedDict()
    adj: "OrderedDict[str, int]" = OrderedDict()
    for _, _, op in traversal.iter_ops(program):
        uni[op.type] = uni.get(op.type, 0) + 1
    for prev, cur in traversal.adjacent_op_pairs(program):
        key = f"{prev}->{cur}"
        adj[key] = adj.get(key, 0) + 1
    uni = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni, adj
