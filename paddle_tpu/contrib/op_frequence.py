"""Op-frequency histogram over a Program (ref
python/paddle/fluid/contrib/op_frequence.py:1)."""
from __future__ import annotations

from collections import OrderedDict

from ..framework.program import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Counts of each op type and of each adjacent op pair, most
    frequent first.  Returns (uni_op_freq, adj_2_op_freq) OrderedDicts
    — the reference's contract."""
    if not isinstance(program, Program):
        raise TypeError(f"The input type should be Program, got "
                        f"{type(program)}")
    uni: "OrderedDict[str, int]" = OrderedDict()
    adj: "OrderedDict[str, int]" = OrderedDict()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] = uni.get(op.type, 0) + 1
            if prev is not None:
                key = f"{prev}->{op.type}"
                adj[key] = adj.get(key, 0) + 1
            prev = op.type
    uni = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni, adj
