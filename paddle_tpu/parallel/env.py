"""Multi-host distributed environment bootstrap.

Capability parity with the reference's multi-node rendezvous:
gen_nccl_id_op (rank0 ncclGetUniqueId RPC-broadcast,
/root/reference/paddle/fluid/operators/distributed_ops/gen_nccl_id_op.cc:31)
and the PADDLE_TRAINER_* env-var topology plane
(benchmark/fluid/README.md:35-47, contrib/trainer.py role parsing).

TPU-native: jax.distributed.initialize handles the rendezvous through the
coordinator; afterwards jax.devices() spans all hosts and meshes laid out
over it put the batch axis on DCN between hosts and ICI within a host.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax


def get_world_info() -> Tuple[int, int, Optional[str]]:
    """(trainer_id, num_trainers, coordinator) from PADDLE_*-compatible or
    PTPU_* env vars."""
    rank = int(os.environ.get("PTPU_TRAINER_ID",
                              os.environ.get("PADDLE_TRAINER_ID", "0")))
    world = int(os.environ.get("PTPU_TRAINERS_NUM",
                               os.environ.get("PADDLE_TRAINERS_NUM", "1")))
    endpoint = os.environ.get(
        "PTPU_COORDINATOR",
        os.environ.get("PADDLE_CURRENT_ENDPOINT"))
    return rank, world, endpoint


def init_distributed_env(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None):
    """Replaces the gen_nccl_id handshake.  No-op for single-host."""
    rank, world, endpoint = get_world_info()
    coordinator_address = coordinator_address or endpoint
    num_processes = num_processes or world
    process_id = process_id if process_id is not None else rank
    if num_processes <= 1 or coordinator_address is None:
        return False
    # CPU worlds need an explicit cross-process collectives backend:
    # without it XLA's CPU client raises "Multiprocess computations
    # aren't implemented" at the first collective dispatch.  Best-effort
    # (older jaxlibs lack the option; TPU/GPU never needs it).
    try:
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
    except Exception:
        pass
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True
