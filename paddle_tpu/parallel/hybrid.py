"""Hybrid-parallel Transformer LM training step: dp × pp × tp(+sp) × ep.

This is the TPU-native superset of the reference's entire distributed stack
(SURVEY.md §2.3 table "Parallelism strategies"): where the reference only has
data parallelism (ParallelExecutor SSA graph + NCCL allreduce,
/root/reference/paddle/fluid/framework/details/multi_devices_graph_pass.cc:572;
pserver mode, transpiler/distribute_transpiler.py:268), this module composes

  dp — batch sharding, gradient psum            (≈ NCCL allreduce :107)
  pp — GPipe pipeline over 'pp' via ppermute,
       microbatch scan                          (new capability)
  tp — Megatron tensor parallel: column/row
       sharded matmuls, vocab-parallel
       embedding + cross entropy                (new capability)
  sp — Megatron sequence parallelism: activations between blocks are
       sequence-sharded over the SAME tp axis; all_gather before the
       column-parallel matmuls, psum_scatter after the row-parallel ones
  ep — expert parallelism: switch-MoE FFN, experts sharded over the dp
       axis, token dispatch via all_to_all      (≈ the *capability* of the
       sharded pserver embedding path, distribute_transpiler.py:1010)

Everything is per-device code inside ONE jax.shard_map over the full mesh —
collectives are explicit (psum / all_gather / psum_scatter / ppermute /
all_to_all), exactly the scaling-book recipe — and jax.grad differentiates
through all of them, which is what replaces the reference's hand-built
backward comm ops.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import jax_compat
from .topology import grad_reduce_axes


@dataclass
class HybridConfig:
    vocab_size: int = 32000
    seq_len: int = 128
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4            # total dense blocks; must divide by pp
    d_ff: int = 1024
    n_microbatches: int = 2      # pipeline microbatches (per dp replica)
    moe_experts: int = 0         # 0 = dense only; else experts per MESH dp axis total
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    compute_dtype: Any = jnp.float32   # bfloat16 on real TPU runs
    remat: bool = True           # jax.checkpoint each stage (HBM for FLOPs)
    learning_rate: float = 1e-3
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    adam_eps: float = 1e-8


def _specs(mesh: Mesh, cfg: HybridConfig) -> Dict[str, P]:
    """PartitionSpec per parameter leaf. Grad reduction axes are derived as
    (mesh axes) - (axes named in the spec)."""
    s = {
        "embed": P("tp", None),            # vocab-parallel rows
        "pos": P(None, None),
        "ln_f": P(None),
        # stacked per-layer block weights, axis 0 = layer -> pp
        "ln1": P("pp", None, None),
        # [L, D, H, 3*hd]: heads axis shards over tp (column parallel)
        "wqkv": P("pp", None, "tp", None),
        # [L, H, hd, D]: heads axis shards over tp (row parallel)
        "wo": P("pp", "tp", None, None),
        "ln2": P("pp", None, None),
        "w1": P("pp", None, "tp"),          # column parallel
        "w2": P("pp", "tp", None),          # row parallel
    }
    if cfg.moe_experts:
        s.update({
            "moe_gate": P("pp", None, None),          # [pp, D, E] replicated/tp
            "moe_w1": P("pp", "dp", None, None),      # [pp, E, D, Fe]
            "moe_w2": P("pp", "dp", None, None),      # [pp, E, Fe, D]
            "moe_ln": P("pp", None, None),
        })
    return s


def init_params(mesh: Mesh, cfg: HybridConfig, seed: int = 0):
    """Global param pytree laid out across the mesh per _specs."""
    rng = np.random.RandomState(seed)
    D, Ff, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    Pp = mesh.shape["pp"]
    assert L % Pp == 0, "n_layers must be divisible by pp"
    assert cfg.d_model % cfg.n_heads == 0
    assert cfg.n_heads % mesh.shape["tp"] == 0, "heads must divide by tp"
    assert cfg.vocab_size % mesh.shape["tp"] == 0
    assert cfg.seq_len % mesh.shape["tp"] == 0, "seq must divide by tp (sp)"

    def g(*shape, scale=None):
        scale = scale if scale is not None else (1.0 / np.sqrt(shape[-2]))
        return (rng.randn(*shape) * scale).astype("float32")

    params = {
        "embed": g(cfg.vocab_size, D, scale=0.02),
        "pos": g(cfg.seq_len, D, scale=0.02),
        "ln_f": np.ones((D,), "float32"),
        # per-layer stacks; ln kept [L, 1, D] so scan slices stay rank-2
        "ln1": np.ones((L, 1, D), "float32"),
        # head-major qkv so tp shards whole heads: [L, D, H, 3*hd]
        "wqkv": g(L, D, cfg.n_heads, 3 * (D // cfg.n_heads)),
        "wo": g(L, cfg.n_heads, D // cfg.n_heads, D,
                scale=1.0 / np.sqrt(D)),
        "ln2": np.ones((L, 1, D), "float32"),
        "w1": g(L, D, Ff),
        "w2": g(L, Ff, D),
    }
    if cfg.moe_experts:
        E = cfg.moe_experts
        assert E % mesh.shape["dp"] == 0, "experts must divide by dp (ep)"
        Fe = Ff
        params["moe_gate"] = g(Pp, D, E, scale=0.02)
        params["moe_w1"] = g(Pp, E, D, Fe)
        params["moe_w2"] = g(Pp, E, Fe, D)
        params["moe_ln"] = np.ones((Pp, 1, D), "float32")

    specs = _specs(mesh, cfg)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def init_opt_state(params):
    zeros = jax.tree.map(jnp.zeros_like, params)
    return {"m": zeros, "v": jax.tree.map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32)}


# --------------------------------------------------------------------------
# per-device building blocks (run inside shard_map)
# --------------------------------------------------------------------------

def _ln(x, scale, eps=1e-5):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * scale


def _attention(h_full, wqkv, wo, dtype):
    """Causal MHA on the full sequence with locally-held heads (tp) —
    all matmuls hit the MXU; XLA fuses mask+softmax.
    wqkv: [D, Hl, 3*hd] head-major; wo: [Hl, hd, D]."""
    mb, T, D = h_full.shape
    hd = wqkv.shape[-1] // 3
    qkv = jnp.einsum("btd,dhe->bthe", h_full, wqkv.astype(dtype))
    q, k, v = jnp.split(qkv, 3, axis=-1)          # [mb, T, Hl, hd]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", w, v)     # [mb, T, Hl, hd]
    return jnp.einsum("bqhd,hdf->bqf", ctx, wo.astype(dtype))


def _moe_ffn(x_s, gate_w, w1e, w2e, cfg: HybridConfig, dp_size, dtype):
    """Switch (top-1) MoE with expert parallelism over the dp axis.

    x_s: [S, D] local tokens (seq-sharded). Experts: E total, E/dp local.
    Returns (out [S, D], aux_loss scalar)."""
    S, D = x_s.shape
    E = gate_w.shape[-1]
    El = E // dp_size
    C = max(1, int(cfg.moe_capacity_factor * S / E))

    logits = jnp.einsum("sd,de->se", x_s, gate_w.astype(dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    expert = jnp.argmax(probs, -1)                       # [S]
    gate = jnp.max(probs, -1)                            # [S]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    # aux load-balance loss (Switch Transformer eq. 4)
    density = jnp.mean(onehot, 0)
    density_proxy = jnp.mean(probs, 0)
    aux = E * jnp.sum(density * density_proxy)
    # position of each token within its expert; drop beyond capacity
    pos = (jnp.cumsum(onehot, 0) - 1.0) * onehot         # [S, E]
    keep = (pos < C) & (onehot > 0)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                            dtype=jnp.float32) * keep[..., None]  # [S,E,C]
    combine = pos_oh * gate[:, None, None]
    dispatch = pos_oh
    xd = jnp.einsum("sec,sd->ecd", dispatch,
                    x_s.astype(jnp.float32)).astype(dtype)       # [E,C,D]
    # all_to_all over dp: rows of E -> owning rank; gather my experts' tokens
    with jax.named_scope("collective:ep_all_to_all"):
        xd = lax.all_to_all(xd, "dp", split_axis=0, concat_axis=0,
                            tiled=True)
    xd = xd.reshape(dp_size, El, C, D).transpose(1, 0, 2, 3)
    xd = xd.reshape(El, dp_size * C, D)                   # [El, dp*C, D]
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", xd, w1e.astype(dtype)))
    o = jnp.einsum("ecf,efd->ecd", h, w2e.astype(dtype))  # [El, dp*C, D]
    o = o.reshape(El, dp_size, C, D).transpose(1, 0, 2, 3).reshape(E, C, D)
    with jax.named_scope("collective:ep_all_to_all"):
        o = lax.all_to_all(o, "dp", split_axis=0, concat_axis=0,
                           tiled=True)
    out = jnp.einsum("sec,ecd->sd", combine,
                     o.astype(jnp.float32)).astype(dtype)
    return out, aux


def build_train_step(mesh: Mesh, cfg: HybridConfig):
    """Returns step(params, opt_state, tokens, labels) -> (params, opt_state,
    loss). tokens/labels: [B, T] int32, B divisible by dp*n_microbatches."""
    Dp, Pp, Tp = mesh.shape["dp"], mesh.shape["pp"], mesh.shape["tp"]
    dtype = cfg.compute_dtype
    n_local_heads = cfg.n_heads // Tp
    Ts = cfg.seq_len // Tp                 # sequence shard (sp)
    M = cfg.n_microbatches
    Lp = cfg.n_layers // Pp
    specs = _specs(mesh, cfg)

    # Per-collective timing scopes: jax.named_scope threads the label into
    # the XLA HLO metadata, so device traces (jax.profiler.start_trace ->
    # perfetto) attribute ICI time to the individual collective — the
    # observability plane's answer to "which collective is the bottleneck"
    def grad_reduce(g, spec):
        axes = grad_reduce_axes(mesh.axis_names, spec)
        if not axes:
            return g
        with jax.named_scope("collective:grad_psum"):
            return lax.psum(g, axes)

    # ---- per-device code -------------------------------------------------
    def embed_micro(p, ids):                  # ids [mb, T] -> [mb, Ts, D]
        tp_r = lax.axis_index("tp")
        Vl = p["embed"].shape[0]
        off = tp_r * Vl
        idx = ids - off
        valid = (idx >= 0) & (idx < Vl)
        part = jnp.take(p["embed"], jnp.clip(idx, 0, Vl - 1), axis=0)
        part = jnp.where(valid[..., None], part, 0.0)
        part = part + p["pos"][None, :, :] / Tp   # pos added once after psum
        with jax.named_scope("collective:vocab_psum_scatter"):
            emb = lax.psum_scatter(part, "tp", scatter_dimension=1,
                                   tiled=True)
        return emb.astype(dtype)               # [mb, Ts, D]

    def block(x_s, lp):                        # one dense block, sp resident
        h = _ln(x_s.astype(jnp.float32), lp["ln1"][0]).astype(dtype)
        with jax.named_scope("collective:sp_all_gather"):
            h_full = lax.all_gather(h, "tp", axis=1, tiled=True)  # sp gather
        a = _attention(h_full, lp["wqkv"], lp["wo"], dtype)
        with jax.named_scope("collective:tp_psum_scatter"):
            a_s = lax.psum_scatter(a.astype(jnp.float32), "tp",
                                   scatter_dimension=1, tiled=True)
        x_s = x_s + a_s.astype(dtype)
        h = _ln(x_s.astype(jnp.float32), lp["ln2"][0]).astype(dtype)
        with jax.named_scope("collective:sp_all_gather"):
            h_full = lax.all_gather(h, "tp", axis=1, tiled=True)
        f = jax.nn.relu(jnp.einsum("btd,df->btf", h_full,
                                   lp["w1"].astype(dtype)))
        f = jnp.einsum("btf,fd->btd", f, lp["w2"].astype(dtype))
        with jax.named_scope("collective:tp_psum_scatter"):
            f_s = lax.psum_scatter(f.astype(jnp.float32), "tp",
                                   scatter_dimension=1, tiled=True)
        return x_s + f_s.astype(dtype)

    def stage(p, x_s):                          # Lp blocks (+ optional MoE)
        block_params = {k: p[k] for k in
                        ("ln1", "wqkv", "wo", "ln2", "w1", "w2")}

        def body(x, lp):
            return block(x, lp), None
        x_s, _ = lax.scan(body, x_s, block_params)
        aux = jnp.zeros((), jnp.float32)
        if cfg.moe_experts:
            mb = x_s.shape[0]
            h = _ln(x_s.astype(jnp.float32), p["moe_ln"][0][0]).astype(dtype)
            flat = h.reshape(-1, cfg.d_model)
            out, aux = _moe_ffn(flat, p["moe_gate"][0], p["moe_w1"][0],
                                p["moe_w2"][0], cfg, Dp, dtype)
            x_s = x_s + out.reshape(mb, Ts, cfg.d_model)
        return x_s, aux

    stage_fn = jax.checkpoint(stage) if cfg.remat else stage

    def vocab_parallel_xent(p, x_s, labels):
        """x_s [N, Ts, D] seq-sharded hidden; labels [N, T]. Megatron
        vocab-parallel cross entropy; returns mean loss over tokens."""
        x = _ln(x_s.astype(jnp.float32), p["ln_f"])
        x_full = lax.all_gather(x, "tp", axis=1, tiled=True)   # [N, T, D]
        logits = jnp.einsum("btd,vd->btv", x_full.astype(dtype),
                            p["embed"].astype(dtype)).astype(jnp.float32)
        # stability shift is gradient-free (pmax has no AD rule, and the
        # shift cancels in lse - label_logit anyway)
        m = lax.pmax(lax.stop_gradient(jnp.max(logits, -1)), "tp")
        se = jnp.sum(jnp.exp(logits - m[..., None]), -1)
        with jax.named_scope("collective:vocab_psum"):
            lse = jnp.log(lax.psum(se, "tp")) + m               # [N, T]
        tp_r = lax.axis_index("tp")
        Vl = logits.shape[-1]
        idx = labels - tp_r * Vl
        valid = (idx >= 0) & (idx < Vl)
        picked = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, Vl - 1)[..., None], axis=-1)[..., 0]
        label_logit = lax.psum(jnp.where(valid, picked, 0.0), "tp")
        return jnp.mean(lse - label_logit)

    def forward_loss(params, tokens, labels):
        """Per-device loss: full pipeline over M microbatches."""
        pp_r = lax.axis_index("pp")
        B_loc = tokens.shape[0]
        mb = B_loc // M
        tok_m = tokens.reshape(M, mb, cfg.seq_len)
        state0 = jnp.zeros((mb, Ts, cfg.d_model), dtype)
        outs0 = jnp.zeros((M, mb, Ts, cfg.d_model), dtype)

        def tick(carry, t):
            state, outs, aux_acc = carry
            in_idx = jnp.clip(t, 0, M - 1)
            x0 = embed_micro(params, tok_m[in_idx])
            inp = jnp.where(pp_r == 0, x0, state)
            out, aux = stage_fn(params, inp)
            # mask bubble ticks: stage s computes valid data for s<=t<s+M
            valid = (t >= pp_r) & (t < pp_r + M)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            o_idx = t - (Pp - 1)
            write = (pp_r == Pp - 1) & (o_idx >= 0)
            slot = jnp.clip(o_idx, 0, M - 1)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, out, outs[slot]), slot, 0)
            with jax.named_scope("collective:pp_ppermute"):
                nxt = lax.ppermute(out, "pp",
                                   [(i, (i + 1) % Pp) for i in range(Pp)])
            return (nxt, outs, aux_acc), None

        (state, outs, aux_acc), _ = lax.scan(
            tick, (state0, outs0, jnp.zeros((), jnp.float32)),
            jnp.arange(M + Pp - 1))

        lbl_m = labels.reshape(M, mb, cfg.seq_len)
        xent = vocab_parallel_xent(params, outs.reshape(M * mb, Ts, -1),
                                   lbl_m.reshape(M * mb, cfg.seq_len))
        is_last = (pp_r == Pp - 1).astype(jnp.float32)
        loss_dev = xent * is_last
        loss = lax.psum(loss_dev, "pp")          # replicate across pp
        if cfg.moe_experts:
            # pmean over tp: each tp rank routed its own sequence shard, so
            # average to keep the scalar replicated and the grad coefficient
            # independent of tp size
            aux_all = lax.pmean(lax.psum(aux_acc, "pp"), "tp") / (M * Pp)
            loss = loss + cfg.moe_aux_weight * aux_all
        return lax.pmean(loss, "dp")             # dp average (grad sync)

    def adam_update(p, g, m, v, step):
        m = cfg.adam_b1 * m + (1 - cfg.adam_b1) * g
        v = cfg.adam_b2 * v + (1 - cfg.adam_b2) * g * g
        mh = m / (1 - cfg.adam_b1 ** step)
        vh = v / (1 - cfg.adam_b2 ** step)
        return p - cfg.learning_rate * mh / (jnp.sqrt(vh) + cfg.adam_eps), m, v

    def device_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(forward_loss)(params, tokens, labels)
        grads = {k: grad_reduce(g, specs[k]) for k, g in grads.items()}
        step = opt_state["step"] + 1
        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            new_p[k], new_m[k], new_v[k] = adam_update(
                params[k], grads[k], opt_state["m"][k], opt_state["v"][k],
                step.astype(jnp.float32))
        return new_p, {"m": new_m, "v": new_v, "step": step}, loss

    pspecs = specs
    ospecs = {"m": specs, "v": specs, "step": P()}
    data_spec = P("dp", None)

    sharded = jax_compat.shard_map(
        device_step, mesh=mesh,
        in_specs=(pspecs, ospecs, data_spec, data_spec),
        out_specs=(pspecs, ospecs, P()), check_rep=False)

    jitted = jax.jit(sharded, donate_argnums=(0, 1))

    def step(params, opt_state, tokens, labels):
        # chaos site: the host-side collective-dispatch boundary — a
        # raise here models an ICI/launch failure surfacing before the
        # program runs (inside the jitted computation nothing is
        # injectable; the host boundary is where recovery logic lives)
        from ..resilience import chaos
        chaos.trigger("hybrid.collective_dispatch")
        from ..observability import perfscope
        if not perfscope.enabled():
            return jitted(params, opt_state, tokens, labels)
        # perfscope on: the comm/cost model is built ONCE from the
        # abstract shapes (a jaxpr trace, before donation invalidates
        # the buffers — never an XLA compile), then every step is
        # timed to completion so the roofline verdict and the
        # collective bubble fractions read against real device time
        import time
        model = perfscope.program_model(
            "hybrid.step", jitted, (params, opt_state, tokens, labels))
        t0 = time.perf_counter()
        out = jitted(params, opt_state, tokens, labels)
        jax.block_until_ready(out)
        perfscope.note_step("hybrid.step",
                            device_s=time.perf_counter() - t0,
                            model=model)
        return out

    step.jitted = jitted        # AOT users (lower/compile) reach through
    return step


def make_fake_lm_batch(cfg: HybridConfig, global_batch: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, cfg.vocab_size,
                         (global_batch, cfg.seq_len)).astype("int32")
    labels = np.roll(tokens, -1, axis=1)
    return tokens, labels


# --- single-device reference (for parity tests) ---------------------------

def reference_loss(params_host, cfg: HybridConfig, tokens, labels):
    """Same math, no parallelism, f32 — ground truth for the hybrid step."""
    p = {k: np.asarray(v).astype("float32") for k, v in params_host.items()}
    x = p["embed"][tokens] + p["pos"][None]
    H, hd = cfg.n_heads, cfg.d_model // cfg.n_heads
    for l in range(cfg.n_layers):
        h = _ln(x, p["ln1"][l][0])
        x = x + _attention(jnp.asarray(h), p["wqkv"][l], p["wo"][l],
                           jnp.float32)
        h = _ln(x, p["ln2"][l][0])
        x = x + jax.nn.relu(h @ p["w1"][l]) @ p["w2"][l]
    x = _ln(x, p["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x, p["embed"])
    lse = jax.scipy.special.logsumexp(logits, -1)
    picked = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
    return jnp.mean(lse - picked)
