"""Sharded sparse-embedding tables + sparse-gradient updates.

Capability parity with the reference's pserver distributed lookup table:
  * /root/reference/python/paddle/fluid/transpiler/distribute_transpiler.py
    :1010,1274 — the embedding table split across pservers, trainers
    prefetch rows by id;
  * operators/distributed/parameter_prefetch.cc:1 — split ids -> RPC
    prefetch -> concat;
  * framework/selected_rows.h — sparse {row ids, row values} gradients
    pushed back to the owning server.

TPU-native redesign: the table lives row-sharded in HBM over a mesh axis
(default "model"); everything runs inside ONE jax.shard_map:

  lookup   = masked local gather + psum over the model axis
             (each rank serves the rows it owns — parameter_prefetch's
             capability, with ICI collectives instead of RPC)
  backward = the row cotangents [B, F, D] are all_gathered over the data
             axis and scatter-added into the owning shard ONLY — a
             SelectedRows-sized exchange (B*F rows), never a dense [V, D]
             gradient allreduce.

The Program/Executor path covers the same capability declaratively:
`layers.embedding(param_attr=ParamAttr(sharding=("model", None)))` row-
shards the Parameter and XLA SPMD inserts the collectives (see
models/deepfm.py, tests/test_sharded_embedding.py); this module is the
explicit-collective engine and the sparse-update fast path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import jax_compat


def row_sharded_lookup(local_table, ids, axis_name: str = "model"):
    """Per-device (inside shard_map): gather rows of a row-sharded table.

    local_table: [V/mp, D] this rank's shard; ids: [...] global int ids.
    Returns [..., D] rows, identical on every rank of `axis_name`."""
    Vl = local_table.shape[0]
    r = lax.axis_index(axis_name)
    local_ids = ids - r * Vl
    valid = (local_ids >= 0) & (local_ids < Vl)
    rows = jnp.take(local_table, jnp.clip(local_ids, 0, Vl - 1), axis=0)
    rows = jnp.where(valid[..., None], rows, 0.0)
    return lax.psum(rows, axis_name)


def sparse_scatter_update(local_table, ids, row_grads, lr: float,
                          axis_name: str = "model",
                          data_axis: str = "data"):
    """Per-device SGD on a row-sharded table from sparse row gradients.

    ids: [B_loc, F] this data-rank's ids; row_grads: [B_loc, F, D] the
    cotangents of the looked-up rows.  The (ids, rows) pairs are
    all_gathered over the data axis (SelectedRows-sized traffic) and each
    model rank scatter-adds the rows it owns — no dense [V, D] gradient
    ever exists."""
    ids_all = lax.all_gather(ids, data_axis, axis=0, tiled=True)
    g_all = lax.all_gather(row_grads, data_axis, axis=0, tiled=True)
    Vl = local_table.shape[0]
    r = lax.axis_index(axis_name)
    local_ids = (ids_all - r * Vl).reshape(-1)
    valid = (local_ids >= 0) & (local_ids < Vl)
    g_flat = g_all.reshape(-1, g_all.shape[-1])
    g_flat = jnp.where(valid[:, None], g_flat, 0.0)
    idx = jnp.where(valid, local_ids, 0)
    return local_table.at[idx].add(-lr * g_flat)


# --------------------------------------------------------------------------
# DeepFM-shaped CTR training step (BASELINE config 4) on a (data, model)
# mesh: the end-to-end proof that the capability matches the reference's
# distributed-lookup-table training.
# --------------------------------------------------------------------------

@dataclass
class ShardedCTRConfig:
    vocab_size: int = 1_000_000
    num_field: int = 39
    embed_dim: int = 8
    fc_sizes: Tuple[int, ...] = (64, 64)
    learning_rate: float = 0.1


def init_ctr_params(mesh: Mesh, cfg: ShardedCTRConfig, seed: int = 0):
    """Tables row-sharded over 'model'; MLP weights replicated."""
    rng = np.random.RandomState(seed)
    mp = mesh.shape["model"]
    assert cfg.vocab_size % mp == 0, "vocab must divide the model axis"
    K = cfg.embed_dim
    params = {
        "w1": np.zeros((cfg.vocab_size, 1), "float32"),
        "emb": (rng.randn(cfg.vocab_size, K) * 0.01).astype("float32"),
    }
    sizes = [cfg.num_field * K] + list(cfg.fc_sizes) + [1]
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"fc{i}_w"] = (rng.randn(a, b) / np.sqrt(a)).astype("float32")
        params[f"fc{i}_b"] = np.zeros((b,), "float32")
    specs = param_specs(cfg)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def param_specs(cfg: ShardedCTRConfig) -> Dict[str, P]:
    specs = {"w1": P("model", None), "emb": P("model", None)}
    n_fc = len(cfg.fc_sizes) + 1
    for i in range(n_fc):
        specs[f"fc{i}_w"] = P(None, None)
        specs[f"fc{i}_b"] = P(None)
    return specs


def _ctr_forward(dense, w1_rows, emb_rows, vals, cfg: ShardedCTRConfig):
    """DeepFM math from looked-up rows (models/deepfm.py, as pure jnp)."""
    first = jnp.sum(w1_rows[..., 0] * vals, axis=1, keepdims=True)
    xv = emb_rows * vals[..., None]                      # [B, F, K]
    sum_sq = jnp.square(jnp.sum(xv, axis=1))
    sq_sum = jnp.sum(jnp.square(xv), axis=1)
    second = 0.5 * jnp.sum(sum_sq - sq_sum, axis=1, keepdims=True)
    h = xv.reshape(xv.shape[0], -1)
    n_fc = len(cfg.fc_sizes) + 1
    for i in range(n_fc):
        h = h @ dense[f"fc{i}_w"] + dense[f"fc{i}_b"]
        if i < n_fc - 1:
            h = jax.nn.relu(h)
    return first + second + h                            # logit [B, 1]


def build_ctr_train_step(mesh: Mesh, cfg: ShardedCTRConfig):
    """step(params, ids, vals, label) -> (params, loss).

    ids/vals [B, F] with B divisible by the data axis; label [B, 1].
    Dense params: replicated, psum'd grads (ParallelExecutor capability).
    Tables: row-sharded, looked up with explicit collectives, updated
    sparsely (pserver distributed-lookup-table capability)."""
    dp = mesh.shape["data"]

    def device_step(params, ids, vals, label):
        tables = {"w1": params["w1"], "emb": params["emb"]}
        dense = {k: v for k, v in params.items() if k not in tables}
        w1_rows = row_sharded_lookup(tables["w1"], ids)
        emb_rows = row_sharded_lookup(tables["emb"], ids)

        def loss_fn(dense, w1_rows, emb_rows):
            """This rank's PARTIAL of the global-mean loss.  Differentiate
            the partial, not a psum'd total: inside shard_map the AD
            transpose of psum is another psum, which would scale every
            cotangent by the axis size."""
            logit = _ctr_forward(dense, w1_rows, emb_rows, vals, cfg)
            z = jnp.clip(logit, -30, 30)
            xent = jnp.maximum(z, 0) - z * label + jnp.log1p(
                jnp.exp(-jnp.abs(z)))
            return jnp.sum(xent) / (dp * ids.shape[0])

        loss_part, (g_dense, g_w1, g_emb) = jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2))(dense, w1_rows, emb_rows)
        loss = lax.psum(loss_part, "data")      # reported global loss
        # replicated dense params: allreduce the local-batch grads — the
        # reference's NCCL allreduce at gradient sites
        # (multi_devices_graph_pass.cc:572)
        g_dense = jax.tree.map(lambda g: lax.psum(g, "data"), g_dense)
        lr = cfg.learning_rate
        new = {k: dense[k] - lr * g_dense[k] for k in dense}
        new["w1"] = sparse_scatter_update(tables["w1"], ids, g_w1, lr)
        new["emb"] = sparse_scatter_update(tables["emb"], ids, g_emb, lr)
        return new, loss

    specs = param_specs(cfg)
    data_spec = P("data", None)
    sharded = jax_compat.shard_map(
        device_step, mesh=mesh,
        in_specs=(specs, data_spec, data_spec, data_spec),
        out_specs=(specs, P()), check_rep=False)
    return jax.jit(sharded, donate_argnums=(0,))


def reference_ctr_step(params_host, cfg: ShardedCTRConfig, ids, vals,
                       label):
    """Single-device f32 ground truth (dense grads) for parity tests."""
    params = {k: jnp.asarray(np.asarray(v)) for k, v in params_host.items()}

    def loss_fn(p):
        dense = {k: v for k, v in p.items() if k not in ("w1", "emb")}
        w1_rows = jnp.take(p["w1"], ids, axis=0)
        emb_rows = jnp.take(p["emb"], ids, axis=0)
        logit = _ctr_forward(dense, w1_rows, emb_rows, vals, cfg)
        z = jnp.clip(logit, -30, 30)
        xent = jnp.maximum(z, 0) - z * label + jnp.log1p(
            jnp.exp(-jnp.abs(z)))
        return jnp.mean(jnp.sum(xent, axis=1))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new = {k: params[k] - cfg.learning_rate * grads[k] for k in params}
    return new, loss


def make_fake_ctr_batch(cfg: ShardedCTRConfig, batch: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (batch, cfg.num_field))
    return (ids.astype("int32"),
            rng.rand(batch, cfg.num_field).astype("float32"),
            rng.randint(0, 2, (batch, 1)).astype("float32"))
