"""Switch (top-1) mixture-of-experts FFN — the shared compute used by
the Program-plane `moe_ffn` op and testable standalone.

The 2018 reference has no MoE; this is the TPU-native expert-parallel
capability (scaling-book recipe): tokens pick one expert by gating,
dispatch rides an all_to_all over the expert mesh axis, experts apply
their FFN slice, a second all_to_all combines.  jax.grad differentiates
straight through both collectives, which is what makes the expert-
sharded parameter gradients complete WITHOUT an allreduce (the a2a vjp
routes every rank's cotangents back to the owning expert shard).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def switch_moe(x, gate_w, w1, w2, capacity_factor: float,
               ep_axis: str = None):
    """x [S, D] local tokens; gate_w [D, E] (E = GLOBAL experts,
    replicated); w1 [El, D, F], w2 [El, F, D] (the LOCAL expert slice —
    El == E without expert parallelism).  Returns (out [S, D], aux
    load-balance loss scalar, Switch Transformer eq. 4).

    With ep_axis set (inside shard_map), experts are sharded over the
    axis and the dispatch/combine each ride one all_to_all.
    """
    S, D = x.shape
    E = gate_w.shape[-1]
    El = w1.shape[0]
    if E % El:
        raise ValueError(f"global experts {E} not divisible by local "
                         f"slice {El}")
    ep = E // El
    if ep > 1 and ep_axis is None:
        raise ValueError(
            f"w1 carries {El} of {E} experts but no expert axis is in "
            f"scope — run through ExpertParallelTranspiler + "
            f"Executor(mesh=...)")
    dtype = x.dtype
    C = max(1, int(capacity_factor * S / E))

    logits = jnp.einsum("sd,de->se", x, gate_w.astype(dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    expert = jnp.argmax(probs, -1)                       # [S]
    gate = jnp.max(probs, -1)                            # [S]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    density = jnp.mean(onehot, 0)
    density_proxy = jnp.mean(probs, 0)
    aux = E * jnp.sum(density * density_proxy)
    # position of each token within its expert; drop beyond capacity
    pos = (jnp.cumsum(onehot, 0) - 1.0) * onehot         # [S, E]
    keep = (pos < C) & (onehot > 0)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                            dtype=jnp.float32) * keep[..., None]  # [S,E,C]
    combine = pos_oh * gate[:, None, None]
    xd = jnp.einsum("sec,sd->ecd", pos_oh,
                    x.astype(jnp.float32)).astype(dtype)          # [E,C,D]
    if ep > 1:
        # rows of E -> owning rank; gather my experts' token slabs
        xd = lax.all_to_all(xd, ep_axis, split_axis=0, concat_axis=0,
                            tiled=True)
        xd = xd.reshape(ep, El, C, D).transpose(1, 0, 2, 3)
        xd = xd.reshape(El, ep * C, D)                   # [El, ep*C, D]
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", xd, w1.astype(dtype)))
    o = jnp.einsum("ecf,efd->ecd", h, w2.astype(dtype))
    if ep > 1:
        o = o.reshape(El, ep, C, D).transpose(1, 0, 2, 3).reshape(E, C, D)
        o = lax.all_to_all(o, ep_axis, split_axis=0, concat_axis=0,
                           tiled=True)
    out = jnp.einsum("sec,ecd->sd", combine,
                     o.astype(jnp.float32)).astype(dtype)
    return out, aux
