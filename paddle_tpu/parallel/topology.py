"""Mesh topology for hybrid parallelism.

The reference's parallel plane is data-parallel only (SURVEY.md §2.3:
ParallelExecutor+NCCL, pserver, NCCL2 multi-node — multi_devices_graph_pass.cc,
listen_and_serv_op.cc).  The TPU-native design generalises it to a named
device mesh with axes:

  dp — data parallel (reference: ParallelExecutor replicas / trainers)
  pp — pipeline parallel (no reference equivalent; new capability)
  tp — tensor parallel, also carries Megatron-style sequence parallelism
       for activations (no reference equivalent)
  cp — context parallel (ring attention) for long sequences — replaces the
       reference's LoD/DynamicRNN story for long inputs (SURVEY.md §5)

Axis order is outermost-first; on real slices put tp innermost so its
collectives ride the fastest ICI links.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np


def make_hybrid_mesh(dp: int = 1, pp: int = 1, tp: int = 1,
                     devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * pp * tp
    if len(devices) < need:
        raise ValueError(f"need {need} devices for mesh dp={dp} pp={pp} "
                         f"tp={tp}, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(dp, pp, tp)
    return jax.sharding.Mesh(arr, ("dp", "pp", "tp"))


def make_context_mesh(dp: int = 1, cp: int = 1,
                      devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * cp
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(dp, cp)
    return jax.sharding.Mesh(arr, ("dp", "cp"))


def grad_reduce_axes(mesh_axes, spec):
    """Mesh axes a gradient must be psum'ed over for a param with this
    PartitionSpec: every axis the param is *replicated* on (i.e. not named
    in the spec).  Shared by the manual-collective training steps."""
    named = {a for part in spec if part
             for a in (part if isinstance(part, tuple) else (part,))}
    return tuple(set(mesh_axes) - named)


def auto_factor(n: int) -> Tuple[int, int, int]:
    """Pick (dp, pp, tp) for n devices: prefer real (>=2) pp and tp when n
    allows, remaining into dp."""
    pp = 2 if n % 2 == 0 and n >= 4 else 1
    tp = 2 if (n // pp) % 2 == 0 and n >= 2 else 1
    dp = n // (pp * tp)
    return dp, pp, tp
