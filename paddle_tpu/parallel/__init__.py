from .parallel_executor import (ParallelExecutor, ExecutionStrategy,
                                BuildStrategy)  # noqa: F401
from .env import init_distributed_env, get_world_info  # noqa: F401
