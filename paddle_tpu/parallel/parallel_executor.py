"""ParallelExecutor: SPMD data-parallel training over a device mesh.

Capability parity with /root/reference/paddle/fluid/framework/
parallel_executor.cc (ctor :191) + python/paddle/fluid/parallel_executor.py:
the user-facing contract (same feed dict, loss averaged across replicas,
param broadcast at start) is preserved, while the machinery is replaced:

  reference                                   here
  ---------                                   ----
  per-place local scopes (:214)               one sharded jit invocation
  NCCLContextMap (:231)                       jax.sharding.Mesh over ICI
  MultiDevSSAGraphBuilder + op handles        XLA SPMD partitioner
  InsertAllReduceOp (:572) / kReduce (:697)   automatic grad psum from
                                              sharding propagation
  ScaleLossGradOp 1/N (:663)                  mean over global batch
  BCastParamsToDevices (:306)                 replicated param sharding
  scope-buffered executor + eager deletion    buffer donation

Multi-node ("NCCL2 mode", num_trainers/trainer_id) maps to
jax.distributed.initialize + a mesh spanning all hosts' devices
(parallel/env.py) — the gen_nccl_id RPC handshake
(operators/distributed_ops/gen_nccl_id_op.cc:31) is replaced by the JAX
coordinator rendezvous.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import jax
import numpy as np

from ..core.place import Place, default_place, data_parallel_mesh
from ..core.profiler import RecordEvent
from ..framework.executor import Executor, Scope, global_scope
from ..framework.program import Program, default_main_program
from ..observability import metrics as obs_metrics

# --- telemetry: the data-parallel plane -----------------------------------
_m_runs = obs_metrics.counter(
    "parallel_executor_runs_total", "ParallelExecutor.run invocations.")
_m_run_seconds = obs_metrics.histogram(
    "parallel_executor_run_seconds",
    "Wall time of one ParallelExecutor.run (global batch across the "
    "mesh, fetch conversion included).")
_m_global_examples_per_sec = obs_metrics.gauge(
    "parallel_executor_examples_per_sec",
    "Global-batch throughput of the last ParallelExecutor.run "
    "(leading dim of the first feed / wall time).")
_m_host_seconds = obs_metrics.histogram(
    "parallel_executor_host_seconds",
    "Host-side dispatch time of one ParallelExecutor.run (excludes "
    "device completion; first run per compiled key includes compile).")
_m_device_seconds = obs_metrics.histogram(
    "parallel_executor_device_seconds",
    "Device time of one ParallelExecutor.run: block-until-ready plus "
    "the device->host copy of the fetches (return_numpy runs only).")


class ExecutionStrategy:
    """ref details/execution_strategy.h — knobs that still mean something
    on TPU are kept; thread counts are XLA's business."""

    def __init__(self):
        self.num_threads = 0            # ignored: XLA schedules
        self.use_experimental_executor = False
        self.num_iteration_per_drop_scope = 1   # ignored: donation covers it
        self.allow_op_delay = False


class BuildStrategy:
    """ref details/build_strategy.h:55.  ReduceStrategy kept for API
    parity: AllReduce == replicated params (grad psum); Reduce == sharded
    optimizer states ≈ ZeRO-1, expressed as param sharding over the mesh."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = (
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice)
        self.memory_optimize = True     # XLA does this
        self.enable_sequential_execution = False
        self.fuse_elewise_add_act_ops = True  # XLA does this
        self.debug_graphviz_path = ""


class ParallelExecutor:
    """fluid.ParallelExecutor equivalent.

    pexe = ParallelExecutor(use_tpu=True, loss_name=loss.name)
    loss, = pexe.run(fetch_list=[loss.name], feed={...})

    The feed carries the GLOBAL batch; it is sharded across the mesh's
    batch axis (the reference's feed-split across places,
    python/paddle/fluid/parallel_executor.py feed handling).
    """

    def __init__(self, use_cuda: bool = False, use_tpu: Optional[bool] = None,
                 loss_name: Optional[str] = None,
                 main_program: Optional[Program] = None,
                 share_vars_from: Optional["ParallelExecutor"] = None,
                 exec_strategy: Optional[ExecutionStrategy] = None,
                 build_strategy: Optional[BuildStrategy] = None,
                 num_trainers: int = 1, trainer_id: int = 0,
                 scope: Optional[Scope] = None,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 place: Optional[Place] = None):
        self.program = main_program or default_main_program()
        self.loss_name = loss_name
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        scope = scope or (share_vars_from._exe.scope if share_vars_from
                          else global_scope())
        self._exe = Executor(place or default_place(), scope=scope,
                             mesh=self.mesh)

    @property
    def device_count(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    def run(self, fetch_list: Sequence, feed=None, feed_dict=None,
            return_numpy: bool = True):
        feed = feed if feed is not None else (feed_dict or {})
        t0 = time.perf_counter()
        with RecordEvent("parallel_executor.run"):
            # host/device anatomy (the trainer's step split, data-
            # parallel face): dispatch without blocking, then account
            # the block-until-ready + D2H copy as device time
            out = self._exe.run(self.program, feed=feed,
                                fetch_list=list(fetch_list),
                                return_numpy=False)
            host_s = time.perf_counter() - t0
            td = time.perf_counter()
            if return_numpy and out:
                jax.block_until_ready(out)
                out = [self._exe.fetch_numpy(v) for v in out]
            device_s = time.perf_counter() - td
        dt = time.perf_counter() - t0
        _m_runs.inc()
        _m_run_seconds.observe(dt)
        _m_host_seconds.observe(host_s)
        if return_numpy:
            _m_device_seconds.observe(device_s)
        if feed and dt > 0:
            # read the batch dim without np.asarray: that would force a
            # device->host copy of the feed on the hot path
            first = next(iter(feed.values()))
            shape = getattr(first, "shape", None)
            if shape is None:
                shape = (len(first),) if hasattr(first, "__len__") else ()
            if shape:
                _m_global_examples_per_sec.set(shape[0] / dt)
        return out

    def explain(self, fetch_list: Sequence, feed=None) -> dict:
        """Cost/memory report for the pjit program this fetch set
        resolves to (Executor.explain over the shared mesh executor):
        per-program FLOPs / bytes accessed / peak HBM plus the cache
        view — the sharded-program face of observability/costmodel.py."""
        return self._exe.explain(self.program, feed=feed or {},
                                 fetch_list=list(fetch_list))

    def cache_report(self, compute_costs: bool = True) -> dict:
        """Compile-cache explorer for this mesh executor's programs."""
        return self._exe.cache_report(compute_costs)
