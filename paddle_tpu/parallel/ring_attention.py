"""Ring attention: context parallelism for long sequences.

The reference has NO long-context strategy (SURVEY.md §5: its story is LoD
ragged batching + DynamicRNN, /root/reference/python/paddle/fluid/layers/
control_flow.py:1395) — this module supplies the TPU-native capability:
sequences sharded over a 'cp' mesh axis, with K/V blocks rotated around the
ring via ppermute while each device accumulates its queries' attention in
flash-attention style (running max + running sum), so the full sequence
never materialises on any one chip.  Overlap of the permute with the local
block matmul is XLA's latency-hiding scheduler's job.

Math: blockwise softmax accumulation (Liu et al., Ring Attention, 2023;
same recurrence as FlashAttention).  jax.grad differentiates through the
scan+ppermute, giving the reverse ring automatically.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import jax_compat
from .topology import grad_reduce_axes

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = True):
    """Per-device blockwise attention; call inside shard_map.

    q,k,v: [B, Ts, H, hd] — local sequence chunk (global seq = cp * Ts).
    Returns [B, Ts, H, hd].  Chunk i holds global positions
    [i*Ts, (i+1)*Ts); causal masking is exact across chunks.
    """
    cp = jax_compat.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    B, Ts, H, hd = q.shape
    scale = 1.0 / np.sqrt(hd)
    q_pos = rank * Ts + jnp.arange(Ts)                    # global q positions

    def step(carry, r):
        o, m, l, kc, vc = carry
        # kc/vc originated on rank (rank - r) mod cp
        src = (rank - r) % cp
        k_pos = src * Ts + jnp.arange(Ts)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kc) * scale  # [B,H,Ts,Ts]
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]       # [Ts, Ts]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))       # [B,H,Ts]
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vc)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o_new, m_new, l_new, kc, vc), None

    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    m0 = jnp.full((B, H, Ts), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Ts), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32)),
        jnp.arange(cp))
    l = jnp.maximum(l, 1e-20)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def plain_attention(q, k, v, causal: bool = True):
    """Single-device reference for parity tests; q,k,v [B,T,H,hd]."""
    B, T, H, hd = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


# --------------------------------------------------------------------------
# Context-parallel LM training step (dp × cp)
# --------------------------------------------------------------------------

@dataclass
class ContextParallelConfig:
    vocab_size: int = 32000
    seq_len: int = 2048          # global sequence
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 1024
    compute_dtype: Any = jnp.float32
    learning_rate: float = 1e-3


def _ln(x, eps=1e-5):
    mu = jnp.mean(x, -1, keepdims=True)
    return (x - mu) * lax.rsqrt(jnp.var(x, -1, keepdims=True) + eps)


def cp_specs():
    return {
        "embed": P(None, None),
        "pos": P("cp", None),            # position table is seq-sharded too
        "wqkv": P(None, None, None, None),
        "wo": P(None, None, None, None),
        "w1": P(None, None, None),
        "w2": P(None, None, None),
    }


def cp_init_params(mesh: Mesh, cfg: ContextParallelConfig, seed: int = 0):
    rng = np.random.RandomState(seed)
    D, L = cfg.d_model, cfg.n_layers
    hd = D // cfg.n_heads

    def g(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype("float32")

    params = {
        "embed": g(cfg.vocab_size, D),
        "pos": g(cfg.seq_len, D),
        "wqkv": g(L, D, cfg.n_heads, 3 * hd, scale=1 / np.sqrt(D)),
        "wo": g(L, cfg.n_heads, hd, D, scale=1 / np.sqrt(D)),
        "w1": g(L, D, cfg.d_ff, scale=1 / np.sqrt(D)),
        "w2": g(L, cfg.d_ff, D, scale=1 / np.sqrt(cfg.d_ff)),
    }
    specs = cp_specs()
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def cp_build_train_step(mesh: Mesh, cfg: ContextParallelConfig):
    """SGD step over tokens [B, T_global] with sequence sharded on 'cp'.
    Every activation is [B, Ts, ...]; attention is the ring."""
    specs = cp_specs()
    dtype = cfg.compute_dtype

    def grad_reduce(g, spec):
        axes = grad_reduce_axes(mesh.axis_names, spec)
        return lax.psum(g, axes) if axes else g

    def forward_loss(p, tokens, labels):
        x = jnp.take(p["embed"], tokens, axis=0) + p["pos"][None]
        x = x.astype(dtype)

        def layer(x, lp):
            h = _ln(x.astype(jnp.float32)).astype(dtype)
            qkv = jnp.einsum("btd,dhe->bthe", h, lp["wqkv"].astype(dtype))
            q, k, v = jnp.split(qkv, 3, axis=-1)
            a = ring_attention(q, k, v, "cp", causal=True)
            x = x + jnp.einsum("bqhd,hdf->bqf", a, lp["wo"].astype(dtype))
            h = _ln(x.astype(jnp.float32)).astype(dtype)
            f = jax.nn.relu(jnp.einsum("btd,df->btf", h,
                                       lp["w1"].astype(dtype)))
            x = x + jnp.einsum("btf,fd->btd", f, lp["w2"].astype(dtype))
            return x, None

        lp = {k: p[k] for k in ("wqkv", "wo", "w1", "w2")}
        x, _ = lax.scan(layer, x, lp)
        x = _ln(x.astype(jnp.float32))
        logits = jnp.einsum("btd,vd->btv", x, p["embed"])
        lse = jax.scipy.special.logsumexp(logits, -1)
        picked = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
        # token mean over the local chunk, then over cp and dp
        return lax.pmean(lax.pmean(jnp.mean(lse - picked), "cp"), "dp")

    def device_step(p, tokens, labels):
        loss, grads = jax.value_and_grad(forward_loss)(p, tokens, labels)
        grads = {k: grad_reduce(g, specs[k]) for k, g in grads.items()}
        new_p = {k: p[k] - cfg.learning_rate * grads[k] for k in p}
        return new_p, loss

    data_spec = P("dp", "cp")
    sharded = jax_compat.shard_map(
        device_step, mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(specs, P()), check_rep=False)
    return jax.jit(sharded, donate_argnums=(0,))
