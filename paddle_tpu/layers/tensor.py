"""Tensor-building layer functions (ref python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..framework.layer_helper import LayerHelper
from ..framework.program import Variable


def create_tensor(dtype="float32", name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.block.create_var(name=helper.name(), dtype=dtype,
                                   persistable=persistable)


def fill_constant(shape, dtype, value, name=None, out=None):
    helper = LayerHelper("fill_constant", name=name)
    out = out or helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant", {}, {"Out": [out]},
                     {"shape": list(shape), "dtype": str(dtype),
                      "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("fill_constant_batch_size_like", {"Input": [input]},
                     {"Out": [out]},
                     {"shape": list(shape), "dtype": str(dtype),
                      "value": float(value), "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def cast(x: Variable, dtype) -> Variable:
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", {"X": [x]}, {"Out": [out]},
                     {"out_dtype": str(dtype)})
    return out


def concat(input: Sequence[Variable], axis=0, name=None) -> Variable:
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", {"X": list(input)}, {"Out": [out]},
                     {"axis": axis})
    return out


def sums(input: Sequence[Variable], out=None) -> Variable:
    helper = LayerHelper("sum")
    out = out or helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", {"X": list(input)}, {"Out": [out]}, {})
    return out


def assign(input, output: Optional[Variable] = None) -> Variable:
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        output = output or helper.create_variable_for_type_inference(
            input.dtype)
        helper.append_op("assign", {"X": [input]}, {"Out": [output]}, {})
    else:
        arr = np.asarray(input)
        output = output or helper.create_variable_for_type_inference(
            str(arr.dtype))
        helper.append_op("assign_value", {}, {"Out": [output]},
                         {"shape": list(arr.shape), "dtype": str(arr.dtype),
                          "values": arr})
    return output


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("arg_min", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    out.stop_gradient = True
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("arg_max", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    out.stop_gradient = True
    return out


def argsort(x, axis=-1, descending=False):
    helper = LayerHelper("argsort")
    out = helper.create_variable_for_type_inference(x.dtype)
    ids = helper.create_variable_for_type_inference("int64")
    helper.append_op("argsort", {"X": [x]},
                     {"Out": [out], "Indices": [ids]},
                     {"axis": axis, "descending": descending})
    return out, ids


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x):
    helper = LayerHelper("fill_any_like")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_any_like", {"X": [x]}, {"Out": [out]},
                     {"value": 1.0})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    out = out or helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", {"X": [x]}, {"Out": [out]}, {})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op("reverse", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    return out


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("linspace", {}, {"Out": [out]},
                     {"start": float(start), "stop": float(stop),
                      "num": int(num), "dtype": str(dtype)})
    return out


def diag(diagonal: np.ndarray):
    return assign(np.diag(np.asarray(diagonal)))


def eye(num_rows, num_columns=None, dtype="float32"):
    helper = LayerHelper("eye")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("eye", {}, {"Out": [out]},
                     {"num_rows": int(num_rows),
                      "num_columns": int(num_columns or num_rows),
                      "dtype": str(dtype)})
    return out
