"""RNN layers (ref python/paddle/fluid/layers/nn.py: dynamic_lstm:443,
dynamic_gru:741, gru_unit:830, and the LSTM/GRU book/benchmark usage
`stacked_dynamic_lstm`)."""
from __future__ import annotations

import numpy as np

from ..framework.layer_helper import LayerHelper, ParamAttr
from ..framework.initializer import XavierInitializer
from ..framework.program import Variable


def dynamic_lstm(input, size, h_0=None, c_0=None, mask=None,
                 param_attr=None, bias_attr=None, use_peepholes=False,
                 is_reverse=False, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 name=None):
    """input: [B, T, 4*H] pre-projected (ref dynamic_lstm contract: the
    x->4H projection is a preceding fc).  size = 4*H.  Returns (hidden
    [B,T,H], cell [B,H] last).  LoD story: pass `mask` [B,T] for padded
    batches."""
    helper = LayerHelper("dynamic_lstm", name=name)
    H = size // 4
    w = helper.create_parameter(param_attr, shape=[H, 4 * H],
                                dtype=input.dtype)
    bias = helper.create_parameter(bias_attr, shape=[4 * H],
                                   dtype=input.dtype, is_bias=True)
    x = helper.append_bias_op(input, bias, dim_start=2)
    inputs = {"Input": [x], "Weight": [w]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if mask is not None:
        inputs["Mask"] = [mask]
    hidden = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    last_c = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("lstm", inputs,
                     {"Hidden": [hidden], "LastH": [last_h],
                      "LastC": [last_c]},
                     {"gate_activation": gate_activation,
                      "cell_activation": cell_activation,
                      "candidate_activation": candidate_activation,
                      "is_reverse": is_reverse})
    return hidden, last_c


def lstm_layer(input, hidden_size, h_0=None, c_0=None, mask=None,
               param_attr=None, bias_attr=None, is_reverse=False,
               name=None):
    """Convenience: x-projection fc + dynamic_lstm (what the reference's
    benchmark stacked_dynamic_lstm composes by hand)."""
    from . import nn
    proj = nn.fc(input, size=4 * hidden_size, num_flatten_dims=2,
                 param_attr=param_attr, bias_attr=False)
    return dynamic_lstm(proj, 4 * hidden_size, h_0=h_0, c_0=c_0, mask=mask,
                        bias_attr=bias_attr, is_reverse=is_reverse,
                        name=name)


def dynamic_gru(input, size, h_0=None, mask=None, param_attr=None,
                bias_attr=None, is_reverse=False,
                gate_activation="sigmoid", candidate_activation="tanh",
                name=None):
    """input: [B, T, 3*H] pre-projected; size = H (ref dynamic_gru:741).
    Returns hidden [B, T, H]."""
    helper = LayerHelper("dynamic_gru", name=name)
    H = size
    w = helper.create_parameter(param_attr, shape=[H, 3 * H],
                                dtype=input.dtype)
    bias = helper.create_parameter(bias_attr, shape=[3 * H],
                                   dtype=input.dtype, is_bias=True)
    x = helper.append_bias_op(input, bias, dim_start=2)
    inputs = {"Input": [x], "Weight": [w]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if mask is not None:
        inputs["Mask"] = [mask]
    hidden = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gru", inputs,
                     {"Hidden": [hidden], "LastH": [last_h]},
                     {"gate_activation": gate_activation,
                      "activation": candidate_activation,
                      "is_reverse": is_reverse})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """One GRU step (ref gru_unit:830): input [B, 3*H] pre-projected,
    hidden [B, H].  Returns (new_hidden, gate, reset_hidden_prev)."""
    helper = LayerHelper("gru_unit", name=name)
    H = size // 3
    w = helper.create_parameter(param_attr, shape=[H, 3 * H],
                                dtype=input.dtype)
    bias = helper.create_parameter(bias_attr, shape=[3 * H],
                                   dtype=input.dtype, is_bias=True)
    x = helper.append_bias_op(input, bias, dim_start=1)
    out = helper.create_variable_for_type_inference(input.dtype)
    gate = helper.create_variable_for_type_inference(input.dtype)
    reset = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gru_unit",
                     {"Input": [x], "HiddenPrev": [hidden], "Weight": [w]},
                     {"Hidden": [out], "Gate": [gate],
                      "ResetHiddenPrev": [reset]},
                     {"activation": activation,
                      "gate_activation": gate_activation})
    return out, gate, reset


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step (ref layers/nn.py lstm_unit): concat(x,h) -> fc 4H ->
    lstm_unit op.  Returns (hidden, cell)."""
    from . import nn, tensor
    helper = LayerHelper("lstm_unit", name=name)
    H = int(cell_t_prev.shape[-1])
    cat = tensor.concat([x_t, hidden_t_prev], axis=1)
    fc_out = nn.fc(cat, size=4 * H, param_attr=param_attr,
                   bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op("lstm_unit",
                     {"X": [fc_out], "C_prev": [cell_t_prev]},
                     {"C": [c], "H": [h]}, {"forget_bias": forget_bias})
    return h, c
