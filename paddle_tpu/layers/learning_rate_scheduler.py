"""In-graph learning-rate schedules.

Capability parity with /root/reference/python/paddle/fluid/layers/
learning_rate_scheduler.py (noam_decay, exponential_decay, natural_exp_
decay, inverse_time_decay, polynomial_decay, piecewise_decay, cosine_decay
+ linear_lr_warmup in the era's usage): a persistable global-step counter
increments once per program run and the decayed lr is computed in-graph, so
the schedule serializes with the program and resumes from checkpoints
(the counter is persistable state like any optimizer accumulator).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..framework.layer_helper import LayerHelper
from ..framework.program import (Variable, default_main_program,
                                 default_startup_program)
from ..framework.registry import register_op, single_input
from ..framework import unique_name

GLOBAL_STEP_VAR = "@lr_global_step@"


@register_op("lr_schedule")
def _lr_schedule(ctx, ins, attrs):
    step = single_input(ins, "Step").astype(jnp.float32).reshape(())
    kind = attrs["kind"]
    if kind == "noam":
        d, warmup = float(attrs["d_model"]), float(attrs["warmup_steps"])
        lr = d ** -0.5 * jnp.minimum(jnp.maximum(step, 1.0) ** -0.5,
                                     jnp.maximum(step, 1.0) * warmup ** -1.5)
    elif kind == "exponential":
        base, decay_steps = float(attrs["lr"]), float(attrs["decay_steps"])
        rate, stair = float(attrs["decay_rate"]), bool(attrs["staircase"])
        e = step / decay_steps
        e = jnp.floor(e) if stair else e
        lr = base * rate ** e
    elif kind == "natural_exp":
        base, decay_steps = float(attrs["lr"]), float(attrs["decay_steps"])
        rate, stair = float(attrs["decay_rate"]), bool(attrs["staircase"])
        e = step / decay_steps
        e = jnp.floor(e) if stair else e
        lr = base * jnp.exp(-rate * e)
    elif kind == "inverse_time":
        base, decay_steps = float(attrs["lr"]), float(attrs["decay_steps"])
        rate, stair = float(attrs["decay_rate"]), bool(attrs["staircase"])
        e = step / decay_steps
        e = jnp.floor(e) if stair else e
        lr = base / (1.0 + rate * e)
    elif kind == "polynomial":
        base, decay_steps = float(attrs["lr"]), float(attrs["decay_steps"])
        end, power = float(attrs["end_lr"]), float(attrs["power"])
        if attrs["cycle"]:
            div = jnp.ceil(jnp.maximum(step, 1.0) / decay_steps)
            total = decay_steps * jnp.maximum(div, 1.0)
        else:
            total = decay_steps
        s = jnp.minimum(step, total)
        lr = (base - end) * (1 - s / total) ** power + end
    elif kind == "piecewise":
        boundaries = list(attrs["boundaries"])
        values = list(attrs["values"])
        lr = jnp.asarray(values[0], jnp.float32)
        for b, v in zip(boundaries, values[1:]):
            lr = jnp.where(step >= b, jnp.float32(v), lr)
    elif kind == "cosine":
        base, step_each = float(attrs["lr"]), float(attrs["step_each_epoch"])
        epochs = float(attrs["epochs"])
        cur_epoch = jnp.floor(step / step_each)
        lr = base / 2.0 * (jnp.cos(cur_epoch * math.pi / epochs) + 1.0)
    elif kind == "linear_warmup":
        start, end = float(attrs["start_lr"]), float(attrs["end_lr"])
        warmup = float(attrs["warmup_steps"])
        frac = jnp.clip(step / warmup, 0.0, 1.0)
        warm = start + (end - start) * frac
        after = ins["After"][0].astype(jnp.float32).reshape(()) \
            if ins.get("After") else jnp.float32(end)
        lr = jnp.where(step < warmup, warm, after)
    else:
        raise ValueError(f"unknown lr schedule {kind!r}")
    return {"Out": [lr.reshape(1)]}


def _global_step(helper: LayerHelper) -> Variable:
    """Shared persistable counter, incremented once per scheduler build
    point (one increment per program run)."""
    block = helper.main_program.global_block()
    if block.has_var(GLOBAL_STEP_VAR):
        return block.var(GLOBAL_STEP_VAR)
    step = block.create_var(name=GLOBAL_STEP_VAR, shape=[1],
                            dtype="int64", persistable=True,
                            stop_gradient=True)
    sb = helper.startup_program.global_block()
    if not sb.has_var(GLOBAL_STEP_VAR):
        sb.create_var(GLOBAL_STEP_VAR, shape=[1], dtype="int64",
                      persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [GLOBAL_STEP_VAR]},
                     attrs={"shape": [1], "dtype": "int64", "value": 0})
    block.append_op("increment_loop_counter", {"X": [GLOBAL_STEP_VAR]},
                    {"Out": [GLOBAL_STEP_VAR]}, {"step": 1})
    return step


def _schedule(kind: str, inputs=None, **attrs) -> Variable:
    helper = LayerHelper("lr_schedule")
    step = _global_step(helper)
    out = helper.block.create_var(
        name=unique_name.generate(f"lr_{kind}"), shape=[1],
        dtype="float32", stop_gradient=True)
    ins = {"Step": [GLOBAL_STEP_VAR]}
    for k, v in (inputs or {}).items():
        ins[k] = [v.name if isinstance(v, Variable) else v]
    helper.main_program.global_block().append_op(
        "lr_schedule", ins, {"Out": [out.name]}, {"kind": kind, **attrs})
    return out


def noam_decay(d_model, warmup_steps):
    return _schedule("noam", d_model=d_model, warmup_steps=warmup_steps)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    return _schedule("exponential", lr=learning_rate,
                     decay_steps=decay_steps, decay_rate=decay_rate,
                     staircase=staircase)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    return _schedule("natural_exp", lr=learning_rate,
                     decay_steps=decay_steps, decay_rate=decay_rate,
                     staircase=staircase)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    return _schedule("inverse_time", lr=learning_rate,
                     decay_steps=decay_steps, decay_rate=decay_rate,
                     staircase=staircase)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    return _schedule("polynomial", lr=learning_rate,
                     decay_steps=decay_steps, end_lr=end_learning_rate,
                     power=power, cycle=cycle)


def piecewise_decay(boundaries, values):
    assert len(values) == len(boundaries) + 1
    return _schedule("piecewise", boundaries=list(boundaries),
                     values=[float(v) for v in values])


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return _schedule("cosine", lr=learning_rate,
                     step_each_epoch=step_each_epoch, epochs=epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """learning_rate may be a float or a schedule Variable to switch to
    after warmup."""
    inputs = {}
    attrs = dict(warmup_steps=warmup_steps, start_lr=start_lr,
                 end_lr=end_lr)
    if isinstance(learning_rate, Variable):
        inputs["After"] = learning_rate
    else:
        attrs["end_lr"] = float(learning_rate)
    return _schedule("linear_warmup", inputs=inputs, **attrs)
