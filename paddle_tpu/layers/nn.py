"""NN layer functions — the user-facing model-building API.

Capability parity with /root/reference/python/paddle/fluid/layers/nn.py
(157 layer fns; fc:186, embedding:295, conv2d:1736, batch_norm:2705, ...).
Each function creates params via LayerHelper (initializers go to the startup
program) and appends ops to the main program.
"""
from __future__ import annotations

import copy
from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.enforce import check_arg
from ..framework.layer_helper import LayerHelper, ParamAttr
from ..framework.initializer import ConstantInitializer, NormalInitializer
from ..framework.program import Variable, default_main_program


def data(name: str, shape: Sequence[int], dtype="float32",
         append_batch_size: bool = True, lod_level: int = 0) -> Variable:
    """Input placeholder (ref layers/io.py data)."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = helper.block.create_var(name=name, shape=shape, dtype=dtype,
                                  is_data=True, stop_gradient=True,
                                  lod_level=lod_level)
    return var


def fc(input: Union[Variable, List[Variable]], size: int, num_flatten_dims=1,
       param_attr=None, bias_attr=None, act=None, name=None) -> Variable:
    """Fully-connected (ref layers/nn.py:186): out = act(sum_i(X_i W_i) + b)."""
    helper = LayerHelper("fc", name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for i, x in enumerate(inputs):
        in_features = int(np.prod([d for d in x.shape[num_flatten_dims:]]))
        w = helper.create_parameter(
            param_attr if not isinstance(param_attr, (list, tuple))
            else param_attr[i],
            shape=[in_features, size], dtype=x.dtype)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op("mul", {"X": [x], "Y": [w]}, {"Out": [out]},
                         {"x_num_col_dims": num_flatten_dims,
                          "y_num_col_dims": 1})
        mul_results.append(out)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op("sum", {"X": mul_results}, {"Out": [pre_bias]}, {})
    bias = helper.create_parameter(bias_attr, shape=[size],
                                   dtype=pre_bias.dtype, is_bias=True)
    pre_act = helper.append_bias_op(pre_bias, bias,
                                    dim_start=num_flatten_dims)
    return helper.append_activation(pre_act, act)


def embedding(input: Variable, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None) -> Variable:
    """ref layers/nn.py:295.

    is_sparse: the reference flips the gradient to SelectedRows for
    pserver traffic (lookup_table_op.cc remote_prefetch); here the
    gradient is an XLA scatter-add into the (donated) table buffer, and
    the distributed capability is carried by the table's sharding — pass
    ``param_attr=ParamAttr(sharding=("model", None))`` to row-shard it
    over the mesh (XLA SPMD inserts the collectives), or use
    parallel/sharded_embedding.py for the explicit-collective shard_map
    path with sparse row updates.  The flag is recorded on the op for
    program-transpiler parity."""
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(
        param_attr, shape=list(size), dtype=dtype,
        default_initializer=NormalInitializer(0.0, 1.0 / np.sqrt(size[1])))
    out = helper.create_variable_for_type_inference(dtype)
    if padding_idx is None:
        pad_attr = -1  # kNoPadding sentinel (ref lookup_table_op.h)
    else:
        # ref layers/nn.py embedding: negative idx counts from vocab end
        pad_attr = int(padding_idx) if padding_idx >= 0 else (
            int(size[0]) + int(padding_idx))
    helper.append_op("lookup_table", {"W": [w], "Ids": [input]},
                     {"Out": [out]}, {"padding_idx": pad_attr,
                                      "is_sparse": bool(is_sparse)})
    return out


def sparse_embedding(input: Variable, size, hash_bucket=True,
                     param_attr=None, dtype="float32",
                     name=None) -> Variable:
    """Sparse-plane table lookup (paddle_tpu/sparse; ref
    lookup_sparse_table_op.cc): like :func:`embedding` but raw ids of
    ANY magnitude fold into the ``size[0]`` buckets with the sparse
    plane's avalanche hash (``hash_bucket=True``, the CTR default) —
    the table is sized by budget, not by the id space.  The gradient is
    inherently SelectedRows-shaped: XLA scatter-adds into only the
    looked-up rows."""
    helper = LayerHelper("sparse_embedding", name=name)
    w = helper.create_parameter(
        param_attr, shape=list(size), dtype=dtype,
        default_initializer=NormalInitializer(0.0, 1.0 / np.sqrt(size[1])))
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("sparse_embedding_lookup",
                     {"W": [w], "Ids": [input]}, {"Out": [out]},
                     {"hash_bucket": bool(hash_bucket)})
    return out


def conv2d(input: Variable, num_filters: int, filter_size, stride=1,
           padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None) -> Variable:
    """ref layers/nn.py:1736 (NCHW, OIHW weights)."""
    helper = LayerHelper("conv2d", name=name)
    c_in = int(input.shape[1])
    fs = filter_size if isinstance(filter_size, (list, tuple)) else (
        filter_size, filter_size)
    w_shape = [num_filters, c_in // groups, fs[0], fs[1]]
    fan_in = (c_in // groups) * fs[0] * fs[1]
    w = helper.create_parameter(
        param_attr, shape=w_shape, dtype=input.dtype,
        default_initializer=NormalInitializer(0.0, np.sqrt(2.0 / fan_in)))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv2d", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": _pair(stride), "paddings": _pair(padding),
                      "dilations": _pair(dilation), "groups": groups})
    bias = helper.create_parameter(bias_attr, shape=[num_filters],
                                   dtype=input.dtype, is_bias=True)
    pre_act = helper.append_bias_op(out, bias, dim_start=1)
    return helper.append_activation(pre_act, act)


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=1, param_attr=None, bias_attr=None,
                     act=None, name=None) -> Variable:
    helper = LayerHelper("conv2d_transpose", name=name)
    c_in = int(input.shape[1])
    fs = filter_size if isinstance(filter_size, (list, tuple)) else (
        filter_size, filter_size)
    w = helper.create_parameter(
        param_attr, shape=[c_in, num_filters // groups, fs[0], fs[1]],
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv2d_transpose", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": _pair(stride), "paddings": _pair(padding),
                      "dilations": _pair(dilation), "groups": groups})
    bias = helper.create_parameter(bias_attr, shape=[num_filters],
                                   dtype=input.dtype, is_bias=True)
    pre_act = helper.append_bias_op(out, bias, dim_start=1)
    return helper.append_activation(pre_act, act)


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [int(v), int(v)]


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, name=None) -> Variable:
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool2d", {"X": [input]}, {"Out": [out]},
                     {"ksize": _pair(pool_size),
                      "pooling_type": "avg" if pool_type == "avg" else "max",
                      "strides": _pair(pool_stride),
                      "paddings": _pair(pool_padding),
                      "global_pooling": global_pooling,
                      "ceil_mode": ceil_mode, "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    h, w = int(input.shape[2]), int(input.shape[3])
    oh, ow = (pool_size if isinstance(pool_size, (list, tuple))
              else (pool_size, pool_size))
    stride = [h // oh, w // ow]
    ksize = [h - (oh - 1) * stride[0], w - (ow - 1) * stride[1]]
    return pool2d(input, ksize, pool_type, stride, 0, name=name)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               moving_mean_name=None, moving_variance_name=None,
               use_global_stats=False, name=None) -> Variable:
    """ref layers/nn.py:2705."""
    helper = LayerHelper("batch_norm", name=name)
    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    scale = helper.create_parameter(
        param_attr, shape=[c], dtype="float32",
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype="float32",
                                   is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False,
                  initializer=ConstantInitializer(0.0)),
        shape=[c], dtype="float32")
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False,
                  initializer=ConstantInitializer(1.0)),
        shape=[c], dtype="float32")
    saved_mean = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "batch_norm",
        {"X": [input], "Scale": [scale], "Bias": [bias],
         "Mean": [mean], "Variance": [variance]},
        {"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
         "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout, "use_global_stats": use_global_stats})
    return helper.append_activation(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None) -> Variable:
    helper = LayerHelper("layer_norm", name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            param_attr, shape=norm_shape, dtype="float32",
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape,
                                    dtype="float32", is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("layer_norm", inputs,
                     {"Y": [out], "Mean": [mean], "Variance": [var]},
                     {"begin_norm_axis": begin_norm_axis,
                      "epsilon": epsilon})
    return helper.append_activation(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None) -> Variable:
    helper = LayerHelper("group_norm", name=name)
    c = int(input.shape[1])
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(
            param_attr, shape=[c], dtype="float32",
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[c], dtype="float32",
                                    is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("group_norm", inputs,
                     {"Y": [out], "Mean": [mean], "Variance": [var]},
                     {"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out, act)


def dropout(x, dropout_prob, is_test=False, seed=None,
            dropout_implementation="downgrade_in_infer", name=None):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", True)
    helper.append_op("dropout", {"X": [x]},
                     {"Out": [out], "Mask": [mask]},
                     {"dropout_prob": dropout_prob, "is_test": is_test,
                      "seed": seed or 0,
                      "dropout_implementation": dropout_implementation})
    return out


def softmax(input, axis=-1, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("softmax", {"X": [input]}, {"Out": [out]},
                     {"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_softmax", {"X": [input]}, {"Out": [out]},
                     {"axis": axis})
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy",
                     {"X": [input], "Label": [label]}, {"Y": [out]},
                     {"soft_label": soft_label,
                      "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    sm = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": [logits], "Label": [label]},
                     {"Loss": [loss], "Softmax": [sm]},
                     {"soft_label": soft_label,
                      "ignore_index": ignore_index})
    return (loss, sm) if return_softmax else loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("square_error_cost",
                     {"X": [input], "Label": [label]}, {"Out": [out]}, {})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", {"X": [x]}, {"Out": [out]}, {})
    return out


def _reduce_layer(op_name):
    def f(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_name, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        attrs = {"keep_dim": keep_dim,
                 "reduce_all": dim is None,
                 "dim": [0] if dim is None else (
                     dim if isinstance(dim, (list, tuple)) else [dim])}
        helper.append_op(op_name, {"X": [input]}, {"Out": [out]}, attrs)
        return out
    f.__name__ = op_name
    return f


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def reshape(x, shape, inplace=False, name=None):
    helper = LayerHelper("reshape", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("reshape", {"X": [x]}, {"Out": [out]},
                     {"shape": list(shape)})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("transpose", {"X": [x]}, {"Out": [out]},
                     {"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=0, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "sections": [], "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op("split", {"X": [input]}, {"Out": outs}, attrs)
    return outs


def stack(x: Sequence[Variable], axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", {"X": list(x)}, {"Y": [out]}, {"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    n = num if num is not None else int(x.shape[axis])
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(n)]
    helper.append_op("unstack", {"X": [x]}, {"Y": outs}, {"axis": axis})
    return outs


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("squeeze", {"X": [input]}, {"Out": [out]},
                     {"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("unsqueeze", {"X": [input]}, {"Out": [out]},
                     {"axes": list(axes)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("flatten", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", {"X": [x]}, {"Out": [out]},
                     {"expand_times": list(expand_times)})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", {"Input": [input]}, {"Out": [out]},
                     {"axes": list(axes), "starts": list(starts),
                      "ends": list(ends)})
    return out


def gather(input, index, axis=0):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", {"X": [input], "Index": [index]},
                     {"Out": [out]}, {"axis": axis})
    return out


def scatter(input, index, updates, overwrite=True):
    helper = LayerHelper("scatter")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter",
                     {"X": [input], "Ids": [index], "Updates": [updates]},
                     {"Out": [out]}, {"overwrite": overwrite})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                      "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", {"X": [x], "Y": [y]}, {"Out": [out]},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    vals = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("top_k", {"X": [input]},
                     {"Out": [vals], "Indices": [ids]}, {"k": int(k)})
    return vals, ids


def accuracy(input, label, k=1, name=None):
    """ref layers/metric_op.py accuracy: topk + accuracy op."""
    vals, ids = topk(input, k)
    helper = LayerHelper("accuracy", name=name)
    acc = helper.create_variable_for_type_inference("float32", True)
    correct = helper.create_variable_for_type_inference("int32", True)
    total = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("accuracy",
                     {"Out": [vals], "Indices": [ids], "Label": [label]},
                     {"Accuracy": [acc], "Correct": [correct],
                      "Total": [total]}, {})
    return acc


def auc(input, label, num_thresholds=4095, name=None):
    """ref layers/metric_op.py auc — streaming AUC with persistable stats."""
    helper = LayerHelper("auc", name=name)
    stat_pos = helper.create_parameter(
        ParamAttr(name=helper.name("stat_pos"), trainable=False,
                  initializer=ConstantInitializer(0.0)),
        shape=[num_thresholds + 1], dtype="float32")
    stat_neg = helper.create_parameter(
        ParamAttr(name=helper.name("stat_neg"), trainable=False,
                  initializer=ConstantInitializer(0.0)),
        shape=[num_thresholds + 1], dtype="float32")
    auc_out = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("auc",
                     {"Predict": [input], "Label": [label],
                      "StatPos": [stat_pos], "StatNeg": [stat_neg]},
                     {"AUC": [auc_out], "StatPosOut": [stat_pos],
                      "StatNegOut": [stat_neg]},
                     {"num_thresholds": num_thresholds})
    return auc_out, [stat_pos, stat_neg]


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", {"X": [input]}, {"Out": [out]},
                     {"depth": int(depth)})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", {"X": [x]}, {"Out": [out]},
                     {"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip_by_norm", {"X": [x]}, {"Out": [out]},
                     {"max_norm": float(max_norm)})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", {"X": [x]}, {"Out": [out]},
                     {"scale": float(scale), "bias": float(bias),
                      "bias_after_scale": bias_after_scale})
    return out


def elementwise_op_layer(op_name):
    def f(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_name, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_name, {"X": [x], "Y": [y]}, {"Out": [out]},
                         {"axis": axis})
        return helper.append_activation(out, act)
    f.__name__ = op_name
    return f


elementwise_add = elementwise_op_layer("elementwise_add")
elementwise_sub = elementwise_op_layer("elementwise_sub")
elementwise_mul = elementwise_op_layer("elementwise_mul")
elementwise_div = elementwise_op_layer("elementwise_div")
elementwise_max = elementwise_op_layer("elementwise_max")
elementwise_min = elementwise_op_layer("elementwise_min")
elementwise_pow = elementwise_op_layer("elementwise_pow")


def _unary_layer(op_name):
    def f(x, name=None, **attrs):
        helper = LayerHelper(op_name, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_name, {"X": [x]}, {"Out": [out]}, attrs)
        return out
    f.__name__ = op_name
    return f


# activations / unary math exposed as layers (ref layers/ops.py is
# auto-generated from OpProtos; here we enumerate)
for _name in ["relu", "sigmoid", "logsigmoid", "tanh", "tanh_shrink", "exp",
              "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin",
              "round", "reciprocal", "log", "square", "softplus",
              "softsign", "elu", "relu6", "stanh", "hard_shrink",
              "softshrink", "hard_sigmoid", "swish", "hard_swish", "mish",
              "thresholded_relu", "erf", "selu", "sign", "gelu",
              "leaky_relu", "brelu", "soft_relu"]:
    globals()[_name] = _unary_layer(_name)


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pow", {"X": [x]}, {"Out": [out]}, {"factor": factor})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [int(x.shape[1])]
    else:
        alpha_shape = [int(np.prod(x.shape[1:]))]
    alpha = helper.create_parameter(
        param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", {"X": [x], "Alpha": [alpha]}, {"Out": [out]},
                     {"mode": mode})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("maxout", {"X": [x]}, {"Out": [out]},
                     {"groups": groups})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("lrn", {"X": [input]},
                     {"Out": [out], "MidOut": [mid]},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pad", {"X": [x]}, {"Out": [out]},
                     {"paddings": list(paddings),
                      "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings, mode="constant", pad_value=0.0, name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", {"X": [input]}, {"Out": [out]},
                     {"paddings": list(paddings), "mode": mode,
                      "pad_value": float(pad_value)})
    return out


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 align_corners=True, name=None):
    helper = LayerHelper("interpolate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    method = "bilinear" if resample.upper() == "BILINEAR" else "nearest"
    attrs = {"interp_method": method, "align_corners": align_corners}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = scale
    helper.append_op("interpolate", {"X": [input]}, {"Out": [out]}, attrs)
    return out


resize_bilinear = image_resize


def resize_nearest(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "NEAREST", name=name)


def sequence_mask(x, maxlen, dtype="int64"):
    helper = LayerHelper("sequence_mask")
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("sequence_mask", {"X": [x]}, {"Y": [out]},
                     {"maxlen": int(maxlen), "out_dtype": str(dtype)})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("norm", {"X": [x]}, {"Out": [out], "Norm": [norm]},
                     {"axis": axis, "epsilon": epsilon})
    return out


def cos_sim(x, y):
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(x.dtype)
    xn = helper.create_variable_for_type_inference(x.dtype, True)
    yn = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("cos_sim", {"X": [x], "Y": [y]},
                     {"Out": [out], "XNorm": [xn], "YNorm": [yn]}, {})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": [x], "Label": [label]}, {"Out": [out]},
                     {"ignore_index": ignore_index, "normalize": normalize})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0):
    helper = LayerHelper("smooth_l1_loss")
    loss = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype, True)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op("smooth_l1_loss", inputs,
                     {"Out": [loss], "Diff": [diff]}, {"sigma": sigma})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("huber_loss", {"X": [input], "Y": [label]},
                     {"Out": [out], "Residual": [residual]},
                     {"delta": float(delta)})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """ref layers/nn.py label_smooth — composed from primitives."""
    smoothed = scale(label, 1.0 - epsilon)
    k = int(label.shape[-1])
    if prior_dist is not None:
        return elementwise_add(smoothed, scale(prior_dist, epsilon))
    return scale(smoothed, 1.0, bias=epsilon / k)


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, input_dim_idx=0,
                                   output_dim_idx=0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("uniform_random_batch_size_like", {"Input": [input]},
                     {"Out": [out]},
                     {"shape": list(shape), "dtype": str(dtype),
                      "min": float(min), "max": float(max), "seed": seed,
                      "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gaussian_random", {}, {"Out": [out]},
                     {"shape": list(shape), "dtype": str(dtype),
                      "mean": mean, "std": std, "seed": seed})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("uniform_random", {}, {"Out": [out]},
                     {"shape": list(shape), "dtype": str(dtype),
                      "min": float(min), "max": float(max), "seed": seed})
    return out


def _binary_compare_layer(op_name, out_dtype="bool"):
    def f(x, y, cond=None, name=None):
        helper = LayerHelper(op_name, name=name)
        out = cond or helper.create_variable_for_type_inference(out_dtype)
        helper.append_op(op_name, {"X": [x], "Y": [y]}, {"Out": [out]}, {})
        return out
    f.__name__ = op_name
    return f


less_than = _binary_compare_layer("less_than")
less_equal = _binary_compare_layer("less_equal")
greater_than = _binary_compare_layer("greater_than")
greater_equal = _binary_compare_layer("greater_equal")
equal = _binary_compare_layer("equal")
not_equal = _binary_compare_layer("not_equal")
logical_and = _binary_compare_layer("logical_and")
logical_or = _binary_compare_layer("logical_or")
logical_xor = _binary_compare_layer("logical_xor")


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", name=name)
    out = out or helper.create_variable_for_type_inference("bool")
    helper.append_op("logical_not", {"X": [x]}, {"Out": [out]}, {})
    return out


def increment(x, value=1.0, in_place=True, name=None):
    """ref layers/tensor increment: x += value (in place by default)."""
    helper = LayerHelper("increment", name=name)
    out = x if in_place else helper.create_variable_for_type_inference(
        x.dtype)
    helper.append_op("increment", {"X": [x]}, {"Out": [out]},
                     {"step": float(value)})
    return out


def fused_multihead_attention(queries, keys, values, n_head, causal=False,
                              param_attr=None, name=None):
    """Projected multi-head attention as ONE fused op (flash kernel on
    TPU).  queries/keys/values: [B, T, D]; returns [B, T, D].  The unfused
    composition lives in nets.scaled_dot_product_attention."""
    helper = LayerHelper("fused_attention", name=name)
    d_model = int(queries.shape[-1])

    def proj_attr(suffix):
        return _suffixed_param_attr(param_attr, suffix)

    projs = []
    for x, sfx in zip((queries, keys, values), ("q", "k", "v")):
        w = helper.create_parameter(proj_attr(sfx),
                                    shape=[d_model, d_model],
                                    dtype=queries.dtype)
        out = helper.create_variable_for_type_inference(queries.dtype)
        helper.append_op("matmul", {"X": [x], "Y": [w]}, {"Out": [out]}, {})
        projs.append(out)
    att = helper.create_variable_for_type_inference(queries.dtype)
    helper.append_op("fused_attention",
                     {"Q": [projs[0]], "K": [projs[1]], "V": [projs[2]]},
                     {"Out": [att]}, {"n_head": n_head, "causal": causal})
    wo = helper.create_parameter(proj_attr("o"), shape=[d_model, d_model],
                                 dtype=queries.dtype)
    out = helper.create_variable_for_type_inference(queries.dtype)
    helper.append_op("matmul", {"X": [att], "Y": [wo]}, {"Out": [out]}, {})
    return out


def _suffixed_param_attr(param_attr, suffix):
    """A shared named param_attr would alias all of a layer's projections
    to one parameter; derive a unique name per projection instead."""
    a = ParamAttr._to_attr(param_attr)
    if a is not None and a.name:
        a = copy.copy(a)
        a.name = f"{a.name}.{suffix}"
    return a


def pipeline_boundary(x, name=None):
    """Mark a pipeline-stage cut for PipelineTranspiler (the 2018
    reference has no pipeline parallelism — SURVEY §2.2; its later
    device_guard annotations play this role).  Identity op in
    un-transpiled programs; with pp_degree = K the program needs K-1
    markers.  `x` may be one Variable or a list/tuple — a PYTREE
    boundary payload (e.g. hidden + a residual branch); every marker in
    a program must carry the same tuple of shapes/dtypes (the ppermute
    ring payload)."""
    helper = LayerHelper("pipeline_boundary", name=name)
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = [helper.create_variable_for_type_inference(v.dtype)
            for v in xs]
    helper.append_op("pipeline_boundary", {"X": xs}, {"Out": outs}, {})
    return outs if isinstance(x, (list, tuple)) else outs[0]


def fused_mha(x, n_head, causal=False, kv=None, size=None, out_size=None,
              param_attr=None, name=None):
    """Projection-fused multi-head attention: ONE op owning Wq/Wk/Wv
    [D, E] and Wo [E, out_size], lowered transpose-free through the
    head-major Pallas flash kernel (ops/attention_ops.py fused_mha).
    x: [B, T, D]; kv: optional [B, Tk, Dk] for cross-attention (causal
    must be False).  E = size or D; returns [B, T, out_size or D]."""
    helper = LayerHelper("fused_mha", name=name)
    D = int(x.shape[-1])
    E = int(size or D)
    check_arg(E % n_head == 0,
              f"fused_mha: model width {E} is not divisible by "
              f"n_head={n_head}")
    d_out = int(out_size or D)
    src = kv if kv is not None else x
    Dk = int(src.shape[-1])

    def attr(sfx):
        return _suffixed_param_attr(param_attr, sfx)

    wq = helper.create_parameter(attr("q"), shape=[D, E], dtype=x.dtype)
    wk = helper.create_parameter(attr("k"), shape=[Dk, E], dtype=x.dtype)
    wv = helper.create_parameter(attr("v"), shape=[Dk, E], dtype=x.dtype)
    wo = helper.create_parameter(attr("o"), shape=[E, d_out],
                                 dtype=x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Wq": [wq], "Wk": [wk], "Wv": [wv], "Wo": [wo]}
    if kv is not None:
        inputs["XKV"] = [kv]
    helper.append_op("fused_mha", inputs, {"Out": [out]},
                     {"n_head": n_head, "causal": causal})
    return out


def moe(input, num_experts, d_hidden, capacity_factor=1.25,
        aux_weight=1e-2, param_attr=None, name=None):
    """Switch (top-1) mixture-of-experts FFN: ONE op owning the gate
    [D, E] and the expert stacks W1 [E, D, F] / W2 [E, F, D]
    (ops/fused_ops.py moe_ffn; TPU-native capability — the 2018
    reference has no MoE).  input: [B, T, D] or [N, D].

    Returns (out, aux_loss): out has input's shape; aux_loss [1] is the
    Switch load-balance loss already scaled by aux_weight — ADD it to
    the training cost.  `ExpertParallelTranspiler` shards the expert
    stacks over a mesh axis and the op dispatches via all_to_all.
    """
    helper = LayerHelper("moe", name=name)
    D = int(input.shape[-1])
    E, F = int(num_experts), int(d_hidden)
    check_arg(E >= 1, f"moe: num_experts must be >= 1, got {E}")

    def attr(sfx):
        return _suffixed_param_attr(param_attr, sfx)

    gate = helper.create_parameter(attr("gate"), shape=[D, E],
                                   dtype=input.dtype)
    w1 = helper.create_parameter(attr("w1"), shape=[E, D, F],
                                 dtype=input.dtype)
    w2 = helper.create_parameter(attr("w2"), shape=[E, F, D],
                                 dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    aux = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "moe_ffn",
        {"X": [input], "Gate": [gate], "W1": [w1], "W2": [w2]},
        {"Out": [out], "AuxLoss": [aux]},
        {"capacity_factor": float(capacity_factor),
         "aux_weight": float(aux_weight)})
    return out, aux


def fused_attention_qkv(q, k, v, n_head, causal=False, name=None):
    """Flash attention on pre-projected q/k/v [B, T, n_head*d] (the
    projections live in the caller, e.g. models.transformer); one fused op
    -> Pallas kernel, O(T) memory.  Note: no attention-prob dropout on
    this path (FlashAttention contract)."""
    helper = LayerHelper("fused_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op("fused_attention", {"Q": [q], "K": [k], "V": [v]},
                     {"Out": [out]}, {"n_head": n_head, "causal": causal})
    return out


def where(condition, x, y):
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("where", {"Condition": [condition], "X": [x],
                               "Y": [y]}, {"Out": [out]}, {})
    return out


def fused_lm_head_loss(x, vocab_size, label, param_attr=None,
                       chunk_size=4096, unroll=False, name=None):
    """Chunked remat LM head + mean softmax-CE in ONE op (owns the
    [D, V] head weight).  Replaces fc -> softmax_with_cross_entropy ->
    mean for big-vocab LMs without materializing [N, V] logits; see
    ops/attention_ops.py fused_lm_head_loss."""
    helper = LayerHelper("fused_lm_head_loss", name=name)
    d = int(x.shape[-1])
    w = helper.create_parameter(param_attr, shape=[d, vocab_size],
                                dtype=x.dtype)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fused_lm_head_loss",
                     {"X": [x], "W": [w], "Label": [label]},
                     {"Loss": [loss]}, {"chunk_size": chunk_size,
                                        "unroll": unroll})
    return loss
