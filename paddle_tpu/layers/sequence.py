"""Sequence-family and remaining layer wrappers.

Closes the breadth gap vs the reference's layers/nn.py (157 fns —
sequence_* family around :1847, linear_chain_crf:868, crf_decoding:934,
nce:4021, hsigmoid:4122, beam_search:2942, warpctc:3292, im2sequence
...): each function is a LayerHelper appending one of the already-
registered ops (see paddle_tpu/ops/) plus any params it owns.

Dense-idiom note: the reference's sequence layers consume LoD tensors;
here the native story is padded [B, T, ...] + mask/length tensors (see
SURVEY.md "Hard parts (a)"), so several wrappers take explicit
mask/length inputs where the reference read LoD.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..framework.layer_helper import LayerHelper, ParamAttr
from ..framework.program import Variable


def _simple(op_type, ins, attrs, dtype, out_slot="Out", name=None,
            extra_outs=()):
    """Append a single op; return its main output (plus extras)."""
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(dtype)
    outputs = {out_slot: [out]}
    extras = []
    for slot, edtype in extra_outs:
        v = helper.create_variable_for_type_inference(edtype, True)
        outputs[slot] = [v]
        extras.append(v)
    helper.append_op(op_type, ins, outputs, attrs)
    return (out, *extras) if extras else out


# --- sequence family ------------------------------------------------------

def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None):
    """Context-window convolution over time (ref layers/nn.py
    sequence_conv): input [B, T, D]."""
    helper = LayerHelper("sequence_conv", name=name)
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr,
                                shape=[filter_size * d, num_filters],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_conv", {"X": [input], "Filter": [w]},
                     {"Out": [out]},
                     {"contextLength": filter_size,
                      "contextStride": filter_stride,
                      "contextStart": -(filter_size // 2)})
    bias = helper.create_parameter(bias_attr, shape=[num_filters],
                                   dtype=input.dtype, is_bias=True)
    out = helper.append_bias_op(out, bias, dim_start=2)
    return helper.append_activation(out, act)


def sequence_context(input, context_length, context_start=None,
                     name=None):
    """Sliding-window concat over time: [B, T, D] ->
    [B, T, context_length*D], zero-padded at the edges (the v2
    context_projection primitive, ref
    trainer_config_helpers/layers.py:738)."""
    attrs = {"context_length": int(context_length)}
    if context_start is not None:
        attrs["context_start"] = int(context_start)
    return _simple("sequence_context", {"X": [input]}, attrs,
                   input.dtype, name=name)


def sequence_pool(input, pool_type, mask=None, is_test=False, name=None):
    """ref layers/nn.py sequence_pool: SUM/AVERAGE/MAX/SQRT/LAST/FIRST
    over the time axis of [B, T, D] (optional [B, T] mask)."""
    ins = {"X": [input]}
    if mask is not None:
        ins["Mask"] = [mask]
    return _simple("sequence_pool", ins, {"pooltype": pool_type.upper()},
                   input.dtype, name=name)


def sequence_first_step(input, mask=None):
    return sequence_pool(input, "FIRST", mask=mask)


def sequence_last_step(input, mask=None):
    return sequence_pool(input, "LAST", mask=mask)


def sequence_softmax(input, mask=None, name=None):
    ins = {"X": [input]}
    if mask is not None:
        ins["Mask"] = [mask]
    return _simple("sequence_softmax", ins, {}, input.dtype, name=name)


def sequence_concat(input: List[Variable], name=None):
    return _simple("sequence_concat", {"X": list(input)}, {},
                   input[0].dtype, name=name)


def sequence_slice(input, offset, length, name=None):
    return _simple("sequence_slice", {"X": [input]},
                   {"offset": int(offset), "length": int(length)},
                   input.dtype, name=name)


def sequence_expand(x, y, ref_level=-1, name=None):
    return _simple("sequence_expand", {"X": [x], "Y": [y]},
                   {"ref_level": ref_level}, x.dtype, name=name)


def sequence_expand_as(x, y, name=None):
    return _simple("sequence_expand_as", {"X": [x], "Y": [y]}, {},
                   x.dtype, name=name)


def sequence_pad(x, pad_value=0.0, maxlen=None, length=None, name=None):
    """Returns (padded, length) like the reference."""
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("sequence_pad", ins,
                     {"Out": [out], "Length": [out_len]},
                     {"padded_length": int(maxlen or -1),
                      "pad_value": pad_value})
    return out, out_len


def sequence_unpad(x, length, name=None):
    return _simple("sequence_unpad", {"X": [x], "Length": [length]}, {},
                   x.dtype, name=name)


def sequence_reshape(input, new_dim, name=None):
    return _simple("sequence_reshape", {"X": [input]},
                   {"new_dim": int(new_dim)}, input.dtype, name=name)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    return _simple("sequence_enumerate", {"X": [input]},
                   {"win_size": int(win_size), "pad_value": int(pad_value)},
                   input.dtype, name=name)


def sequence_scatter(input, index, updates, name=None):
    return _simple("sequence_scatter",
                   {"X": [input], "Ids": [index], "Updates": [updates]},
                   {}, input.dtype, name=name)


def sequence_reverse(x, length=None, name=None):
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    return _simple("sequence_reverse", ins, {}, x.dtype, out_slot="Y",
                   name=name)


def lod_reset(x, y=None, target_lod=None, name=None):
    ins = {"X": [x]}
    if y is not None:
        ins["Y"] = [y]
    return _simple("lod_reset", ins,
                   {"target_lod": list(target_lod or [])}, x.dtype,
                   name=name)


# --- CRF / CTC family -----------------------------------------------------

def linear_chain_crf(input, label, mask=None, param_attr=None, name=None):
    """ref layers/nn.py:868: emission [B,T,N] + label [B,T] ->
    LogLikelihood [B,1]; owns the Transition param [N+2, N]
    (start/stop rows first, as in the reference)."""
    helper = LayerHelper("linear_chain_crf", name=name)
    n_tags = int(input.shape[-1])
    trans = helper.create_parameter(param_attr, shape=[n_tags + 2, n_tags],
                                    dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype, True)
    em_exps = helper.create_variable_for_type_inference(input.dtype, True)
    tr_exps = helper.create_variable_for_type_inference(input.dtype, True)
    ins = {"Emission": [input], "Transition": [trans], "Label": [label]}
    if mask is not None:
        ins["Mask"] = [mask]
    helper.append_op("linear_chain_crf", ins,
                     {"LogLikelihood": [ll], "Alpha": [alpha],
                      "EmissionExps": [em_exps],
                      "TransitionExps": [tr_exps]}, {})
    return ll


def crf_decoding(input, param_attr, label=None, mask=None, name=None):
    """ref layers/nn.py:934: viterbi decode with the Transition param
    created by linear_chain_crf (pass the same ParamAttr/name).  In a
    standalone decode program (the v2 infer pattern) the parameter is
    created here under that name and its trained value arrives via the
    scope.  A name with no matching var in a program that already
    contains linear_chain_crf warns (likely typo -> untrained
    transitions); note the check runs at THIS layer's build time, so
    build the crf cost before the decode to get the protection."""
    helper = LayerHelper("crf_decoding", name=name)
    attr = ParamAttr._to_attr(param_attr)
    block = helper.main_program.global_block()
    if attr.name and block.has_var(attr.name):
        trans = block.var(attr.name)
    else:
        has_crf = any(op.type == "linear_chain_crf"
                      for op in block.ops)
        if attr.name and has_crf:
            # standalone-decode builds legitimately create the param
            # here (trained values arrive via the scope); but when THIS
            # program also trains a linear_chain_crf, a name typo means
            # the decode silently runs an UNTRAINED transition
            import warnings
            warnings.warn(
                f"crf_decoding: no variable named {attr.name!r} in a "
                f"program that contains linear_chain_crf — creating a "
                f"fresh Transition parameter.  Pass the SAME param "
                f"name as the crf layer or the decode uses untrained "
                f"transitions.", stacklevel=3)
        n_tags = int(input.shape[-1])
        trans = helper.create_parameter(
            attr, shape=[n_tags + 2, n_tags], dtype=input.dtype)
    out = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [trans]}
    if label is not None:
        ins["Label"] = [label]
    if mask is not None:
        ins["Mask"] = [mask]
    helper.append_op("crf_decoding", ins, {"ViterbiPath": [out]}, {})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, mask=None, name=None):
    helper = LayerHelper("chunk_eval", name=name)
    outs = {}
    names = ["Precision", "Recall", "F1-Score", "NumInferChunks",
             "NumLabelChunks", "NumCorrectChunks"]
    vars_ = []
    for n in names:
        v = helper.create_variable_for_type_inference("float32", True)
        outs[n] = [v]
        vars_.append(v)
    ins = {"Inference": [input], "Label": [label]}
    if mask is not None:
        ins["Mask"] = [mask]
    helper.append_op("chunk_eval", ins, outs,
                     {"chunk_scheme": chunk_scheme,
                      "num_chunk_types": num_chunk_types,
                      "excluded_chunk_types": excluded_chunk_types or []})
    return tuple(vars_)


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None, name=None):
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    return _simple("warpctc", ins,
                   {"blank": blank, "norm_by_times": norm_by_times},
                   input.dtype, out_slot="Loss", name=name)


def ctc_greedy_decoder(input, blank, name=None):
    """argmax + ctc_align collapse (ref layers/nn.py ctc_greedy_decoder)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    am = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_max", {"X": [input]}, {"Out": [am]},
                     {"axis": -1})
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("ctc_align", {"Input": [am]}, {"Output": [out]},
                     {"blank": blank})
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  name=None):
    helper = LayerHelper("edit_distance", name=name)
    if ignored_tokens:
        erased = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("sequence_erase", {"X": [input]},
                         {"Out": [erased]},
                         {"tokens": list(ignored_tokens)})
        input = erased
        erased_l = helper.create_variable_for_type_inference(label.dtype)
        helper.append_op("sequence_erase", {"X": [label]},
                         {"Out": [erased_l]},
                         {"tokens": list(ignored_tokens)})
        label = erased_l
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("edit_distance",
                     {"Hyps": [input], "Refs": [label]},
                     {"Out": [out], "SequenceNum": [seq_num]},
                     {"normalized": normalized})
    return out, seq_num


# --- sampling-softmax family ---------------------------------------------

def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None):
    """ref layers/nn.py:4021; owns Weight [N, D] and Bias [N]."""
    helper = LayerHelper("nce", name=name)
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr,
                                shape=[num_total_classes, d],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[num_total_classes],
                                dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(input.dtype)
    s_logits = helper.create_variable_for_type_inference(input.dtype, True)
    s_labels = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("nce",
                     {"Input": [input], "Label": [label], "Weight": [w],
                      "Bias": [b]},
                     {"Cost": [cost], "SampleLogits": [s_logits],
                      "SampleLabels": [s_labels]},
                     {"num_total_classes": num_total_classes,
                      "num_neg_samples": num_neg_samples})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """ref layers/nn.py:4122; owns W [num_classes-1, D] and Bias."""
    helper = LayerHelper("hsigmoid", name=name)
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, d],
                                dtype=input.dtype)
    b = helper.create_parameter(bias_attr, shape=[num_classes - 1],
                                dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("hierarchical_sigmoid",
                     {"X": [input], "Label": [label], "W": [w],
                      "Bias": [b]},
                     {"Out": [out], "PreOut": [pre_out]},
                     {"num_classes": num_classes})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):
    return _simple("sampling_id", {"X": [x]},
                   {"min": min, "max": max, "seed": seed, "dtype": dtype},
                   dtype, name=name)


# --- beam search ----------------------------------------------------------

def beam_search(pre_ids, pre_scores, log_probs, beam_size, end_id,
                state=None, name=None):
    """One dense expansion step (ref layers/nn.py:2942 — LoD candidate
    lists become [B, K] tensors; see ops/beam_search_ops.py)."""
    helper = LayerHelper("beam_search", name=name)
    ids = helper.create_variable_for_type_inference("int64")
    scores = helper.create_variable_for_type_inference(log_probs.dtype)
    parents = helper.create_variable_for_type_inference("int64", True)
    ins = {"PreIds": [pre_ids], "PreScores": [pre_scores],
           "LogProbs": [log_probs]}
    outs = {"Ids": [ids], "Scores": [scores], "Parents": [parents]}
    if state is not None:
        ins["State"] = [state]
        st = helper.create_variable_for_type_inference(state.dtype, True)
        outs["StateOut"] = [st]
    helper.append_op("beam_search", ins, outs,
                     {"beam_size": beam_size, "end_id": end_id})
    if state is not None:
        return ids, scores, parents, outs["StateOut"][0]
    return ids, scores, parents


def beam_search_decode(ids, parents, scores, beam_size=None, end_id=1,
                       name=None):
    helper = LayerHelper("beam_search_decode", name=name)
    s_ids = helper.create_variable_for_type_inference("int64")
    s_scores = helper.create_variable_for_type_inference("float32")
    helper.append_op("beam_search_decode",
                     {"Ids": [ids], "Parents": [parents],
                      "Scores": [scores]},
                     {"SentenceIds": [s_ids], "SentenceScores": [s_scores]},
                     {"end_id": end_id})
    return s_ids, s_scores


# --- vision extras --------------------------------------------------------

def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None):
    helper = LayerHelper("conv3d", name=name)
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else (filter_size,) * 3)
    cin = int(input.shape[1])
    w = helper.create_parameter(
        param_attr, shape=[num_filters, cin // groups, *k],
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv3d", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": stride, "paddings": padding,
                      "dilations": dilation, "groups": groups})
    bias = helper.create_parameter(bias_attr, shape=[num_filters],
                                   dtype=input.dtype, is_bias=True)
    out = helper.append_bias_op(out, bias, dim_start=1)
    return helper.append_activation(out, act)


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d_transpose", name=name)
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else (filter_size,) * 3)
    cin = int(input.shape[1])
    w = helper.create_parameter(param_attr,
                                shape=[cin, num_filters, *k],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv3d_transpose",
                     {"Input": [input], "Filter": [w]}, {"Output": [out]},
                     {"strides": stride, "paddings": padding})
    bias = helper.create_parameter(bias_attr, shape=[num_filters],
                                   dtype=input.dtype, is_bias=True)
    out = helper.append_bias_op(out, bias, dim_start=1)
    return helper.append_activation(out, act)


def pool3d(input, pool_size=2, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False, exclusive=True,
           name=None):
    return _simple("pool3d", {"X": [input]},
                   {"ksize": pool_size, "pooling_type": pool_type,
                    "strides": pool_stride or pool_size,
                    "paddings": pool_padding, "exclusive": exclusive,
                    "global_pooling": global_pooling},
                   input.dtype, name=name)


def adaptive_pool3d(input, pool_size, pool_type="max", name=None):
    """Like adaptive_pool2d (layers/nn.py): derive a regular pool3d
    whose output is exactly pool_size bins."""
    d, h, w = (int(input.shape[2]), int(input.shape[3]),
               int(input.shape[4]))
    od, oh, ow = (pool_size if isinstance(pool_size, (list, tuple))
                  else (pool_size,) * 3)
    stride = [d // od, h // oh, w // ow]
    ksize = [d - (od - 1) * stride[0], h - (oh - 1) * stride[1],
             w - (ow - 1) * stride[2]]
    return pool3d(input, ksize, pool_type, stride, 0, name=name)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_batch_id=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        ins["RoisBatchId"] = [rois_batch_id]
    return _simple("roi_pool", ins,
                   {"pooled_height": pooled_height,
                    "pooled_width": pooled_width,
                    "spatial_scale": spatial_scale},
                   input.dtype, name=name)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_batch_id=None,
              name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        ins["RoisBatchId"] = [rois_batch_id]
    return _simple("roi_align", ins,
                   {"pooled_height": pooled_height,
                    "pooled_width": pooled_width,
                    "spatial_scale": spatial_scale,
                    "sampling_ratio": sampling_ratio},
                   input.dtype, name=name)


def psroi_pool(input, rois, output_channels, spatial_scale,
               pooled_height, pooled_width, rois_batch_id=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        ins["RoisBatchId"] = [rois_batch_id]
    return _simple("psroi_pool", ins,
                   {"output_channels": output_channels,
                    "spatial_scale": spatial_scale,
                    "pooled_height": pooled_height,
                    "pooled_width": pooled_width},
                   input.dtype, name=name)


def im2sequence(input, filter_size=1, stride=1, padding=0,
                per_example=False, name=None):
    """per_example=False: LoD-style flat rows (N*oh*ow, C*kh*kw);
    per_example=True keeps the batch dim -> (N, oh*ow, C*kh*kw)."""
    pads = (list(padding) if isinstance(padding, (list, tuple))
            else [padding] * 4)
    return _simple("im2sequence", {"X": [input]},
                   {"kernels": filter_size, "strides": stride,
                    "paddings": pads, "per_example": bool(per_example)},
                   input.dtype, name=name)


def grid_sampler(x, grid, name=None):
    return _simple("grid_sampler", {"X": [x], "Grid": [grid]}, {},
                   x.dtype, out_slot="Output", name=name)


def affine_grid(theta, out_shape=None, name=None):
    if out_shape is None:
        raise ValueError("layers.affine_grid: out_shape is required "
                         "(static [N, C, H, W] list or a Variable)")
    ins = {"Theta": [theta]}
    attrs = {}
    if isinstance(out_shape, Variable):
        ins["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = list(out_shape)
    return _simple("affine_grid", ins, attrs, theta.dtype,
                   out_slot="Output", name=name)


def affine_channel(x, scale=None, bias=None, param_attr=None,
                   bias_attr=None, data_layout="NCHW", name=None):
    """ref layers/nn.py affine_channel: out = scale * x + bias per
    channel; owns the params when scale/bias vars are not passed."""
    from ..framework.initializer import ConstantInitializer
    helper = LayerHelper("affine_channel", name=name)
    c = int(x.shape[1 if data_layout == "NCHW" else -1])
    if scale is None:
        scale = helper.create_parameter(
            param_attr, shape=[c], dtype=x.dtype,
            default_initializer=ConstantInitializer(1.0))
    if bias is None:
        bias = helper.create_parameter(bias_attr, shape=[c],
                                       dtype=x.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("affine_channel",
                     {"X": [x], "Scale": [scale], "Bias": [bias]},
                     {"Out": [out]}, {"data_layout": data_layout})
    return out


def space_to_depth(x, blocksize, name=None):
    return _simple("space_to_depth", {"X": [x]},
                   {"blocksize": blocksize}, x.dtype, name=name)


def crop(x, shape=None, offsets=None, name=None):
    return _simple("crop", {"X": [x]},
                   {"shape": list(shape or []),
                    "offsets": list(offsets or [])}, x.dtype, name=name)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _simple("pad_constant_like", {"X": [x], "Y": [y]},
                   {"pad_value": pad_value}, y.dtype, name=name)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """ref layers/nn.py image_resize_short: resize so the short side is
    out_short_len (static shapes: computed at build time)."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    scale = out_short_len / short
    from .nn import image_resize
    return image_resize(input, out_shape=[int(round(h * scale)),
                                          int(round(w * scale))],
                        resample=resample)


def random_crop(x, shape, seed=None, name=None):
    return _simple("random_crop", {"X": [x]}, {"shape": list(shape)},
                   x.dtype, name=name)


# --- losses / metrics extras ---------------------------------------------

def bpr_loss(input, label, name=None):
    return _simple("bpr_loss", {"X": [input], "Label": [label]}, {},
                   input.dtype, out_slot="Y", name=name)


def rank_loss(label, left, right, name=None):
    return _simple("rank_loss",
                   {"Label": [label], "Left": [left], "Right": [right]},
                   {}, left.dtype, name=name)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype, True)
    helper.append_op("margin_rank_loss",
                     {"Label": [label], "X1": [left], "X2": [right]},
                     {"Out": [out], "Activated": [act]},
                     {"margin": margin})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    return _simple("log_loss",
                   {"Predicted": [input], "Labels": [label]},
                   {"epsilon": epsilon}, input.dtype, out_slot="Loss",
                   name=name)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """ref layers/nn.py dice_loss — composed from element/reduce ops
    (the reference composes it the same way, not as one kernel)."""
    helper = LayerHelper("dice_loss", name=name)
    red_dims = list(range(1, len(input.shape)))

    def _app(op, ins, attrs=None, dtype=None):
        o = helper.create_variable_for_type_inference(dtype or input.dtype)
        helper.append_op(op, ins, {"Out": [o]}, attrs or {})
        return o

    labf = _app("cast", {"X": [label]}, {"out_dtype": "float32"})
    inter = _app("elementwise_mul", {"X": [input], "Y": [labf]})
    inter = _app("reduce_sum", {"X": [inter]}, {"dim": red_dims})
    s_in = _app("reduce_sum", {"X": [input]}, {"dim": red_dims})
    s_lb = _app("reduce_sum", {"X": [labf]}, {"dim": red_dims})
    union = _app("elementwise_add", {"X": [s_in], "Y": [s_lb]})
    num = _app("scale", {"X": [inter]}, {"scale": 2.0, "bias": epsilon})
    den = _app("scale", {"X": [union]}, {"scale": 1.0, "bias": epsilon})
    dice = _app("elementwise_div", {"X": [num], "Y": [den]})
    loss = _app("scale", {"X": [dice]}, {"scale": -1.0, "bias": 1.0})
    return _app("reduce_mean", {"X": [loss]}, {"dim": [0]})


def mean_iou(input, label, num_classes, name=None):
    helper = LayerHelper("mean_iou", name=name)
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int64", True)
    correct = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("mean_iou",
                     {"Predictions": [input], "Labels": [label]},
                     {"OutMeanIou": [miou], "OutWrong": [wrong],
                      "OutCorrect": [correct]},
                     {"num_classes": num_classes})
    return miou, wrong, correct


# --- misc -----------------------------------------------------------------

def multiplex(inputs: List[Variable], index, name=None):
    return _simple("multiplex", {"X": list(inputs), "Ids": [index]}, {},
                   inputs[0].dtype, name=name)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    helper = LayerHelper("row_conv", name=name)
    d = int(input.shape[-1])
    w = helper.create_parameter(param_attr,
                                shape=[future_context_size + 1, d],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", {"X": [input], "Filter": [w]},
                     {"Out": [out]}, {})
    return helper.append_activation(out, act)


def bilinear_tensor_product(x, y, size, param_attr=None, bias_attr=None,
                            act=None, name=None):
    helper = LayerHelper("bilinear_tensor_product", name=name)
    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    w = helper.create_parameter(param_attr, shape=[size, dx, dy],
                                dtype=x.dtype)
    b = helper.create_parameter(bias_attr, shape=[size], dtype=x.dtype,
                                is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("bilinear_tensor_product",
                     {"X": [x], "Y": [y], "Weight": [w], "Bias": [b]},
                     {"Out": [out]}, {})
    return helper.append_activation(out, act)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _simple("add_position_encoding", {"X": [input]},
                   {"alpha": alpha, "beta": beta}, input.dtype, name=name)


def similarity_focus(input, axis, indexes, name=None):
    return _simple("similarity_focus", {"X": [input]},
                   {"axis": axis, "indexes": list(indexes)}, input.dtype,
                   name=name)


def hash(input, hash_size, num_hash=1, name=None):
    return _simple("hash", {"X": [input]},
                   {"mod_by": hash_size, "num_hash": num_hash}, "int64",
                   name=name)


def merge_selected_rows(ids, values, name=None):
    helper = LayerHelper("merge_selected_rows", name=name)
    out_ids = helper.create_variable_for_type_inference("int64")
    out = helper.create_variable_for_type_inference(values.dtype)
    helper.append_op("merge_selected_rows",
                     {"Ids": [ids], "Values": [values]},
                     {"OutIds": [out_ids], "Out": [out]}, {})
    return out_ids, out


def get_tensor_from_selected_rows(ids, values, height, name=None):
    return _simple("get_tensor_from_selected_rows",
                   {"Ids": [ids], "Values": [values]},
                   {"height": height}, values.dtype, name=name)


def shape(input, name=None):
    return _simple("shape", {"Input": [input]}, {}, "int32", name=name)


def sum(x: Union[Variable, List[Variable]], name=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return _simple("sum", {"X": list(xs)}, {}, xs[0].dtype, name=name)


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    dtype="float32", name=None):
    return _simple("gaussian_random_batch_size_like", {"Input": [input]},
                   {"shape": list(shape), "mean": mean, "std": std,
                    "dtype": dtype}, dtype, name=name)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """ref layers/nn.py autoincreased_step_counter: a persistent int64
    counter incremented by `step` each run, starting at `begin`."""
    helper = LayerHelper("autoincreased_step_counter")
    name = counter_name or "@step_counter@"
    block = helper.main_program.global_block()
    if block.has_var(name):
        return block.var(name)
    ctr = block.create_var(name=name, shape=[1], dtype="int64",
                           persistable=True, stop_gradient=True)
    sb = helper.startup_program.global_block()
    if not sb.has_var(name):
        sb.create_var(name, shape=[1], dtype="int64", persistable=True)
        # init to begin-step: the in-program increment runs before the
        # first read, so the first observed value is exactly `begin`
        sb.append_op("fill_constant", outputs={"Out": [name]},
                     attrs={"shape": [1], "dtype": "int64",
                            "value": int(begin) - int(step)})
    block.append_op("increment_loop_counter", {"X": [name]},
                    {"Out": [name]}, {"step": int(step)})
    return ctr


def lstm(input, init_h=None, init_c=None, max_len=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False,
         param_attr=None, name=None):
    """cudnn_lstm-style fused multi-layer LSTM (ref layers/nn.py lstm).
    init_h/init_c: optional [num_layers*ndir, B, H] initial states
    (dropout_prob/max_len accepted for API parity; inter-layer dropout
    is not applied on this fused path)."""
    if hidden_size is None:
        raise ValueError("layers.lstm: hidden_size is required")
    helper = LayerHelper("lstm", name=name)
    d = int(input.shape[-1])
    ndir = 2 if is_bidirec else 1
    n = 0
    din = d
    for _ in range(num_layers):
        n += ndir * (din * 4 * hidden_size + hidden_size * 4 * hidden_size
                     + 4 * hidden_size)
        din = hidden_size * ndir
    w = helper.create_parameter(param_attr, shape=[n], dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype, True)
    last_c = helper.create_variable_for_type_inference(input.dtype, True)
    ins = {"Input": [input], "W": [w]}
    if init_h is not None:
        ins["InitH"] = [init_h]
    if init_c is not None:
        ins["InitC"] = [init_c]
    helper.append_op("cudnn_lstm", ins,
                     {"Out": [out], "LastH": [last_h], "LastC": [last_c]},
                     {"hidden_size": hidden_size, "num_layers": num_layers,
                      "is_bidirec": is_bidirec})
    return out, last_h, last_c


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  name=None):
    """Projected LSTM (ref layers/nn.py dynamic_lstmp -> lstmp op)."""
    helper = LayerHelper("dynamic_lstmp", name=name)
    hidden = size // 4
    w = helper.create_parameter(param_attr,
                                shape=[proj_size, 4 * hidden],
                                dtype=input.dtype)
    pw = helper.create_parameter(None, shape=[hidden, proj_size],
                                 dtype=input.dtype)
    proj = helper.create_variable_for_type_inference(input.dtype)
    cell = helper.create_variable_for_type_inference(input.dtype)
    last_h = helper.create_variable_for_type_inference(input.dtype, True)
    last_c = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("lstmp",
                     {"Input": [input], "Weight": [w], "ProjWeight": [pw]},
                     {"Projection": [proj], "Cell": [cell],
                      "LastH": [last_h], "LastC": [last_c]}, {})
    # reference dynamic_lstmp returns (projection, per-step cell sequence)
    return proj, cell


def scale_sub_region(x, indices, value=1.0, name=None):
    """Scale a per-instance CHW sub-box of [B, C, H, W] by `value`
    (ref scale_sub_region_op); indices [B, 6] 1-based inclusive
    (C0, C1, H0, H1, W0, W1)."""
    return _simple("scale_sub_region", {"X": [x], "Indices": [indices]},
                   {"value": float(value)}, x.dtype, name=name)
