"""Operator overloading on Variable (ref layers/math_op_patch.py)."""
from __future__ import annotations

import numpy as np

from ..framework.layer_helper import LayerHelper
from ..framework.program import Variable


def _scalar_to_var(ref: Variable, value):
    helper = LayerHelper("fill_constant")
    out = helper.create_variable_for_type_inference(ref.dtype)
    helper.append_op("fill_constant", {}, {"Out": [out]},
                     {"shape": [1], "dtype": ref.dtype,
                      "value": float(value)})
    return out


def _binary(op_name, reverse=False):
    def impl(self, other):
        from . import nn
        if not isinstance(other, Variable):
            other = _scalar_to_var(self, other)
        x, y = (other, self) if reverse else (self, other)
        helper = LayerHelper(op_name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_name, {"X": [x], "Y": [y]}, {"Out": [out]},
                         {"axis": -1})
        return out
    return impl


def _compare(op_name):
    def impl(self, other):
        if not isinstance(other, Variable):
            other = _scalar_to_var(self, other)
        helper = LayerHelper(op_name)
        out = helper.create_variable_for_type_inference("bool")
        helper.append_op(op_name, {"X": [self], "Y": [other]},
                         {"Out": [out]}, {"axis": -1})
        out.stop_gradient = True
        return out
    return impl


def monkey_patch_variable():
    Variable.__add__ = _binary("elementwise_add")
    Variable.__radd__ = _binary("elementwise_add", reverse=True)
    Variable.__sub__ = _binary("elementwise_sub")
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True)
    Variable.__mul__ = _binary("elementwise_mul")
    Variable.__rmul__ = _binary("elementwise_mul", reverse=True)
    Variable.__truediv__ = _binary("elementwise_div")
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__mod__ = _binary("elementwise_mod")
    Variable.__floordiv__ = _binary("elementwise_floordiv")
    Variable.__eq__ = _compare("equal")
    Variable.__ne__ = _compare("not_equal")
    Variable.__lt__ = _compare("less_than")
    Variable.__le__ = _compare("less_equal")
    Variable.__gt__ = _compare("greater_than")
    Variable.__ge__ = _compare("greater_equal")
    Variable.__hash__ = lambda self: hash(id(self))

    def _neg(self):
        from . import nn
        return nn.scale(self, -1.0)
    Variable.__neg__ = _neg
