"""Layers package (ref python/paddle/fluid/layers/)."""
from . import nn
from . import tensor
from . import rnn
from . import control_flow
from . import learning_rate_scheduler
from .nn import *  # noqa: F401,F403
from .tensor import (create_tensor, fill_constant,  # noqa: F401
                     fill_constant_batch_size_like, cast, concat, sums,
                     assign, argmin, argmax, argsort, ones, zeros,
                     ones_like, zeros_like, reverse, linspace, eye, diag)
from .rnn import (dynamic_lstm, dynamic_gru, gru_unit,  # noqa: F401
                  lstm_unit, lstm_layer)
from .control_flow import While, Switch, StaticRNN  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup)
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()
