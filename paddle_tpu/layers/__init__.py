"""Layers package (ref python/paddle/fluid/layers/)."""
from . import nn
from . import tensor
from . import rnn
from . import control_flow
from . import learning_rate_scheduler
from . import sequence
from .nn import *  # noqa: F401,F403
from .sequence import (  # noqa: F401
    sequence_conv, sequence_context, sequence_pool, scale_sub_region,
    sequence_first_step,
    sequence_last_step,
    sequence_softmax, sequence_concat, sequence_slice, sequence_expand,
    sequence_expand_as, sequence_pad, sequence_unpad, sequence_reshape,
    sequence_enumerate, sequence_scatter, sequence_reverse, lod_reset,
    linear_chain_crf, crf_decoding, chunk_eval, warpctc,
    ctc_greedy_decoder, edit_distance, nce, hsigmoid, sampling_id,
    beam_search, beam_search_decode, conv3d, conv3d_transpose, pool3d,
    adaptive_pool3d, roi_pool, roi_align, psroi_pool, im2sequence,
    grid_sampler, affine_grid, affine_channel, space_to_depth, crop,
    pad_constant_like, image_resize_short, random_crop, bpr_loss,
    rank_loss, margin_rank_loss, log_loss, dice_loss, mean_iou,
    multiplex, row_conv, bilinear_tensor_product, add_position_encoding,
    similarity_focus, hash, merge_selected_rows,
    get_tensor_from_selected_rows, shape, sum,
    gaussian_random_batch_size_like, autoincreased_step_counter, lstm,
    dynamic_lstmp)
from .tensor import (create_tensor, fill_constant,  # noqa: F401
                     fill_constant_batch_size_like, cast, concat, sums,
                     assign, argmin, argmax, argsort, ones, zeros,
                     ones_like, zeros_like, reverse, linspace, eye, diag)
from .rnn import (dynamic_lstm, dynamic_gru, gru_unit,  # noqa: F401
                  lstm_unit, lstm_layer)
from .control_flow import While, Switch, StaticRNN  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup)
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()
