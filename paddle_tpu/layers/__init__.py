"""Layers package (ref python/paddle/fluid/layers/)."""
from . import nn
from . import tensor
from .nn import *  # noqa: F401,F403
from .tensor import (create_tensor, fill_constant,  # noqa: F401
                     fill_constant_batch_size_like, cast, concat, sums,
                     assign, argmin, argmax, argsort, ones, zeros,
                     ones_like, zeros_like, reverse, linspace, eye, diag)
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()
