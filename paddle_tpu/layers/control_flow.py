"""Control-flow layer DSL (ref python/paddle/fluid/layers/control_flow.py:
While:504, Switch:1139, IfElse:1265, StaticRNN:278, DynamicRNN:1395).

TPU-first: the block-builder API is preserved (context managers appending
ops into sub-blocks) but the sub-blocks lower to lax.while_loop/lax.cond/
lax.scan, so shapes must be loop-invariant and ragged sequences come in
padded with masks (DynamicRNN capability = StaticRNN over padded batch +
sequence_mask; SURVEY.md hard part (a/b))."""
from __future__ import annotations

import contextlib
from typing import List, Optional, Sequence

import numpy as np

from ..framework.layer_helper import LayerHelper
from ..framework.program import Variable, default_main_program
from ..framework import unique_name
from . import tensor as tensor_layers


class While:
    """ref control_flow.py:504.

    i = layers.fill_constant([1], "int64", 0)
    n = layers.fill_constant([1], "int64", 10)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        ... ops writing loop state (must re-assign cond via layers.assign)
    """

    def __init__(self, cond: Variable, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.program = self.helper.main_program

    @contextlib.contextmanager
    def block(self):
        parent_idx = self.program._current_block_idx
        sub = self.program.create_block()
        yield
        self.program._current_block_idx = parent_idx
        parent = self.program.blocks[parent_idx]
        # loop-carried state = every pre-existing var the sub-block writes;
        # route it through the op's Out so the final values land in the env
        from ..ops.control_flow import _block_written_vars
        outs = [n for n in _block_written_vars(sub) if parent.has_var(n)]
        if self.cond_var.name not in outs:
            outs.append(self.cond_var.name)
        parent.append_op("while", {"Cond": [self.cond_var.name]},
                         {"Out": outs},
                         {"sub_block": sub.idx,
                          "condition": self.cond_var.name,
                          "out_vars": outs})


class Switch:
    """ref control_flow.py:1139 — builds a chain of conditional blocks.

    with layers.Switch() as switch:
        with switch.case(cond1): ...assign...
        with switch.default(): ...assign...
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.program = self.helper.main_program
        self._case_conds: List[Variable] = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @contextlib.contextmanager
    def case(self, condition: Variable):
        # condition AND not(any previous condition)
        from . import nn
        cond = condition
        for prev in self._case_conds:
            notp = nn.logical_not(prev)
            cond = nn.logical_and(cond, notp)
        self._case_conds.append(condition)
        with _conditional_block(self.program, cond):
            yield

    @contextlib.contextmanager
    def default(self):
        from . import nn
        assert self._case_conds, "default() requires at least one case()"
        cond = nn.logical_not(self._case_conds[0])
        for prev in self._case_conds[1:]:
            cond = nn.logical_and(cond, nn.logical_not(prev))
        with _conditional_block(self.program, cond):
            yield


@contextlib.contextmanager
def _conditional_block(program, cond: Variable):
    parent_idx = program._current_block_idx
    sub = program.create_block()
    yield
    program._current_block_idx = parent_idx
    parent = program.blocks[parent_idx]
    # out_vars: every pre-existing var the sub-block writes
    from ..ops.control_flow import _block_written_vars
    outs = [n for n in _block_written_vars(sub) if parent.has_var(n)]
    parent.append_op("conditional_block", {"Cond": [cond.name]},
                     {"Out": outs},
                     {"sub_block": sub.idx, "out_vars": outs})


class StaticRNN:
    """ref control_flow.py:278 — per-timestep block over [B, T, ...]
    inputs, lowered to ONE lax.scan.

    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)            # [B, D] slice of [B, T, D]
        h_prev = rnn.memory(init=h0)       # carried state
        h = layers.fc(concat([x_t, h_prev]), size=H, act="tanh")
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()                            # [B, T, H]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.program = self.helper.main_program
        self._x: List[tuple] = []          # (outer var, inner var)
        self._memories: List[dict] = []
        self._outputs: List[Variable] = []
        self._sub = None
        self._parent_idx = None
        self._result: Optional[List[Variable]] = None

    @contextlib.contextmanager
    def step(self):
        self._parent_idx = self.program._current_block_idx
        self._sub = self.program.create_block()
        yield
        self.program._current_block_idx = self._parent_idx
        self._finalize()

    def step_input(self, x: Variable) -> Variable:
        """x: [B, T, ...] outer var; returns the per-step [B, ...] var."""
        inner = self._sub.create_var(
            name=unique_name.generate("rnn_step_in"), dtype=x.dtype,
            shape=(x.shape[0],) + tuple(x.shape[2:]) if x.shape else None)
        self._x.append((x, inner))
        return inner

    def memory(self, init: Variable) -> Variable:
        """Carried state initialised from `init` [B, H]."""
        inner = self._sub.create_var(
            name=unique_name.generate("rnn_mem"), dtype=init.dtype,
            shape=init.shape)
        self._memories.append({"init": init, "pre": inner, "new": None})
        return inner

    def update_memory(self, mem: Variable, new: Variable):
        for m in self._memories:
            if m["pre"].name == mem.name:
                m["new"] = new
                return
        raise ValueError(f"{mem.name} is not a memory of this StaticRNN")

    def step_output(self, out: Variable):
        self._outputs.append(out)

    def _finalize(self):
        parent = self.program.blocks[self._parent_idx]
        for m in self._memories:
            if m["new"] is None:
                raise ValueError("every memory needs update_memory()")
        # carry var names: inside the block, after running ops, the carry
        # value for memory m is m['new']; the scan op maps carry slot name
        # pre -> reads new. We implement by appending assign new->pre.
        for m in self._memories:
            self._sub.append_op("assign", {"X": [m["new"].name]},
                                {"Out": [m["pre"].name]}, {})
        carry = [m["pre"].name for m in self._memories]
        # x vars are scanned over time: the op needs [T, B, ...]; outer
        # vars are [B, T, ...] so transpose first in the parent block
        x_names = []
        for outer, inner in self._x:
            perm = list(range(len(outer.shape)))
            perm[0], perm[1] = 1, 0
            t_var = parent.create_var(
                name=unique_name.generate(outer.name + ".tbd"),
                dtype=outer.dtype)
            parent.append_op("transpose", {"X": [outer.name]},
                             {"Out": [t_var.name]}, {"axis": perm})
            x_names.append((t_var.name, inner.name))
        y_names = [o.name for o in self._outputs]

        # output shapes: scan stacks per-step outputs as [T, ...]; T comes
        # from the first scanned input's time axis when static
        T = None
        if self._x:
            outer0 = self._x[0][0]
            if outer0.shape and len(outer0.shape) > 1:
                T = outer0.shape[1]
        outs = []
        for o in self._outputs:
            shape = ((T,) + tuple(o.shape)) if (T is not None and
                                                o.shape is not None) else None
            outs.append(parent.create_var(
                name=unique_name.generate("rnn_out"), dtype=o.dtype,
                shape=shape))
        carry_outs = [parent.create_var(
            name=unique_name.generate("rnn_carry"), dtype=m["init"].dtype,
            shape=m["init"].shape)
            for m in self._memories]
        parent.append_op(
            "static_rnn_scan",
            {"Init": [m["init"].name for m in self._memories],
             "X": [t for t, _ in x_names]},
            {"Ys": [o.name for o in outs],
             "CarryOut": [c.name for c in carry_outs]},
            {"sub_block": self._sub.idx,
             "carry_vars": carry,
             "x_inner_vars": [i for _, i in x_names],
             "y_vars": y_names})
        self._result = outs

    def __call__(self) -> Variable:
        """Returns the first step_output stacked over time as [B, T, ...]."""
        helper = LayerHelper("static_rnn_out")
        out = self._result[0]
        tr = helper.create_variable_for_type_inference(out.dtype)
        # scan stacks as [T, B, ...] -> transpose back
        nd = len(self._outputs[0].shape or (0, 0)) + 1
        perm = list(range(nd))
        perm[0], perm[1] = 1, 0
        if out.shape is not None and len(out.shape) >= 2:
            tr.shape = (out.shape[1], out.shape[0]) + tuple(out.shape[2:])
        helper.main_program.current_block().append_op(
            "transpose", {"X": [out.name]}, {"Out": [tr.name]},
            {"axis": perm})
        return tr

    def outputs(self) -> List[Variable]:
        return self._result
