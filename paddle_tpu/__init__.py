"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle Fluid ~1.2 (reference: /root/reference), built on
JAX/XLA/Pallas/pjit idioms.

Architecture (vs the reference, see SURVEY.md):
  * Program/Block/Operator IR (framework/program.py) — serializable
    program-as-data like ProgramDesc, but executed by compiling the WHOLE
    program into one jitted XLA function (framework/executor.py), not by an
    op-by-op interpreter.
  * Autodiff: append_backward marks a vjp boundary; XLA differentiates
    (framework/backward.py).  Optimizers are in-program ops (optimizer.py).
  * Parallelism: jax.sharding.Mesh + pjit/shard_map replace
    ParallelExecutor/NCCL/pserver (parallel/).
  * Hot ops get Pallas TPU kernels (kernels/).
"""
from . import core
from .core.place import CPUPlace, TPUPlace, CUDAPlace, default_place
from .core import flags, profiler
from .framework.program import (Program, Block, Variable, Parameter,
                                program_guard, default_main_program,
                                default_startup_program,
                                reset_default_programs)
from .framework import unique_name
from .framework.executor import Executor, Scope, global_scope
from .framework.async_executor import AsyncExecutor, DataFeedDesc, Slot
from .framework.backward import append_backward
from .framework.layer_helper import ParamAttr
from .framework import initializer
from . import layers
from . import optimizer
from . import regularizer
from . import clip
from . import io
from . import metrics
from . import nets
from . import reader
from . import dataset
from . import transpiler
from . import analysis
from . import contrib
from . import debugger
from . import observability
from . import resilience
from . import serving
from . import imperative
from . import inference
from . import distributed
from . import sparse
from .data_feeder import DataFeeder
from .trainer import (BeginEpochEvent, BeginStepEvent, CheckpointConfig,
                      EndEpochEvent, EndStepEvent, Trainer)
from .parallel import ParallelExecutor, ExecutionStrategy, BuildStrategy

__version__ = "0.1.0"
