"""Armada: health-aware multi-replica serving router (ISSUE 20).

An HTTP frontend over N supervised serving workers — the reference's
Go cloud tier (etcd-backed fault-tolerant master/pserver) applied to
the inference plane: clients POST /serving/generate to ONE address and
replica death, drain or overload is the router's problem, not theirs.

  * Routing: readiness-probed (GET /healthz on every replica, the
    worker's batcher state) + least-loaded (in-flight count, then the
    probed queue depth, round-robin among ties).
  * Retry-elsewhere: a 503-drained / connection-refused /
    deadline-exceeded dispatch answer is retried on a DIFFERENT
    replica with deterministic backoff (resilience/retry.py jitter)
    under a per-request retry budget; 429 (shed) and 4xx pass through
    — backpressure and client errors are not failover events.
  * Per-replica circuit breakers: ``router_breaker_threshold``
    consecutive errors open the breaker (no routing); after
    ``router_breaker_reset_s`` it half-opens and one probe (or, with
    no alternative replica, one trial request) decides recovery.
  * Deadlines end to end: the client's ``timeout_s`` (or
    ``router_default_deadline_s``) is a hard wall — every hop carries
    only the REMAINING budget, and an expired deadline is an explicit
    504, never a lost request.
  * Graceful drain: ``drain_replica`` stops admitting to a replica
    BEFORE telling it to drain (in-flight finishes elsewhere);
    SIGTERM on the router drains every replica, waits out its own
    in-flight dispatches, then exits.

Chaos sites ``router.dispatch`` / ``router.probe`` make every failure
mode injectable; journal kind ``router`` records spawn/ready/drain/
dead/route-away transitions; ``router_*`` metrics put per-replica
requests, retries, breaker state and the healthy-replica gauge on
/metrics.  The module is imported LAZILY — a single-replica process
that never touches the router keeps byte-identical routes, metric
families and compile keys (the flag-off invariance idiom; regression
in tests/test_router.py).
"""
from __future__ import annotations

import json
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..core import flags
from ..observability import journal as obs_journal
from ..observability import metrics as obs_metrics
from ..observability import tracectx as obs_tracectx
from ..resilience import chaos
from ..resilience import retry as rretry

SCHEMA = "paddle_tpu.serving.router.v1"

_m_requests = obs_metrics.counter(
    "router_requests_total",
    "Client requests terminated by the router, by answering replica "
    "('none' when no replica answered) and terminal status.",
    ("replica", "status"))
_m_dispatches = obs_metrics.counter(
    "router_dispatches_total",
    "Dispatch attempts started, by target replica (a client request "
    "that retries elsewhere counts once per hop).", ("replica",))
_m_retries = obs_metrics.counter(
    "router_retries_total",
    "Retry-elsewhere events, by reason (drained | refused | timeout "
    "| error).", ("reason",))
_m_breaker = obs_metrics.gauge(
    "router_breaker_state",
    "Per-replica circuit breaker: 0 closed, 1 half-open, 2 open.",
    ("replica",))
_m_healthy = obs_metrics.gauge(
    "router_healthy_replicas",
    "Replicas currently ready with a closed breaker.")
_m_latency = obs_metrics.histogram(
    "router_request_seconds",
    "End-to-end router latency per client request (all hops + "
    "backoff included).")

# breaker gauge encoding
_BREAKER_CODE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class HttpTransport:
    """Default wire: urllib against each replica's observability
    endpoint.  Returns ``(code, doc)`` for ANY HTTP answer (4xx/5xx
    included — those are classified by the router, not exceptions);
    raises ConnectionError when the replica is unreachable and
    TimeoutError when the socket deadline expires."""

    def get_json(self, url: str, path: str,
                 timeout: float) -> Tuple[int, dict]:
        import socket
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(url.rstrip("/") + path,
                                        timeout=timeout) as r:
                return r.status, json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            return e.code, self._body(e)
        except socket.timeout as e:
            raise TimeoutError(f"{url}{path}: {e}") from e
        except urllib.error.URLError as e:
            if isinstance(e.reason, socket.timeout):
                raise TimeoutError(f"{url}{path}: {e.reason}") from e
            raise ConnectionError(f"{url}{path}: {e.reason}") from e

    def post_json(self, url: str, path: str, body: dict, timeout: float,
                  headers: Optional[Dict[str, str]] = None
                  ) -> Tuple[int, dict]:
        import socket
        import urllib.error
        import urllib.request
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        req = urllib.request.Request(
            url.rstrip("/") + path, data=json.dumps(body).encode(),
            headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            return e.code, self._body(e)
        except socket.timeout as e:
            raise TimeoutError(f"{url}{path}: {e}") from e
        except urllib.error.URLError as e:
            if isinstance(e.reason, socket.timeout):
                raise TimeoutError(f"{url}{path}: {e.reason}") from e
            raise ConnectionError(f"{url}{path}: {e.reason}") from e

    @staticmethod
    def _body(e) -> dict:
        try:
            return json.loads(e.read().decode() or "{}")
        except Exception:
            return {"error": f"HTTP {e.code}"}


class Replica:
    """One routed serving worker: probed health + load + breaker."""

    __slots__ = ("rid", "url", "state", "queue_depth", "inflight",
                 "breaker", "consecutive", "open_until", "last_seen")

    def __init__(self, rid: str, url: str):
        self.rid = str(rid)
        self.url = str(url).rstrip("/")
        # "starting" | "ready" | "draining" | "dead"
        self.state = "starting"
        self.queue_depth = 0
        self.inflight = 0
        self.breaker = "closed"          # "closed" | "open"
        self.consecutive = 0             # consecutive dispatch/probe
        self.open_until = 0.0            # errors while closed
        self.last_seen = 0.0

    def breaker_state(self, now: float) -> str:
        if self.breaker == "closed":
            return "closed"
        return "half_open" if now >= self.open_until else "open"

    def to_dict(self, now: float) -> dict:
        return {"replica": self.rid, "url": self.url,
                "state": self.state,
                "breaker": self.breaker_state(now),
                "inflight": self.inflight,
                "queue_depth": self.queue_depth,
                "consecutive_errors": self.consecutive}


class Router:
    """Health/load-aware request router over N serving replicas.

    Every tunable has a constructor override (tests) defaulting to its
    ``router_*`` flag; `transport`, `now_fn` and `sleep_fn` are seams
    so the breaker/drain state machines are testable with no sockets
    and no real sleeps."""

    def __init__(self, replicas: Sequence[Union[str, Tuple[str, str]]],
                 *, transport: Optional[HttpTransport] = None,
                 now_fn: Callable[[], float] = time.time,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 retry_budget: Optional[int] = None,
                 probe_interval: Optional[float] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 backoff_s: Optional[float] = None,
                 default_deadline_s: Optional[float] = None):
        self.transport = transport or HttpTransport()
        self._now = now_fn
        self._sleep = sleep_fn

        def _f(flag, override):
            return flags.get_flag(flag) if override is None else override

        self.retry_budget = int(_f("router_retry_budget", retry_budget))
        self.probe_interval = float(_f("router_probe_interval_s",
                                       probe_interval))
        self.breaker_threshold = int(_f("router_breaker_threshold",
                                        breaker_threshold))
        self.breaker_reset_s = float(_f("router_breaker_reset_s",
                                        breaker_reset_s))
        self.default_deadline_s = float(_f("router_default_deadline_s",
                                           default_deadline_s))
        self._retry = rretry.RetryPolicy(
            name="router_dispatch", max_attempts=self.retry_budget + 1,
            base_delay=float(_f("router_backoff_s", backoff_s)),
            max_delay=1.0)
        self._lock = threading.RLock()
        self.replicas: List[Replica] = []
        for i, spec in enumerate(replicas):
            rid, url = (str(i), spec) if isinstance(spec, str) else spec
            self.replicas.append(Replica(rid, url))
        self._rr = 0                     # round-robin tie-break cursor
        self._draining = False
        self._drain_requested = False    # SIGTERM flag: the probe loop
        self._stop_evt = threading.Event()   # honors it off-handler
        self._thread: Optional[threading.Thread] = None
        self._update_healthy()

    # -- lifecycle ---------------------------------------------------------
    @property
    def running(self) -> bool:
        return not self._stop_evt.is_set()

    @property
    def draining(self) -> bool:
        return self._draining

    def start(self) -> "Router":
        """Start the probe loop — also the router's control loop (it
        notices revived replicas, closes recovered breakers, honors a
        pending SIGTERM drain)."""
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop_evt.clear()
                self._thread = threading.Thread(
                    target=self._probe_loop, name="router-probe",
                    daemon=True)
                self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        """Stop the probe loop (no drain — tests/conftest)."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)

    def install_signal_handlers(self):
        """SIGTERM/SIGINT = drain every replica, finish in-flight
        dispatches, then exit (the worker's preemption contract, one
        level up).  The handler only sets a flag — the probe loop does
        the actual teardown outside signal context."""

        def _handler(signum, frame):
            self._drain_requested = True

        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)

    def request_drain(self):
        """Async-signal-safe drain trigger (what the SIGTERM handler
        does); the probe loop picks it up within one interval."""
        self._drain_requested = True

    def _probe_loop(self):
        while not self._stop_evt.is_set():
            if self._drain_requested and not self._draining:
                self.begin_drain(stop=True)
                return
            try:
                self.probe_all()
            except Exception:
                pass                     # probes must never kill the loop
            self._stop_evt.wait(self.probe_interval)

    # -- probing -----------------------------------------------------------
    def probe_all(self) -> int:
        """Probe every replica once; returns the ready count."""
        for rep in list(self.replicas):
            self.probe_once(rep)
        with self._lock:
            return sum(1 for r in self.replicas if r.state == "ready")

    def probe_once(self, rep: Replica) -> bool:
        """GET /healthz on one replica; classify and update state.
        Chaos site ``router.probe`` injects probe-path failures."""
        now = self._now()
        try:
            chaos.trigger("router.probe")
            code, doc = self.transport.get_json(
                rep.url, "/healthz",
                timeout=max(self.probe_interval, 1.0))
        except (ConnectionError, OSError, TimeoutError,
                chaos.InjectedFault):
            self._mark(rep, "dead")
            self._strike(rep, "probe")
            return False
        serving = (doc or {}).get("serving") or {}
        with self._lock:
            rep.last_seen = now
            rep.queue_depth = int(serving.get("queue_depth") or 0)
        state = serving.get("state")
        if state == "running":
            self._mark(rep, "ready")
            self._probe_success(rep)
            return True
        if state == "draining":
            self._mark(rep, "draining")
        elif state == "stopped":
            self._mark(rep, "dead")
        else:
            # healthz answered but no serving section: the worker's
            # endpoint is up before/without a batcher — not routable
            self._mark(rep, "starting")
        return False

    def _mark(self, rep: Replica, state: str):
        """State transition with journal on CHANGE only."""
        with self._lock:
            old, rep.state = rep.state, state
            if old == state:
                return
            resumed = state == "ready" and old in ("dead", "draining")
            self._update_healthy()
        event = {"ready": "ready", "dead": "dead",
                 "draining": "drain", "starting": "starting"}[state]
        obs_journal.emit("router", event, replica=rep.rid, url=rep.url,
                         previous=old)
        if resumed:
            # the headline transition: a killed/drained replica is
            # back in rotation
            obs_journal.emit("router", "resume", replica=rep.rid,
                            url=rep.url)

    def _strike(self, rep: Replica, where: str):
        """One consecutive-error strike; trips/re-arms the breaker."""
        now = self._now()
        with self._lock:
            rep.consecutive += 1
            tripped = False
            if rep.breaker == "closed" \
                    and rep.consecutive >= self.breaker_threshold:
                rep.breaker = "open"
                rep.open_until = now + self.breaker_reset_s
                tripped = True
            elif rep.breaker == "open" and now >= rep.open_until:
                # failed half-open trial: re-open for another window
                rep.open_until = now + self.breaker_reset_s
            _m_breaker.labels(replica=rep.rid).set(
                _BREAKER_CODE[rep.breaker_state(now)])
            self._update_healthy()
        if tripped:
            obs_journal.emit("router", "breaker_open", replica=rep.rid,
                             consecutive=rep.consecutive, where=where)

    def _probe_success(self, rep: Replica):
        now = self._now()
        with self._lock:
            rep.consecutive = 0
            closed = rep.breaker != "closed"
            rep.breaker = "closed"
            _m_breaker.labels(replica=rep.rid).set(0.0)
            self._update_healthy()
        if closed:
            obs_journal.emit("router", "breaker_close", replica=rep.rid)

    def _update_healthy(self):
        # call under lock
        now = self._now()
        _m_healthy.set(float(sum(
            1 for r in self.replicas
            if r.state == "ready" and r.breaker_state(now) == "closed")))

    # -- membership --------------------------------------------------------
    def add_replica(self, url: str, rid: Optional[str] = None) -> Replica:
        """Register a new (spawning) replica; the probe loop promotes
        it to ready once its worker answers /healthz running."""
        with self._lock:
            rid = str(len(self.replicas)) if rid is None else str(rid)
            rep = Replica(rid, url)
            self.replicas.append(rep)
        obs_journal.emit("router", "spawn", replica=rep.rid, url=rep.url)
        return rep

    def drain_replica(self, rid: Optional[str] = None,
                      stop: bool = False) -> str:
        """Graceful scale-down verb (the Helmsman ``drain_replica``
        actuator): stop admitting to one replica — chosen, or the
        least-loaded ready one — THEN tell it to drain.  The mark is
        synchronous under the router lock, so no dispatch can start
        against the replica after this returns."""
        with self._lock:
            if rid is not None:
                cands = [r for r in self.replicas if r.rid == str(rid)]
            else:
                cands = sorted(
                    (r for r in self.replicas if r.state == "ready"),
                    key=lambda r: (r.inflight, r.queue_depth, r.rid))
            if not cands:
                raise RuntimeError(
                    f"drain_replica: no ready replica to drain "
                    f"(rid={rid!r})")
            rep = cands[0]
        self._mark(rep, "draining")
        try:
            self.transport.post_json(rep.url, "/serving/drain",
                                     {"stop": bool(stop)}, timeout=5.0)
        except (ConnectionError, OSError, TimeoutError) as e:
            # already gone = already drained; the probe will classify
            obs_journal.emit("router", "drain_rpc_failed",
                             replica=rep.rid, error=repr(e)[:120])
        return rep.rid

    def begin_drain(self, stop: bool = True, timeout: float = 30.0):
        """Router-wide drain (SIGTERM semantics): stop admitting, tell
        every replica to drain, wait out in-flight dispatches; with
        ``stop`` also end the probe loop (process exit follows)."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        obs_journal.emit("router", "drain_begin",
                         replicas=len(self.replicas), stop=bool(stop))
        for rep in list(self.replicas):
            try:
                self.transport.post_json(rep.url, "/serving/drain",
                                         {"stop": bool(stop)},
                                         timeout=5.0)
            except (ConnectionError, OSError, TimeoutError):
                pass                     # dead already = drained already
            self._mark(rep, "draining")
        deadline = self._now() + timeout
        while self._now() < deadline:
            with self._lock:
                if not any(r.inflight for r in self.replicas):
                    break
            self._sleep(0.05)
        obs_journal.emit("router", "drain_complete",
                         replicas=len(self.replicas))
        if stop:
            self._stop_evt.set()

    # -- routing -----------------------------------------------------------
    def _pick(self, tried: set) -> Optional[Replica]:
        """Least-loaded ready replica with a closed breaker; falls back
        to a half-open trial when nothing closed is routable.  Prefers
        replicas this request has NOT yet failed on; round-robin among
        load ties."""
        now = self._now()
        with self._lock:
            cands = [r for r in self.replicas
                     if r.state == "ready"
                     and r.breaker_state(now) == "closed"]
            if not cands:
                cands = [r for r in self.replicas
                         if r.state == "ready"
                         and r.breaker_state(now) == "half_open"]
            if not cands:
                return None
            fresh = [r for r in cands if r.rid not in tried]
            if fresh:
                cands = fresh
            key = min((r.inflight, r.queue_depth) for r in cands)
            ties = [r for r in cands
                    if (r.inflight, r.queue_depth) == key]
            self._rr += 1
            rep = ties[self._rr % len(ties)]
            rep.inflight += 1
            return rep

    def handle(self, body: dict, trace=None) -> Tuple[int, dict]:
        """Route one ``POST /serving/generate`` body; returns
        ``(http_code, doc)`` exactly like the single-replica path, plus
        ``replica`` (who answered) and ``hops`` (dispatches consumed).
        Every outcome is explicit: ok | shed (429, passthrough) |
        drained/error (503) | timeout (504) — never a lost request."""
        t0 = time.perf_counter()
        if self._draining or not self.running:
            return 503, {"error": "router is draining",
                         "status": "drained"}
        try:
            timeout_s = float(body.get("timeout_s")
                              or self.default_deadline_s)
        except (TypeError, ValueError):
            return 400, {"error": "malformed request field: timeout_s",
                         "status": "error"}
        deadline = self._now() + timeout_s
        tried: set = set()
        hops = 0
        last_reason, last_doc = "error", {}
        while True:
            remaining = deadline - self._now()
            if remaining <= 0:
                return self._finish(t0, None, 504, {
                    "error": f"deadline exceeded after {hops} "
                             f"dispatch(es)", "status": "timeout",
                    "hops": hops})
            rep = self._pick(tried)
            if rep is None:
                return self._finish(t0, None, 503, {
                    "error": "no healthy replica "
                             f"(last: {last_reason})",
                    "status": "drained" if last_reason == "drained"
                              else "error",
                    "hops": hops})
            hops += 1
            _m_dispatches.labels(replica=rep.rid).inc()
            outcome: Tuple[str, int, dict]
            try:
                outcome = self._dispatch(rep, body, remaining, trace)
            finally:
                with self._lock:
                    rep.inflight -= 1
            verdict, code, doc = outcome
            if verdict == "done":
                if isinstance(doc, dict):
                    doc.setdefault("replica", rep.rid)
                    doc["hops"] = hops
                status = {200: "ok", 429: "shed"}.get(
                    code, str(doc.get("status") or "error"))
                if code == 200:
                    self._probe_success(rep)
                return self._finish(t0, rep, code, doc, status=status)
            # retry-elsewhere: strike (drain is a clean signal, not an
            # error), journal, back off, go around
            last_reason, last_doc = verdict, doc
            tried.add(rep.rid)
            if verdict != "drained":
                self._strike(rep, "dispatch")
            _m_retries.labels(reason=verdict).inc()
            obs_journal.emit("router", "route_away", replica=rep.rid,
                             reason=verdict, hop=hops)
            if hops > self.retry_budget:
                code = 504 if verdict == "timeout" else 503
                status = {"drained": "drained",
                          "timeout": "timeout"}.get(verdict, "error")
                return self._finish(t0, None, code, {
                    "error": f"retry budget exhausted after {hops} "
                             f"dispatch(es) (last: {verdict})",
                    "status": status, "hops": hops,
                    "last": (last_doc or {}).get("error")})
            delay = self._retry.delay(hops)
            self._sleep(min(delay, max(0.0, deadline - self._now())))

    def _dispatch(self, rep: Replica, body: dict, remaining: float,
                  trace) -> Tuple[str, int, dict]:
        """One hop: returns ("done", code, doc) for a terminal answer
        or (reason, code, doc) with reason in drained | refused |
        timeout | error for a retry-elsewhere condition.  Chaos site
        ``router.dispatch`` injects failures at this seam."""
        child = obs_tracectx.start_trace("router.dispatch", parent=trace)
        headers = ({"traceparent": child.traceparent()}
                   if child is not None else None)
        hop_body = dict(body, timeout_s=remaining)
        t0_unix, t0_perf = time.time(), time.perf_counter()
        reason, code, doc = "error", 0, {}
        try:
            chaos.trigger("router.dispatch")
            code, doc = self.transport.post_json(
                rep.url, "/serving/generate", hop_body,
                timeout=remaining + 1.0, headers=headers)
            if code == 503 and isinstance(doc, dict) \
                    and doc.get("status") == "drained":
                self._mark(rep, "draining")
                reason = "drained"
            elif code == 504:
                reason = "timeout"
            elif code in (200, 429) or 400 <= code < 500:
                reason = "done"          # terminal: answer, shed, or
            else:                        # a client error — passthrough
                reason = "error"         # 5xx: failed on this replica
            return reason, code, doc
        except TimeoutError as e:        # before OSError: TimeoutError
            reason = "timeout"           # IS an OSError since py3.10
            return "timeout", 0, {"error": repr(e)[:200]}
        except (ConnectionError, OSError) as e:
            reason = "refused"
            return "refused", 0, {"error": repr(e)[:200]}
        except chaos.InjectedFault as e:
            reason = "error"
            return "error", 0, {"error": repr(e)[:200]}
        finally:
            if child is not None and trace is not None:
                obs_tracectx.record_span(
                    "router.dispatch", trace.trace_id, child.span_id,
                    trace.span_id, t0_unix, t0_perf,
                    time.perf_counter() - t0_perf, kind="client",
                    attrs={"replica": rep.rid, "outcome": reason,
                           "code": code})

    def _finish(self, t0: float, rep: Optional[Replica], code: int,
                doc: dict, status: Optional[str] = None
                ) -> Tuple[int, dict]:
        status = status or str((doc or {}).get("status") or "error")
        _m_requests.labels(replica=rep.rid if rep else "none",
                           status=status).inc()
        _m_latency.observe(time.perf_counter() - t0)
        return code, doc

    # -- status ------------------------------------------------------------
    def status_doc(self) -> dict:
        now = self._now()
        with self._lock:
            reps = [r.to_dict(now) for r in self.replicas]
        return {
            "schema": SCHEMA, "time_unix": time.time(),
            "running": self.running, "draining": self._draining,
            "retry_budget": self.retry_budget,
            "healthy": sum(1 for r in reps
                           if r["state"] == "ready"
                           and r["breaker"] == "closed"),
            "replicas": reps,
        }


# -- process-wide singleton (mirrors serving.attach/get/reset) --------------
_mod_lock = threading.Lock()
_router: Optional[Router] = None


def attach(router: Router) -> Router:
    """Register the process-wide router ``POST /serving/generate``
    routes through (takes precedence over a locally attached
    batcher)."""
    global _router
    with _mod_lock:
        if _router is not None and _router is not router \
                and _router.running:
            raise RuntimeError(
                "a serving router is already attached; reset() first")
        _router = router
    return router


def get() -> Optional[Router]:
    return _router


def reset():
    """Test hook (rides serving.reset()/conftest): stop the probe
    loop, detach, and drop per-replica metric series so one case's
    fleet cannot leak into the next."""
    global _router
    with _mod_lock:
        r, _router = _router, None
    if r is not None:
        r.stop()
    _m_requests.clear()
    _m_dispatches.clear()
    _m_retries.clear()
    _m_breaker.clear()
    _m_healthy.set(0.0)


def _main(argv: Optional[List[str]] = None) -> int:
    """``python -m paddle_tpu.serving.router <port> --replica URL ...``
    — a standalone router frontend over already-running workers."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.router",
        description="Armada serving router: health-aware frontend "
                    "over N serving workers.")
    ap.add_argument("port", type=int)
    ap.add_argument("--replica", action="append", required=True,
                    help="replica base URL (repeatable)")
    args = ap.parse_args(argv)
    from ..observability import server as obs_server
    router = attach(Router(list(args.replica)).start())
    router.install_signal_handlers()
    srv = obs_server.start_http_server(port=args.port)
    print(f"ROUTER_READY {srv.url} replicas={len(router.replicas)}",
          flush=True)
    try:
        while router.running:
            time.sleep(0.1)
    finally:
        reset()
        obs_server.stop_http_server()
    print("ROUTER_DRAINED", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
