"""Online serving plane (ISSUE 8): continuous batching, decode-loop KV
cache, and SLO-metered admission control behind the fleet HTTP stack.

The fourth major plane after observability, resilience and the elastic
fleet — the production analog of the reference's C-API inference tier
(``AnalysisPredictor``/``NaiveExecutor``, PAPER.md §1 L4):

  * :mod:`.kv_cache`   — batched incremental decode over trained
    ``build_lm_net`` weights: per-layer K/V buffers, bucketed AOT
    prefill, one compiled decode step, per-slot retire/backfill.
  * :mod:`.batcher`    — request queue + continuous batcher + bounded
    admission (``ShedError`` = HTTP 429) + SIGTERM drain + SLO metrics.
  * :mod:`.loadgen`    — closed-loop concurrent client streams with
    p50/p99 TTFT / per-token reporting (the serving soak headline).
  * :mod:`.worker`     — a supervised serving process (engine + batcher
    + observability endpoint) the PR 5 supervisor can babysit under
    chaos.

One process-wide batcher may be ATTACHED here; the observability
endpoint's ``/serving`` route and ``POST /serving/generate`` resolve
through :func:`get`, and tests detach via :func:`reset` (conftest).
"""
from __future__ import annotations

import sys
import threading
import time
from typing import Optional

from ..observability import metrics as obs_metrics
# promoted to observability/metrics.py (ISSUE 15: the alert engine
# needs quantile predicates without importing the serving plane);
# re-exported here so existing serving callers keep working
from ..observability.metrics import histogram_quantiles  # noqa: F401
from .batcher import ContinuousBatcher, ServingRequest, ShedError
from .kv_cache import DecodeEngine, extract_lm_params

__all__ = ["DecodeEngine", "extract_lm_params", "ContinuousBatcher",
           "ServingRequest", "ShedError", "attach", "get", "drain",
           "reset", "status_doc", "histogram_quantiles", "get_router",
           "replica_id"]

_lock = threading.Lock()
_batcher: Optional[ContinuousBatcher] = None


def attach(batcher: ContinuousBatcher) -> ContinuousBatcher:
    """Register the process-wide batcher the HTTP routes serve from."""
    global _batcher
    with _lock:
        if _batcher is not None and _batcher is not batcher \
                and _batcher.running:
            raise RuntimeError(
                "a serving batcher is already attached; reset() first")
        _batcher = batcher
    return batcher


def get() -> Optional[ContinuousBatcher]:
    return _batcher


def get_router():
    """The attached Armada router (serving/router.py), or None.
    DELIBERATELY lazy: the router module is looked up, never imported
    — a single-replica process that never touches the router keeps
    byte-identical routes, metric families and import graph (the
    router-off invariance contract; tests/test_router.py)."""
    mod = sys.modules.get(__name__ + ".router")
    return None if mod is None else mod.get()


def replica_id() -> Optional[str]:
    """This worker's replica identity in a routed fleet (the
    supervisor's env_factory sets PTPU_REPLICA_ID), or None when the
    process is not a fleet member."""
    import os
    return os.environ.get("PTPU_REPLICA_ID")


def drain(stop: bool = False) -> dict:
    """Drain the attached batcher on command (ISSUE 17): the
    controller's ``drain`` actuator and the body behind
    ``POST /serving/drain``.  Raises RuntimeError when no batcher is
    attached — a drain that silently did nothing is exactly the
    actuator failure the controller's circuit breaker exists to
    catch."""
    b = get()
    if b is None:
        raise RuntimeError("serving.drain: no serving batcher attached")
    b.begin_drain(stop=stop)
    return {"status": "draining", "stop": bool(stop),
            "queued": b.queue_depth}


def reset():
    """Test hook (conftest): stop the attached batcher (loop thread
    JOINED), detach it from the HTTP routes; same for the router
    (probe thread joined, per-replica metric series dropped) when its
    module was ever imported."""
    global _batcher
    with _lock:
        b, _batcher = _batcher, None
    if b is not None:
        b.stop()
    mod = sys.modules.get(__name__ + ".router")
    if mod is not None:
        mod.reset()


def status_doc() -> dict:
    """The ``/serving`` route body: batcher/engine state plus SLO
    quantiles derived from the serving histograms."""
    b = get()
    doc = {
        "schema": "paddle_tpu.serving.v1",
        "time_unix": time.time(),
        "attached": b is not None,
    }
    if b is not None:
        doc.update(b.status_doc())
    r = get_router()
    if r is not None:
        doc["router"] = r.status_doc()

    def _counter_value(name, **labels):
        m = obs_metrics.REGISTRY.get(name)
        if m is None:
            return 0.0
        if labels:
            return m.labels(**labels).value
        return m.total()

    doc["tokens_generated"] = _counter_value(
        "serving_tokens_generated_total")
    doc["requests"] = {
        status: _counter_value("serving_requests_total", status=status)
        for status in ("ok", "shed", "drained", "error")}
    doc["compiles"] = _counter_value("serving_compiles_total")
    for key, hist in (("ttft_s", "serving_ttft_seconds"),
                      ("per_token_s", "serving_token_seconds"),
                      ("prefill_s", "serving_prefill_seconds"),
                      ("decode_step_s", "serving_decode_step_seconds")):
        doc[key] = histogram_quantiles(hist, [0.5, 0.99])
    return doc
