"""Supervised serving worker process.

``python -m paddle_tpu.serving.worker <port> [seed]`` builds a small
deterministic ``transformer_lm``, AOT-prepares the decode engine's
bucket grid, starts the continuous batcher and the observability HTTP
endpoint on `port` (``/serving`` status + ``POST /serving/generate``),
and serves until SIGTERM — which drains in-flight sequences at a
decode-step boundary before a clean exit 0 (the PR 2 preemption
contract applied to serving).

The PR 5 supervisor babysits this process in the chaos soak
(tests/test_serving.py slow lane): ``PTPU_CHAOS_SPEC=
"serving.decode_step=exit:..."`` hard-kills it mid-decode, the
supervisor restarts it chaos-stripped on the SAME port, and loadgen's
retrying streams ride through the capacity gap.  Model geometry is
fixed by (seed, env) so a restarted incarnation serves identical
weights.

Env knobs (all optional): PTPU_SERVING_WORKER_BATCH (decode slots,
default 4), PTPU_SERVING_WORKER_MAXLEN (default 64),
PTPU_SERVING_WORKER_BUCKETS (default "8,16").
"""
from __future__ import annotations

import os
import sys
import time
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m paddle_tpu.serving.worker <port> [seed]",
              file=sys.stderr)
        return 2
    port = int(argv[0])
    seed = int(argv[1]) if len(argv) > 1 else 7
    # this container has no reachable TPU; serving tests/soaks run on
    # CPU unless the operator says otherwise (tests/conftest.py quirk)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import paddle_tpu as pt
    from paddle_tpu import models, serving
    from paddle_tpu.framework.executor import global_scope
    from paddle_tpu.observability import server as obs_server

    max_batch = int(os.environ.get("PTPU_SERVING_WORKER_BATCH", "4"))
    max_len = int(os.environ.get("PTPU_SERVING_WORKER_MAXLEN", "64"))
    buckets = [int(b) for b in os.environ.get(
        "PTPU_SERVING_WORKER_BUCKETS", "8,16").split(",")]

    cfg = models.transformer.TransformerConfig(
        src_vocab_size=97, tgt_vocab_size=97, max_length=max_len,
        n_layer=2, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    _, _, _logits = models.transformer.build_lm_net(
        cfg, seq_len=min(max_len, 32), is_test=True,
        fused_attention=False, fused_head=False)
    exe = pt.Executor(pt.CPUPlace())
    pt.default_startup_program().random_seed = seed
    exe.run(pt.default_startup_program())

    params = serving.extract_lm_params(
        pt.default_main_program(), global_scope(), cfg)
    engine = serving.DecodeEngine(cfg, params, max_batch=max_batch,
                                  max_len=max_len,
                                  prompt_buckets=buckets, seed=seed)
    engine.prepare()
    batcher = serving.ContinuousBatcher(engine)
    batcher.start()
    serving.attach(batcher)
    batcher.install_signal_handlers()
    # drain-on-command (ISSUE 17): attaching before the HTTP server
    # comes up means POST /serving/drain — the Helmsman controller's
    # remote drain actuator — is live from the first ready line; a
    # drain directed at a worker that hasn't attached yet is a 503,
    # which the controller counts as an actuator failure
    srv = obs_server.start_http_server(port=port)
    # cold-start headline (ROADMAP item 1): process exec to "can answer
    # a request" — interpreter + imports + model build + the bucket
    # grid, which prepare() above either AOT-compiled (cold) or
    # deserialized from the persistent executable cache (warm: set
    # PTPU_JIT_CACHE_DIR / the jit_cache_dir flag, framework/
    # jit_cache.py).  On /metrics and in the bench/soak dumps; bench.py
    # gates the cold/warm pair as serving_ready_{cold,warm}_seconds.
    from paddle_tpu import observability as obs
    ready_s = time.time() - obs.process_start_unix()
    obs.metrics.gauge(
        "serving_ready_seconds",
        "Serving cold start: process start to the ready line (model "
        "build + AOT prefill-grid/decode compile included).").set(
        ready_s)
    # ready line carries the BOUND port and fleet identity (ISSUE 20):
    # a router/fleet log grep reads which replica came up where, and
    # GET /healthz reports the same truth machine-readably (the
    # "serving" section: running/draining state + queue depth)
    rid = serving.replica_id() or "0"
    print(f"SERVING_READY {srv.url} replica={rid} "
          f"port={srv.address[1]} ready_s={ready_s:.2f}", flush=True)
    try:
        while batcher.running:
            time.sleep(0.1)
    finally:
        # SIGTERM landed: the drain already finished (loop exited);
        # detach routes and release the port for a successor
        serving.reset()
        obs_server.stop_http_server()
    print("SERVING_DRAINED", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
