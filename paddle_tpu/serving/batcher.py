"""Continuous batcher + admission control over the KV-cache decode engine.

The serving plane's control loop (ISSUE 8 tentpole, part a/c): one
thread owns the :class:`~.kv_cache.DecodeEngine` and runs

    admit (fill free slots from the queue) -> decode_step -> retire

forever.  New requests are admitted AT DECODE-STEP BOUNDARIES — a
finished sequence's slot is backfilled while the other slots keep
decoding, so short requests never wait for a full batch to drain
(in-flight/continuous batching, the vLLM-style scheduling the
reference's one-shot ``AnalysisPredictor`` tier never had).

Admission control: the queue is bounded by ``serving_queue_limit``;
past it :meth:`ContinuousBatcher.submit` raises :class:`ShedError`
(HTTP 429 at the /serving/generate route) — an EXPLICIT rejection the
client can retry, never an unbounded queue or a silent drop.

SLO metering: every request carries its timing — TTFT (submit to first
token, prefill inclusive) and per-token decode latency land in
``serving_ttft_seconds`` / ``serving_token_seconds`` histograms;
queue depth / batch occupancy / tokens generated ride as gauges and
counters.  All of it is on the /metrics exposition (local and
fleet-merged) plus the /serving status route.

Resilience: the PR 2 preemption idiom applies — SIGTERM begins a DRAIN
honored at the decode-step boundary (stop admitting, shed the queue
explicitly, finish in-flight sequences, then stop); chaos sites
``serving.admit`` and ``serving.decode_step`` let the soak kill or
fault the loop deterministically (docs/RESILIENCE.md).
"""
from __future__ import annotations

import signal
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import journal as obs_journal
from ..observability import metrics as obs_metrics
from ..observability import tracectx as obs_tracectx
from ..resilience import chaos
from .kv_cache import DecodeEngine

# decode spans are flushed per CHUNK tokens (one span per token would
# bloat the store; one per request would hide mid-decode stalls)
_DECODE_CHUNK_TOKENS = 8

_m_queue_depth = obs_metrics.gauge(
    "serving_queue_depth",
    "Requests admitted but not yet prefilled into a decode slot.")
_m_occupancy = obs_metrics.gauge(
    "serving_batch_occupancy",
    "Active decode slots / serving_max_batch (0..1).")
_m_active = obs_metrics.gauge(
    "serving_active_slots", "Active decode slots (absolute).")
_m_tokens = obs_metrics.counter(
    "serving_tokens_generated_total",
    "Tokens emitted by the decode loop across all requests.")
_m_requests = obs_metrics.counter(
    "serving_requests_total",
    "Serving requests by terminal status: ok, shed (bounded-queue "
    "429), drained (rejected/aborted by SIGTERM drain), error.",
    ("status",))
_m_ttft = obs_metrics.histogram(
    "serving_ttft_seconds",
    "Time to first token: submit -> queue wait -> bucketed prefill -> "
    "first sampled token.")
_m_token_latency = obs_metrics.histogram(
    "serving_token_seconds",
    "Per-token decode latency (one decode-step dispatch, per active "
    "slot).")
_m_step = obs_metrics.histogram(
    "serving_decode_step_seconds",
    "Whole decode-step dispatch latency (all slots at once).")
_m_draining = obs_metrics.gauge(
    "serving_draining", "1 while a SIGTERM drain is in progress.")
_m_drains = obs_metrics.counter(
    "serving_drains_total",
    "SIGTERM/explicit drains honored at a decode-step boundary.")


class ShedError(RuntimeError):
    """Request rejected by admission control.  ``draining=False`` is
    the bounded-queue rejection (HTTP 429: back off, retry HERE);
    ``draining=True`` means the instance is going away (HTTP 503:
    fail over)."""

    def __init__(self, msg: str, queue_depth: int = 0,
                 draining: bool = False):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.draining = draining


class ServingRequest:
    """One generation request and its lifecycle/timing record."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "eos_id",
                 "tokens", "status", "error", "submit_t", "first_token_t",
                 "finish_t", "_done", "trace", "submit_unix", "admit_t",
                 "_chunk_t0", "_chunk_unix", "_chunk_tokens")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 temperature: float, eos_id: Optional[int],
                 trace: Optional[obs_tracectx.TraceContext] = None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.tokens: List[int] = []
        self.status = "pending"       # -> ok | error | drained
        self.error: Optional[str] = None
        self.submit_t = time.perf_counter()
        self.submit_unix = time.time()
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self._done = threading.Event()
        # request X-ray (observability/tracectx.py): the trace this
        # request's queue-wait/prefill/decode spans land under.  Minted
        # at submit() when tracing is on and none was handed in (the
        # HTTP route passes the client's traceparent-derived context).
        self.trace = trace
        self.admit_t: Optional[float] = None
        self._chunk_t0: Optional[float] = None
        self._chunk_unix: Optional[float] = None
        self._chunk_tokens = 0

    @property
    def trace_id(self) -> Optional[str]:
        return self.trace.trace_id if self.trace is not None else None

    # -- batcher side -------------------------------------------------------
    def _span(self, name: str, start_unix: float, start_perf: float,
              dur: float, kind: str, **attrs):
        if self.trace is None:
            return
        obs_tracectx.record_span(
            name, self.trace.trace_id, obs_tracectx.new_span_id(),
            self.trace.span_id, start_unix, start_perf, dur, kind=kind,
            attrs=attrs or None)

    def _flush_decode_chunk(self, now: float):
        """Emit the accumulated decode-chunk span (a window of up to
        _DECODE_CHUNK_TOKENS tokens) — mid-decode stalls then show as a
        long chunk instead of vanishing into one request-wide span."""
        if self.trace is None or self._chunk_t0 is None \
                or self._chunk_tokens == 0:
            return
        self._span("serving.decode", self._chunk_unix, self._chunk_t0,
                   now - self._chunk_t0, "decode",
                   tokens=self._chunk_tokens)
        self._chunk_t0 = None
        self._chunk_tokens = 0

    def _note_token(self, now: float):
        if self.trace is None:
            return
        if self._chunk_t0 is None:
            self._chunk_t0 = now
            self._chunk_unix = time.time()
        self._chunk_tokens += 1
        if self._chunk_tokens >= _DECODE_CHUNK_TOKENS:
            self._flush_decode_chunk(time.perf_counter())

    def _finish(self, status: str, error: Optional[str] = None):
        if self._done.is_set():      # terminal exactly once (a stop()
            return                   # after loop exit must not recount)
        self.status = status
        self.error = error
        self.finish_t = time.perf_counter()
        _m_requests.labels(status=status).inc()
        if self.trace is not None:
            self._flush_decode_chunk(self.finish_t)
            self._span("serving.retire", time.time(), self.finish_t,
                       0.0, "marker", status=status)
            # the ROOT span: the whole request, submit -> terminal
            obs_tracectx.record_span(
                "serving.request", self.trace.trace_id,
                self.trace.span_id, None, self.submit_unix,
                self.submit_t, self.finish_t - self.submit_t,
                kind="request",
                attrs={"status": status, "tokens": len(self.tokens),
                       "prompt_len": len(self.prompt),
                       **({"error": error[:120]} if error else {})})
            self._maybe_capture_slo()
        self._done.set()

    def _maybe_capture_slo(self):
        """Flight-style capture keyed by trace id when this request
        breached the serving_p99_budget_ms SLO (TTFT or per-token) —
        the evidence survives span-store eviction and is served by
        GET /trace/<id>."""
        budget_ms = float(flags.get_flag("serving_p99_budget_ms"))
        if budget_ms <= 0 or self.status != "ok" \
                or self.first_token_t is None:
            return
        ttft_ms = (self.first_token_t - self.submit_t) * 1e3
        per_tok_ms = None
        if len(self.tokens) > 1 and self.finish_t is not None:
            per_tok_ms = ((self.finish_t - self.first_token_t)
                          / (len(self.tokens) - 1)) * 1e3
        if ttft_ms > budget_ms or (per_tok_ms is not None
                                   and per_tok_ms > budget_ms):
            obs_tracectx.capture(
                self.trace.trace_id, "slo_breach",
                budget_ms=budget_ms, ttft_ms=round(ttft_ms, 3),
                per_token_ms=None if per_tok_ms is None
                else round(per_tok_ms, 3))

    # -- client side --------------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> dict:
        """Block until terminal; returns the response document (also
        the /serving/generate body).  Raises TimeoutError if the
        request is still in flight after `timeout`."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request not finished after {timeout}s "
                f"(status {self.status})")
        ttft = (None if self.first_token_t is None
                else self.first_token_t - self.submit_t)
        total = (None if self.finish_t is None
                 else self.finish_t - self.submit_t)
        doc = {"status": self.status, "tokens": list(self.tokens),
               "n_tokens": len(self.tokens),
               "error": self.error,
               "ttft_s": ttft, "latency_s": total}
        if self.trace is not None:
            doc["trace_id"] = self.trace.trace_id
        return doc


class ContinuousBatcher:
    """Single decode loop fronting a :class:`DecodeEngine`.

    ``start()`` spawns the loop thread; ``submit()`` is thread-safe and
    returns a :class:`ServingRequest` future.  ``begin_drain()`` (or
    SIGTERM via :meth:`install_signal_handlers`) stops admission,
    sheds the queue with explicit ``drained`` responses, finishes the
    in-flight sequences and — with ``stop=True`` — exits the loop.
    """

    def __init__(self, engine: DecodeEngine,
                 queue_limit: Optional[int] = None):
        self.engine = engine
        self.queue_limit = int(
            queue_limit if queue_limit is not None
            else flags.get_flag("serving_queue_limit"))
        self._queue: List[ServingRequest] = []
        self._slots: Dict[int, ServingRequest] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._draining = False
        self._stop_after_drain = False
        # set by the SIGTERM handler INSTEAD of calling begin_drain
        # directly: a handler runs on the main thread at an arbitrary
        # bytecode boundary — possibly inside submit()'s lock — so it
        # must touch nothing but this plain flag (no locks, no Events);
        # the loop honors it at the next decode-step boundary
        self._drain_requested = False
        self._old_handlers: Dict[int, object] = {}
        self.started_t: Optional[float] = None

    # -- lifecycle ----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("batcher already running")
        self._stop = False
        self._draining = False
        self._stop_after_drain = False
        self._drain_requested = False
        self.started_t = time.time()
        self._thread = threading.Thread(
            target=self._loop, name="serving-batcher", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0):
        """Hard stop: abort in-flight requests with explicit 'drained'
        responses and join the loop thread."""
        import warnings
        self._stop = True
        self._wake.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout)
            if t.is_alive():
                # keep the reference: `running` must stay True so a
                # second loop thread can't be started over an engine
                # the wedged one still owns
                warnings.warn(
                    f"serving batcher loop did not exit within "
                    f"{timeout}s; engine may be wedged in a dispatch",
                    RuntimeWarning, stacklevel=2)
                return
        self._thread = None
        self._fail_pending("drained", "serving stopped")
        _m_draining.set(0.0)
        # lazy: goodput has a python -m CLI and must stay out of the
        # package-import graph (runpy double-import warning otherwise)
        from ..observability import goodput as obs_goodput
        obs_goodput.note_drain_end()

    def begin_drain(self, stop: bool = True):
        """SIGTERM semantics (the PR 2 preemption contract, honored at
        the decode-step boundary): no new admissions, queued requests
        get explicit 'drained' responses, active sequences finish."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._stop_after_drain = stop
        _m_draining.set(1.0)
        _m_drains.inc()
        from ..observability import goodput as obs_goodput
        obs_goodput.note_drain_begin()
        obs_flight.record("serving", "drain_begin",
                          queued=self.queue_depth,
                          active=len(self._slots))
        obs_journal.emit("serving", "drain_begin",
                         queued=self.queue_depth,
                         active=len(self._slots), stop=stop)
        self._shed_queue("drained", "serving is draining (SIGTERM)")
        self._wake.set()

    def install_signal_handlers(self):
        """SIGTERM -> drain at the next decode-step boundary, chaining
        any previous handler (the Trainer's preemption hook coexists).
        The handler itself only sets a plain flag — it may interrupt
        the main thread INSIDE one of our own lock sections, where
        calling begin_drain (or any threading primitive) would
        deadlock.  Main thread only — elsewhere this degrades to no
        signal-driven drain, like Trainer._install_preemption_handlers."""
        if threading.current_thread() is not threading.main_thread():
            return

        def _on_term(signum, frame):
            self._drain_requested = True
            old = self._old_handlers.get(signum)
            if callable(old):
                old(signum, frame)

        self._old_handlers[signal.SIGTERM] = signal.signal(
            signal.SIGTERM, _on_term)

    def restore_signal_handlers(self):
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)
        self._old_handlers.clear()

    # -- admission ----------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               eos_id: Optional[int] = None,
               trace: Optional[obs_tracectx.TraceContext] = None
               ) -> ServingRequest:
        """Admit one request (bounded queue) — raises ShedError past
        serving_queue_limit or while draining.  ``trace`` carries an
        upstream traceparent-derived context (the HTTP route); without
        one, a fresh trace is minted per request when request_tracing
        is on — EVERY admitted request has a retrievable X-ray."""
        chaos.trigger("serving.admit", ConnectionAbortedError)
        if not self.running:
            raise RuntimeError("serving batcher is not running")
        if max_new_tokens is None:
            max_new_tokens = int(flags.get_flag("serving_max_new_tokens"))
        if trace is None:
            # a CHILD of any ambient context, never the ambient context
            # itself: two submits under one traced scope must not share
            # a root span id (span-id dedupe would collapse their
            # roots); no ambient -> a fresh trace per request
            trace = obs_tracectx.start_trace(
                "serving.request", parent=obs_tracectx.current())
        req = ServingRequest(prompt, max_new_tokens, temperature, eos_id,
                             trace=trace)
        # validate NOW so a hopeless request is an error at the door,
        # not a dead slot later (bucket fit AND room to generate)
        self.engine.validate_prompt(len(req.prompt))
        with self._lock:
            # _drain_requested too (ISSUE 20 bugfix): between SIGTERM
            # landing and the next decode-step boundary the batcher is
            # already doomed — admitting here would queue-then-shed,
            # making a failing-over router (or client) WAIT on a dying
            # replica's queue instead of getting the synchronous
            # `drained` answer that triggers retry-elsewhere
            if self._draining or self._stop or self._drain_requested:
                req._finish("drained", "serving is draining")
                raise ShedError("serving is draining", self.queue_depth,
                                draining=True)
            if len(self._queue) >= self.queue_limit:
                req._finish("shed",
                            f"queue at limit {self.queue_limit}")
                raise ShedError(
                    f"serving queue at limit ({self.queue_limit})",
                    len(self._queue))
            self._queue.append(req)
            _m_queue_depth.set(len(self._queue))
        self._wake.set()
        return req

    # -- loop ---------------------------------------------------------------
    def _shed_queue(self, status: str, msg: str):
        with self._lock:
            shed, self._queue = self._queue, []
            _m_queue_depth.set(0)
        for req in shed:
            req._finish(status, msg)

    def _fail_pending(self, status: str, msg: str):
        self._shed_queue(status, msg)
        with self._lock:
            slots, self._slots = dict(self._slots), {}
        for slot, req in slots.items():
            self.engine.retire_slot(slot)
            if not req.done():
                req._finish(status, msg)
        self._publish_gauges()

    def _publish_gauges(self):
        _m_occupancy.set(self.engine.occupancy)
        _m_active.set(float(len(self._slots)))
        _m_queue_depth.set(float(len(self._queue)))

    def _admit_at_boundary(self):
        """Backfill free slots from the queue — the continuous-batching
        moment: this runs BETWEEN decode steps, never mid-dispatch."""
        while True:
            with self._lock:
                if self._draining or not self._queue:
                    return
                free = self.engine.free_slots()
                if not free:
                    return
                req = self._queue.pop(0)
                _m_queue_depth.set(len(self._queue))
                slot = free[0]
            req.admit_t = time.perf_counter()
            # X-ray: how long the request sat behind admission control
            req._span("serving.queue_wait", req.submit_unix,
                      req.submit_t, req.admit_t - req.submit_t, "queue",
                      queue_depth=len(self._queue))
            t_pf_unix, t_pf = time.time(), time.perf_counter()
            try:
                # activate the request's context for the dispatch: the
                # engine's prefill histogram gains this trace's
                # exemplar, and a lazy bucket compile inside
                # start_sequence lands INSIDE this request's timeline
                with obs_tracectx.activate(req.trace):
                    first = self.engine.start_sequence(
                        slot, req.prompt, temperature=req.temperature)
            except Exception as e:
                # the dispatch donates the K/V slabs, so ANY prefill
                # failure may have invalidated the cache for everyone
                # (XlaRuntimeError subclasses RuntimeError — exception
                # type cannot tell pre- from post-dispatch).  Validation
                # errors were already rejected at submit(), so recover
                # like a decode failure: fail in-flight requests
                # explicitly and reallocate via engine.reset()
                req._finish("error", f"prefill failed: {e!r}")
                obs_flight.record("serving", "prefill_error",
                                  error=repr(e)[:200])
                self._fail_pending_active(e)
                continue
            req.first_token_t = time.perf_counter()
            req._span("serving.prefill", t_pf_unix, t_pf,
                      req.first_token_t - t_pf, "prefill",
                      bucket=self.engine.bucket_for(len(req.prompt)),
                      slot=slot)
            from ..observability import perfscope as obs_perfscope
            if obs_perfscope.enabled():
                obs_perfscope.note_phase(
                    "serving.prefill", req.first_token_t - t_pf,
                    trace_id=(req.trace.trace_id
                              if req.trace else None))
            with obs_tracectx.activate(req.trace):
                # TTFT exemplar: the p99 bucket links to THIS trace
                _m_ttft.observe(req.first_token_t - req.submit_t)
            req.tokens.append(first)
            _m_tokens.inc()
            with self._lock:
                self._slots[slot] = req
            if self._maybe_finish(slot, req, first):
                continue
            self._publish_gauges()

    def _maybe_finish(self, slot: int, req: ServingRequest,
                      token: int) -> bool:
        full = self.engine.remaining_capacity(slot) <= 0
        hit_eos = req.eos_id is not None and token == req.eos_id
        if (len(req.tokens) >= req.max_new_tokens or hit_eos or full):
            self.engine.retire_slot(slot)
            with self._lock:
                self._slots.pop(slot, None)
            req._finish("ok")
            self._publish_gauges()
            return True
        return False

    def _loop(self):
        # try/finally: even an unexpected exception outside the decode
        # try-block (admission, bookkeeping) must not strand pending
        # requests in 'pending' — every request terminates explicitly
        try:
            self._loop_body()
        finally:
            self._fail_pending("drained", "serving loop exited")

    def _loop_body(self):
        while True:
            if self._stop:
                break
            if self._drain_requested and not self._draining:
                # SIGTERM landed since the last boundary (the handler
                # only sets the flag — see install_signal_handlers)
                self.begin_drain(stop=True)
            self._admit_at_boundary()
            with self._lock:
                active = dict(self._slots)
                drain_done = self._draining and not active
            if drain_done:
                if self._stop_after_drain:
                    obs_journal.emit("serving", "drain_complete")
                    from ..observability import goodput as obs_goodput
                    obs_goodput.note_drain_end()
                    break
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            if not active:
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            t0 = time.perf_counter()
            try:
                chaos.trigger("serving.decode_step")
                out = self.engine.decode_step()
            except Exception as e:
                # one bad step must not wedge the plane: fail the
                # in-flight requests EXPLICITLY and keep serving
                obs_flight.record("serving", "decode_step_error",
                                  error=repr(e)[:200])
                self._fail_pending_active(e)
                continue
            now = time.perf_counter()
            dt = now - t0
            _m_step.observe(dt)
            from ..observability import perfscope as obs_perfscope
            if obs_perfscope.enabled():
                # exemplar: any slot that decoded in this step links
                # the regression verdict back to a retrievable trace
                tid = next((r.trace.trace_id for r in active.values()
                            if r.trace is not None), None)
                obs_perfscope.note_phase("serving.decode_step", dt,
                                         trace_id=tid)
            for slot, tok in out.items():
                req = active.get(slot)
                if req is None:
                    continue
                req.tokens.append(tok)
                _m_tokens.inc()
                if req.trace is not None:
                    # per-slot exemplar: the per-token p99 bucket links
                    # to the trace that was decoding in that step
                    with obs_tracectx.activate(req.trace):
                        _m_token_latency.observe(dt)
                    req._note_token(t0)
                else:
                    _m_token_latency.observe(dt)
                self._maybe_finish(slot, req, tok)
            self._publish_gauges()

    def _fail_pending_active(self, exc: Exception):
        with self._lock:
            slots, self._slots = dict(self._slots), {}
        for slot, req in slots.items():
            self.engine.retire_slot(slot)
            req._finish("error", f"decode step failed: {exc!r}")
        self.engine.reset()
        self._publish_gauges()

    # -- status (the /serving route body) -----------------------------------
    def status_doc(self) -> dict:
        return {
            "running": self.running,
            "draining": self._draining,
            "queue_depth": self.queue_depth,
            "queue_limit": self.queue_limit,
            "active_slots": len(self._slots),
            "max_batch": self.engine.max_batch,
            "occupancy": round(self.engine.occupancy, 4),
            "prompt_buckets": list(self.engine.prompt_buckets),
            "max_len": self.engine.max_len,
            "started_unix": self.started_t,
        }
