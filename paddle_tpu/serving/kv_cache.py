"""Decode-loop KV cache for the transformer LM (serving tentpole, ISSUE 8).

The training stack runs ``build_lm_net`` as one whole-program jit — a
full O(T^2) recompute per step.  Generation that way costs a full
forward pass PER TOKEN.  This module is the serving-side twin: the same
trained weights (bound via :func:`models.transformer.lm_program_spec`)
run through an incremental decode step with pre-allocated per-layer K/V
buffers updated in place via ``lax.dynamic_update_slice`` /
scatter-``.at`` — one compiled executable advances EVERY slot of the
serving batch by one token, so the request path never traces.

Reference analog: the C-API inference tier's ``AnalysisPredictor``
held a NaiveExecutor loop per request; there was no incremental decode
at all (2018).  Here the decode state is explicit and batched:

  * K/V buffers  ``[L, B, H, T_max, d_head]`` — one slab per layer,
    every serving slot side by side, written at per-slot positions.
  * Per-slot sequence state (lengths, last token, active mask, RNG
    key, temperature) so the continuous batcher can retire a finished
    sequence and backfill its slot MID-DECODE without touching the
    other slots' caches.
  * Bucketed prompt lengths: prefill compiles once per
    ``serving_prompt_buckets`` entry at startup (``prepare()``), decode
    compiles exactly once — the compile log after startup is silent
    (no request-path recompile storm for forensics to report).
  * Greedy + temperature sampling per slot (temperature 0 = argmax,
    matching the full-recompute forward token-for-token).

AOT discipline is the Predictor's: everything is ``.lower().compile()``d
up front and only compiled executables run on the request path — a
shape drift is an ERROR, never a silent recompile.
"""
from __future__ import annotations

import contextlib
import time
import warnings
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core import flags
from ..observability import flight as obs_flight
from ..observability import metrics as obs_metrics
from ..observability import tracectx as obs_tracectx
from ..resilience import chaos

_m_compiles = obs_metrics.counter(
    "serving_compiles_total",
    "Serving-plane AOT compiles (prefill buckets + the decode step). "
    "Moves at prepare() time only; growth under load is a request-path "
    "recompile — the storm the bucket grid exists to prevent.",
    ("kind",))
_m_compile_seconds = obs_metrics.gauge(
    "serving_startup_compile_seconds",
    "Total wall time prepare() spent AOT-compiling the bucket grid "
    "and decode step.")
_m_prefill = obs_metrics.histogram(
    "serving_prefill_seconds",
    "Prompt prefill latency (one compiled bucket dispatch).")

_NEG = -1e9   # the additive mask value build_lm_net bakes into its bias


def extract_lm_params(program, scope, cfg) -> Dict[str, np.ndarray]:
    """Pull the trained LM weights out of (program, scope) keyed by the
    ROLE names of :func:`models.transformer.lm_program_spec` —
    ``emb``, ``l{i}.ln1.scale`` … ``w_head`` — the flat pytree
    :class:`DecodeEngine` binds its compiled steps to."""
    from ..models.transformer import lm_program_spec
    spec = lm_program_spec(program)
    if spec["n_layer"] != cfg.n_layer:
        raise ValueError(
            f"program has {spec['n_layer']} layers but cfg.n_layer="
            f"{cfg.n_layer}")

    def _get(name):
        v = scope.find_var(name)
        if v is None:
            raise ValueError(f"parameter {name!r} missing from scope — "
                             "run the startup program first")
        return np.asarray(v)

    params = {"emb": _get(spec["emb"]), "w_head": _get(spec["w_head"]),
              "ln_f.scale": _get(spec["ln_f"][0]),
              "ln_f.bias": _get(spec["ln_f"][1])}
    for i, lay in enumerate(spec["layers"]):
        params[f"l{i}.ln1.scale"] = _get(lay["ln1"][0])
        params[f"l{i}.ln1.bias"] = _get(lay["ln1"][1])
        params[f"l{i}.ln2.scale"] = _get(lay["ln2"][0])
        params[f"l{i}.ln2.bias"] = _get(lay["ln2"][1])
        for k in ("w_qkv", "w_o", "w_fc1", "b_fc1", "w_fc2", "b_fc2"):
            params[f"l{i}.{k}"] = _get(lay[k])
    return params


def _ln(x, scale, bias, eps=1e-5):
    """layer_norm over the trailing axis — the op's own f32 math
    (ops/nn_ops.py _layer_norm fallback; the Pallas kernel computes the
    same formula)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    return (xf - mean) * jax.lax.rsqrt(var + eps) * scale + bias


def _sample_one(logits, key, temp):
    """Greedy when temp == 0, else categorical at ``logits / temp`` —
    per slot, vmapped in the decode step."""
    key, sub = jax.random.split(key)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temp, 1e-6)
    sampled = jax.random.categorical(sub, scaled).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy), key


class DecodeEngine:
    """Batched incremental decode over ``build_lm_net`` weights.

    Slot protocol (driven by serving/batcher.py, single-threaded):

      1. ``start_sequence(slot, prompt, temperature)`` — bucketed
         prefill writes the prompt's K/V at the slot and returns the
         FIRST generated token (the TTFT token).
      2. ``decode_step()`` — one compiled dispatch appends one token to
         every active slot (inactive slots compute but are masked).
      3. ``retire_slot(slot)`` — frees the slot for backfill; its cache
         rows are simply overwritten by the next prefill.

    Cache layout: ``lengths[slot]`` tokens occupy K/V positions
    ``[0, lengths)``; ``last_token[slot]`` is the NEXT input, written
    at position ``lengths`` by the decode step before attending.
    """

    def __init__(self, cfg, params: Dict[str, np.ndarray],
                 max_batch: Optional[int] = None,
                 max_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 seed: int = 0):
        self.cfg = cfg
        self.max_batch = int(max_batch if max_batch is not None
                             else flags.get_flag("serving_max_batch"))
        self.max_len = int(max_len if max_len is not None
                           else cfg.max_length)
        if self.max_len > cfg.max_length:
            raise ValueError(f"max_len {self.max_len} exceeds the "
                             f"model's max_length {cfg.max_length}")
        if prompt_buckets is None:
            prompt_buckets = [
                int(b) for b in str(flags.get_flag(
                    "serving_prompt_buckets")).split(",") if b.strip()]
        buckets = sorted(set(int(b) for b in prompt_buckets))
        self.prompt_buckets = [b for b in buckets if b <= self.max_len]
        if not self.prompt_buckets:
            raise ValueError(
                f"no prompt bucket fits max_len={self.max_len} "
                f"(got {buckets})")
        self._params = {k: jnp.asarray(v) for k, v in params.items()}
        from ..models.transformer import position_encoding_table
        self._pos = jnp.asarray(
            position_encoding_table(cfg.max_length, cfg.d_model)
            [:self.max_len])
        self._n_head = cfg.n_head
        self._d_head = cfg.d_key
        self._scale = float(cfg.d_key) ** -0.5

        B, L = self.max_batch, cfg.n_layer
        kv_shape = (L, B, cfg.n_head, self.max_len, cfg.d_key)
        self._kv_k = jnp.zeros(kv_shape, jnp.float32)
        self._kv_v = jnp.zeros(kv_shape, jnp.float32)
        self._lengths = jnp.zeros((B,), jnp.int32)
        self._last = jnp.zeros((B,), jnp.int32)
        self._active = np.zeros((B,), bool)       # host-side slot map
        self._temps = jnp.zeros((B,), jnp.float32)
        self._keys = jnp.stack(
            [jax.random.PRNGKey(seed + i) for i in range(B)])
        # host-side prompt bucket per slot — the memscope occupancy
        # ledger aggregates waste per bucket from this
        self._slot_bucket = np.zeros((B,), np.int32)
        self._compiled_prefill: Dict[int, object] = {}
        self._compiled_step = None
        # construction-time registration (not the request path): lets
        # the memscope census claim the slabs as the serving_kv plane
        from ..observability import memscope as obs_memscope
        obs_memscope.register_kv_engine(self)

    # -- traced bodies ------------------------------------------------------
    def _layer(self, p, i, x, attend):
        """One transformer block shared by prefill and decode; the
        caller provides the attention plumbing (cache write + score
        masking differ between the two)."""
        y = _ln(x, p[f"l{i}.ln1.scale"], p[f"l{i}.ln1.bias"])
        qkv = jnp.matmul(y, p[f"l{i}.w_qkv"])
        E = self._n_head * self._d_head
        q, k, v = qkv[..., :E], qkv[..., E:2 * E], qkv[..., 2 * E:]
        ctx = attend(i, q, k, v)
        x = x + jnp.matmul(ctx, p[f"l{i}.w_o"])
        y2 = _ln(x, p[f"l{i}.ln2.scale"], p[f"l{i}.ln2.bias"])
        h = jax.nn.relu(jnp.matmul(y2, p[f"l{i}.w_fc1"])
                        + p[f"l{i}.b_fc1"])
        return x + jnp.matmul(h, p[f"l{i}.w_fc2"]) + p[f"l{i}.b_fc2"]

    def _prefill_fn(self, bucket: int):
        """Trace-time factory: prefill for one prompt bucket.  Batch of
        ONE prompt (the batcher admits at decode boundaries; prefill
        latency is one small dispatch), written into `slot`."""
        H, dh = self._n_head, self._d_head
        D = self.cfg.d_model
        causal = jnp.where(
            jnp.arange(bucket)[None, :] > jnp.arange(bucket)[:, None],
            jnp.float32(_NEG), jnp.float32(0.0))

        def run(p, kv_k, kv_v, tokens, length, slot, key, temp):
            # tokens [bucket] i32; positions beyond `length` are pad —
            # causal masking keeps them out of every row < length
            x = p["emb"][tokens] * jnp.float32(D) ** 0.5 \
                + self._pos[:bucket]

            def split_heads(t):                     # [T,H*dh]->[H,T,dh]
                return t.reshape(bucket, H, dh).transpose(1, 0, 2)

            for i in range(self.cfg.n_layer):
                def attend(li, q, k, v):
                    nonlocal kv_k, kv_v
                    kh, vh = split_heads(k), split_heads(v)
                    kv_k = jax.lax.dynamic_update_slice(
                        kv_k, kh[None, None], (li, slot, 0, 0, 0))
                    kv_v = jax.lax.dynamic_update_slice(
                        kv_v, vh[None, None], (li, slot, 0, 0, 0))
                    qh = split_heads(q)
                    s = jnp.einsum("hqd,hkd->hqk", qh, kh) * self._scale
                    w = jax.nn.softmax(s + causal[None], axis=-1)
                    ctx = jnp.einsum("hqk,hkd->hqd", w, vh)
                    return ctx.transpose(1, 0, 2).reshape(bucket, H * dh)

                x = self._layer(p, i, x, attend)
            x = _ln(x, p["ln_f.scale"], p["ln_f.bias"])
            xlast = jax.lax.dynamic_index_in_dim(
                x, length - 1, axis=0, keepdims=False)
            logits = jnp.matmul(xlast, p["w_head"])
            tok, key = _sample_one(logits, key, temp)
            return kv_k, kv_v, tok, key

        return run

    def _step_fn(self):
        """One decode step for the WHOLE slot batch: write the pending
        token's K/V at each slot's position, attend over the cache,
        sample the next token.  Inactive slots compute-and-mask (fixed
        shape, one executable)."""
        B, H, dh = self.max_batch, self._n_head, self._d_head
        D, T = self.cfg.d_model, self.max_len
        iB = jnp.arange(B)

        def run(p, kv_k, kv_v, last, lengths, active, keys, temps):
            pos = jnp.clip(lengths, 0, T - 1)
            x = p["emb"][last] * jnp.float32(D) ** 0.5 + self._pos[pos]
            valid = jnp.arange(T)[None, :] <= pos[:, None]   # [B,T]
            bias = jnp.where(valid, 0.0, _NEG)[:, None, :]   # [B,1,T]

            for i in range(self.cfg.n_layer):
                def attend(li, q, k, v):
                    nonlocal kv_k, kv_v
                    kh = k.reshape(B, H, dh)
                    vh = v.reshape(B, H, dh)
                    kv_k = kv_k.at[li, iB, :, pos, :].set(kh)
                    kv_v = kv_v.at[li, iB, :, pos, :].set(vh)
                    qh = q.reshape(B, H, dh)
                    s = jnp.einsum("bhd,bhtd->bht", qh, kv_k[li]) \
                        * self._scale
                    w = jax.nn.softmax(s + bias, axis=-1)
                    ctx = jnp.einsum("bht,bhtd->bhd", w, kv_v[li])
                    return ctx.reshape(B, H * dh)

                x = self._layer(p, i, x, attend)
            x = _ln(x, p["ln_f.scale"], p["ln_f.bias"])
            logits = jnp.matmul(x, p["w_head"])            # [B,V]
            toks, keys = jax.vmap(_sample_one)(logits, keys, temps)
            toks = jnp.where(active, toks, last)
            new_len = jnp.where(active, jnp.minimum(lengths + 1, T),
                                lengths)
            return kv_k, kv_v, toks, new_len, keys

        return run

    # -- AOT compile --------------------------------------------------------
    def _sds(self, like):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                           jnp.result_type(a)), like)

    def _persist_components(self, **extra) -> dict:
        """Stable persistent-cache key components of this engine's
        executables: model geometry + grid shape + the weight pytree's
        (name, shape, dtype) signature.  Weight VALUES are call-time
        arguments — same-geometry engines share entries; a geometry or
        build change is a clean miss."""
        comps = {"d_model": self.cfg.d_model,
                 "n_layer": self.cfg.n_layer, "n_head": self._n_head,
                 "d_head": self._d_head, "max_batch": self.max_batch,
                 "max_len": self.max_len,
                 "params": sorted((k, tuple(v.shape), str(v.dtype))
                                  for k, v in self._params.items())}
        comps.update(extra)
        return comps

    def _compile_prefill(self, bucket: int, kind: str) -> float:
        """AOT-compile one prompt bucket's prefill executable; returns
        the compile seconds.  ``kind`` labels serving_compiles_total:
        "prefill" from prepare(), "prefill_lazy" when a request-path
        miss compiled it under serving_lazy_bucket_compile — tagged
        with the triggering request's trace so the recompile shows in
        that request's own timeline.

        Persistent cache (framework/jit_cache.py): a warm replica
        deserializes the bucket's executable instead of compiling —
        serving_compiles_total stays FROZEN on that path (nothing
        compiled; jit_cache_hits_total{kind=serving_prefill} moves)."""
        from ..framework import jit_cache as pjit_cache
        tb = time.perf_counter()
        comps = khash = None
        if pjit_cache.enabled():
            comps = self._persist_components(bucket=int(bucket))
            khash = pjit_cache.entry_key("serving_prefill", comps)
            loaded = pjit_cache.load("serving_prefill", khash, comps)
            if loaded is not None:
                self._compiled_prefill[bucket] = loaded
                return time.perf_counter() - tb
        p_sds = self._sds(self._params)
        kv_sds = self._sds(self._kv_k)
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        f32 = jax.ShapeDtypeStruct((), jnp.float32)
        key_sds = self._sds(self._keys[0])
        # donate the K/V slabs: the old cache is dead the moment the
        # call returns, so XLA updates in place instead of copying two
        # [L,B,H,T,dh] buffers per dispatch
        with obs_tracectx.span("serving.compile_bucket", kind="compile",
                               bucket=bucket, lazy=(kind != "prefill")):
            self._compiled_prefill[bucket] = jax.jit(
                self._prefill_fn(bucket), donate_argnums=(1, 2)).lower(
                p_sds, kv_sds, kv_sds,
                jax.ShapeDtypeStruct((bucket,), jnp.int32),
                i32, i32, key_sds, f32).compile()
        dt = time.perf_counter() - tb
        _m_compiles.labels(kind=kind).inc()
        obs_flight.record("compile", f"serving.prefill[{bucket}]",
                          bucket=bucket, compile_kind=kind,
                          trace_id=obs_tracectx.current_trace_id())
        if khash is not None:
            pjit_cache.store("serving_prefill", khash, comps,
                             self._compiled_prefill[bucket])
        return dt

    def prepare(self) -> dict:
        """AOT-compile the full bucket grid + the decode step NOW, so
        serving startup cost is one call and the request path never
        traces.  Returns {bucket: seconds} + totals; records
        serving_compiles_total and the startup-compile gauge."""
        from ..framework import jit_cache as pjit_cache
        t0 = time.perf_counter()
        report = {}
        p_sds = self._sds(self._params)
        kv_sds = self._sds(self._kv_k)
        for bucket in self.prompt_buckets:
            if bucket in self._compiled_prefill:
                continue
            report[f"prefill_{bucket}"] = round(
                self._compile_prefill(bucket, kind="prefill"), 3)
        if self._compiled_step is None:
            tb = time.perf_counter()
            B = self.max_batch
            comps = khash = None
            if pjit_cache.enabled():
                comps = self._persist_components()
                khash = pjit_cache.entry_key("serving_decode", comps)
                loaded = pjit_cache.load("serving_decode", khash, comps)
                if loaded is not None:
                    self._compiled_step = loaded
                    report["decode_step"] = round(
                        time.perf_counter() - tb, 3)
            if self._compiled_step is None:
                self._compiled_step = jax.jit(
                    self._step_fn(), donate_argnums=(1, 2)).lower(
                    p_sds, kv_sds, kv_sds,
                    jax.ShapeDtypeStruct((B,), jnp.int32),
                    jax.ShapeDtypeStruct((B,), jnp.int32),
                    jax.ShapeDtypeStruct((B,), jnp.bool_),
                    self._sds(self._keys),
                    jax.ShapeDtypeStruct((B,), jnp.float32)).compile()
                report["decode_step"] = round(
                    time.perf_counter() - tb, 3)
                _m_compiles.labels(kind="decode_step").inc()
                obs_flight.record("compile", "serving.decode_step",
                                  batch=B)
                if khash is not None:
                    pjit_cache.store("serving_decode", khash, comps,
                                     self._compiled_step)
        total = time.perf_counter() - t0
        _m_compile_seconds.set(total)
        report["total_seconds"] = round(total, 3)
        print(f"[serving] prepared {len(self.prompt_buckets)} prompt "
              f"bucket(s) {self.prompt_buckets} x batch "
              f"{self.max_batch} in {total:.2f}s "
              f"(decode step + prefill grid AOT-compiled)")
        return report

    @staticmethod
    @contextlib.contextmanager
    def _donation_quiet():
        """Backends that cannot donate (CPU) warn per dispatch; the
        donation is intentional (in-place K/V update on TPU), the
        warning is noise — same policy as the executor's donate-feeds
        twin."""
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*donated buffers were not usable.*")
            yield

    # -- slot lifecycle -----------------------------------------------------
    def reset(self):
        """Forget all sequence state (compiled executables survive).
        The K/V slabs are REALLOCATED, not just ignored: they are
        donated into every dispatch, so a dispatch that failed midway
        (the batcher's decode-error recovery path calls reset()) may
        have invalidated the old buffers."""
        self._kv_k = jnp.zeros(self._kv_k.shape, jnp.float32)
        self._kv_v = jnp.zeros(self._kv_v.shape, jnp.float32)
        self._lengths = jnp.zeros((self.max_batch,), jnp.int32)
        self._last = jnp.zeros((self.max_batch,), jnp.int32)
        self._active[:] = False
        self._temps = jnp.zeros((self.max_batch,), jnp.float32)
        self._slot_bucket[:] = 0

    def free_slots(self) -> List[int]:
        return [i for i in range(self.max_batch) if not self._active[i]]

    def active_slots(self) -> List[int]:
        return [i for i in range(self.max_batch) if self._active[i]]

    @property
    def occupancy(self) -> float:
        return float(self._active.sum()) / float(self.max_batch)

    def add_bucket(self, bucket: int):
        """Grow the prompt-bucket grid after construction (an operator
        widening the grid on a live replica).  The new bucket compiles
        at the next prepare() — or lazily on first hit when
        serving_lazy_bucket_compile is on, attributed to the
        triggering request's trace."""
        bucket = int(bucket)
        if bucket > self.max_len:
            raise ValueError(
                f"bucket {bucket} exceeds max_len {self.max_len}")
        if bucket not in self.prompt_buckets:
            self.prompt_buckets = sorted(self.prompt_buckets + [bucket])

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.prompt_buckets[-1]}")

    def validate_prompt(self, prompt_len: int) -> int:
        """Every at-the-door rejection in one place (the batcher calls
        this BEFORE queueing, so a hopeless request errors at submit,
        not as a dead slot later): bucket fit, room to generate, AND —
        unless serving_lazy_bucket_compile allows a request-path
        compile — a PREPARED bucket.  Without that last check an
        add_bucket() not followed by prepare() would admit requests
        that then raise mid-prefill, where the batcher's donated-cache
        recovery fails every in-flight request.  Returns the bucket."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len >= self.max_len:
            raise ValueError(
                f"prompt length {prompt_len} leaves no room to "
                f"generate (max_len {self.max_len})")
        bucket = self.bucket_for(prompt_len)
        if bucket not in self._compiled_prefill \
                and not flags.get_flag("serving_lazy_bucket_compile"):
            raise ValueError(
                f"prompt bucket {bucket} is not prepared — call "
                f"prepare() (or enable serving_lazy_bucket_compile "
                f"to pay the compile on the request path)")
        return bucket

    def remaining_capacity(self, slot: int) -> int:
        """Tokens this slot can still EMIT.  The cache holds positions
        [0, max_len); a decode step at lengths == max_len - 1 writes
        the final position and still emits a valid token (whose K/V is
        never needed), so capacity is max_len - lengths, not one less."""
        return self.max_len - int(self._lengths[slot])

    def start_sequence(self, slot: int, prompt: Sequence[int],
                       temperature: float = 0.0) -> int:
        """Bucketed prefill of `prompt` into `slot`; returns the first
        generated token.  One compiled dispatch — never a trace."""
        if self._active[slot]:
            raise ValueError(f"slot {slot} is still active")
        n = len(prompt)
        bucket = self.validate_prompt(n)
        fn = self._compiled_prefill.get(bucket)
        if fn is None:
            if not flags.get_flag("serving_lazy_bucket_compile"):
                raise RuntimeError(
                    f"bucket {bucket} not prepared — call prepare() "
                    "before serving (request-path compiles are "
                    "forbidden)")
            # opt-in escape hatch: compile NOW, attributed — the span
            # lands inside the active request's X-ray timeline, so "why
            # was this one slow" answers itself with the compile bar
            self._compile_prefill(bucket, kind="prefill_lazy")
            fn = self._compiled_prefill[bucket]
        toks = np.zeros((bucket,), np.int32)
        toks[:n] = np.asarray(prompt, np.int32)
        t0 = time.perf_counter()
        with self._donation_quiet():
            self._kv_k, self._kv_v, tok, key = fn(
                self._params, self._kv_k, self._kv_v, jnp.asarray(toks),
                np.int32(n), np.int32(slot), self._keys[slot],
                np.float32(temperature))
        tok = int(tok)
        _m_prefill.observe(time.perf_counter() - t0)
        self._lengths = self._lengths.at[slot].set(n)
        self._last = self._last.at[slot].set(tok)
        self._temps = self._temps.at[slot].set(float(temperature))
        self._keys = self._keys.at[slot].set(key)
        self._active[slot] = True
        self._slot_bucket[slot] = bucket
        from ..observability import memscope as obs_memscope
        if obs_memscope.enabled():
            obs_memscope.note_kv(self)
        return tok

    def retire_slot(self, slot: int):
        self._active[slot] = False
        from ..observability import memscope as obs_memscope
        if obs_memscope.enabled():
            obs_memscope.note_kv(self)

    def decode_step(self) -> Dict[int, int]:
        """Advance every active slot one token (ONE compiled dispatch);
        returns {slot: token}.  Slots whose cache is full are excluded
        (the batcher must retire them)."""
        if self._compiled_step is None:
            raise RuntimeError("call prepare() first")
        lengths = np.asarray(self._lengths)
        runnable = self._active & (lengths < self.max_len)
        if not runnable.any():
            return {}
        # chaos site: a simulated RESOURCE_EXHAUSTED at the serving
        # dispatch — the shared memory.alloc catalog entry; memscope
        # (when on) freezes the census into a flight bundle first
        try:
            chaos.trigger("memory.alloc")
        except chaos.InjectedFault:
            from ..observability import memscope as obs_memscope
            if obs_memscope.enabled():
                obs_memscope.note_alloc_failure("serving.decode_step",
                                                label="serving.decode")
            raise
        active = jnp.asarray(runnable)
        with self._donation_quiet():
            self._kv_k, self._kv_v, toks, self._lengths, self._keys = \
                self._compiled_step(
                    self._params, self._kv_k, self._kv_v, self._last,
                    self._lengths, active, self._keys, self._temps)
        self._last = toks
        host = np.asarray(toks)
        from ..observability import memscope as obs_memscope
        if obs_memscope.enabled():
            obs_memscope.note_kv(self)
        return {int(i): int(host[i]) for i in np.where(runnable)[0]}
