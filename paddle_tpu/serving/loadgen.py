"""Serving load generator — the soak headline (ISSUE 8 part d).

Same shape as ``resilience/soak.py``: N concurrent CLOSED-LOOP client
streams (each waits for its response before issuing the next request)
drive a batcher-fronted LM and the run reports p50/p99 TTFT and
per-token latency, token throughput, and a full admission ledger —
every attempt ends as ``ok``, an explicit ``shed`` (429), or an
explicit ``error``; nothing is silently lost.  The chaos variant
(tests/test_serving.py slow lane) points the HTTP submit function at a
supervised :mod:`.worker` process while ``PTPU_CHAOS_SPEC`` kills it
mid-decode — the supervisor restores capacity and the streams ride
through the gap on retries.

``python -m paddle_tpu.serving.loadgen --url http://host:port`` drives
any live serving endpoint; exit 1 when the p99 per-token budget
(``serving_p99_budget_ms`` or ``--budget-ms``) is exceeded or a stream
gave up.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..core import flags
from .batcher import ContinuousBatcher, ShedError

SubmitFn = Callable[[Sequence[int], int, float], dict]


def inproc_submit(batcher: ContinuousBatcher,
                  timeout: float = 60.0) -> SubmitFn:
    """Submit function bound to an in-process batcher."""

    def submit(prompt, max_new_tokens, temperature):
        req = batcher.submit(prompt, max_new_tokens=max_new_tokens,
                             temperature=temperature)
        return req.result(timeout=timeout)

    return submit


def http_submit(url: str, timeout: float = 60.0) -> SubmitFn:
    """Submit function for a remote worker's ``POST /serving/generate``.
    Raises ShedError on 429; ConnectionError family on a dead worker
    (the chaos-kill window) so the stream can retry."""
    import urllib.error
    import urllib.request
    endpoint = url.rstrip("/") + "/serving/generate"

    def submit(prompt, max_new_tokens, temperature):
        body = json.dumps({
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "timeout_s": timeout}).encode()
        req = urllib.request.Request(
            endpoint, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:200]
            if e.code == 429:
                raise ShedError(f"shed by server: {detail}") from e
            raise ConnectionError(
                f"HTTP {e.code} from {endpoint}: {detail}") from e
        except urllib.error.URLError as e:
            raise ConnectionError(f"{endpoint} unreachable: {e.reason}") \
                from e

    return submit


def round_robin_submit(targets: Sequence[tuple]) -> SubmitFn:
    """Round-robin over named submit fns (ISSUE 20): ``targets`` is a
    sequence of ``(name, submit_fn)``.  The returned fn carries a
    ``per_target`` ledger — per-target ok/shed/error counts — so a
    multi-replica soak can assert WHERE traffic landed, not just that
    it terminated."""
    targets = [(str(n), fn) for n, fn in targets]
    if not targets:
        raise ValueError("round_robin_submit: no targets")
    lock = threading.Lock()
    cursor = [0]
    per_target = {n: {"ok": 0, "shed": 0, "error": 0}
                  for n, _ in targets}

    def submit(prompt, max_new_tokens, temperature):
        with lock:
            name, fn = targets[cursor[0] % len(targets)]
            cursor[0] += 1
        try:
            resp = fn(prompt, max_new_tokens, temperature)
        except ShedError:
            with lock:
                per_target[name]["shed"] += 1
            raise
        except Exception:
            with lock:
                per_target[name]["error"] += 1
            raise
        with lock:
            per_target[name]["ok" if resp.get("status") == "ok"
                             else "error"] += 1
        return resp

    submit.per_target = per_target
    return submit


def http_submit_multi(urls: Sequence[str],
                      timeout: float = 60.0) -> SubmitFn:
    """Round-robin HTTP submit over several serving endpoints (the
    multi ``--url`` CLI path): each target keeps its own ledger row."""
    return round_robin_submit(
        [(u, http_submit(u, timeout)) for u in urls])


def router_submit(router, timeout: float = 60.0) -> SubmitFn:
    """Submit function bound to an IN-PROCESS Armada router
    (serving/router.py): same exception contract as http_submit so
    run_loadgen's ledger semantics carry over — 429 raises ShedError,
    any other non-200 raises ConnectionError (the stream retries)."""

    def submit(prompt, max_new_tokens, temperature):
        code, doc = router.handle({
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "timeout_s": timeout})
        if code == 429:
            raise ShedError(str(doc.get("error")),
                            int(doc.get("queue_depth") or 0))
        if code != 200:
            raise ConnectionError(f"router HTTP {code}: {doc}")
        return doc

    return submit


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


def run_loadgen(submit: SubmitFn, streams: int = 8,
                requests_per_stream: int = 4,
                prompt_len_range=(4, 14), max_new_tokens: int = 8,
                temperature: float = 0.0, vocab_size: int = 64,
                p99_budget_ms: Optional[float] = None, seed: int = 0,
                max_attempts: int = 60,
                retry_sleep_s: float = 0.1) -> dict:
    """Drive `streams` closed-loop clients; returns the soak report.

    Every attempt is accounted (ok/shed/error); a request retries shed
    and transport errors up to `max_attempts` before its stream counts
    it as given up — under chaos the retries are what carries the
    stream across a worker restart.
    """
    if p99_budget_ms is None:
        p99_budget_ms = float(flags.get_flag("serving_p99_budget_ms"))
    counts = {"issued": 0, "ok": 0, "shed": 0, "error": 0,
              "gave_up": 0, "tokens": 0, "retried_ok": 0}
    ttfts: List[float] = []
    per_token: List[float] = []
    trace_ids: List[str] = []      # X-ray: one per ok response that
    lock = threading.Lock()        # carried a trace_id (all of them,
    # when request_tracing is on) — the soak's every-request-has-a-
    # retrievable-trace check reads this

    def stream(sid: int):
        rng = np.random.RandomState(seed * 1000 + sid)
        for _ in range(requests_per_stream):
            n = int(rng.randint(prompt_len_range[0],
                                prompt_len_range[1] + 1))
            prompt = rng.randint(1, vocab_size, n).tolist()
            for attempt in range(max_attempts):
                with lock:
                    counts["issued"] += 1
                try:
                    resp = submit(prompt, max_new_tokens, temperature)
                except ShedError:
                    with lock:
                        counts["shed"] += 1
                    time.sleep(retry_sleep_s)
                    continue
                except (ConnectionError, OSError, TimeoutError):
                    with lock:
                        counts["error"] += 1
                    time.sleep(retry_sleep_s * 2)
                    continue
                if resp.get("status") != "ok":
                    with lock:
                        counts["error"] += 1
                    time.sleep(retry_sleep_s)
                    continue
                with lock:
                    counts["ok"] += 1
                    if attempt > 0:
                        # the zero-lost headline's other half: the
                        # request DID succeed after riding through a
                        # shed/kill/drain window on retries
                        counts["retried_ok"] += 1
                    counts["tokens"] += int(resp.get("n_tokens") or 0)
                    if resp.get("trace_id"):
                        trace_ids.append(str(resp["trace_id"]))
                    if resp.get("ttft_s") is not None:
                        ttfts.append(float(resp["ttft_s"]))
                    if (resp.get("latency_s") is not None
                            and resp.get("ttft_s") is not None
                            and (resp.get("n_tokens") or 0) > 1):
                        per_token.append(
                            (resp["latency_s"] - resp["ttft_s"])
                            / (resp["n_tokens"] - 1))
                break
            else:
                with lock:
                    counts["gave_up"] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=stream, args=(i,), daemon=True)
               for i in range(streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0

    p99_tok_ms = _pct(per_token, 99)
    p99_tok_ms = None if p99_tok_ms is None else p99_tok_ms * 1e3
    accounted = (counts["issued"]
                 == counts["ok"] + counts["shed"] + counts["error"])
    budget_ok = (p99_budget_ms <= 0 or p99_tok_ms is None
                 or p99_tok_ms <= p99_budget_ms)
    report = {
        "streams": streams,
        "requests_per_stream": requests_per_stream,
        "duration_s": round(dt, 3),
        "counts": dict(counts),
        "accounted": accounted,
        "tokens_per_sec": round(counts["tokens"] / dt, 2) if dt else 0.0,
        "ttft_ms": {
            "p50": None if not ttfts else _pct(ttfts, 50) * 1e3,
            "p99": None if not ttfts else _pct(ttfts, 99) * 1e3},
        "per_token_ms": {
            "p50": None if not per_token else _pct(per_token, 50) * 1e3,
            "p99": p99_tok_ms},
        "p99_budget_ms": p99_budget_ms,
        "budget_ok": budget_ok,
        "trace_ids": trace_ids,
        # per-target admission rows when the submit fn keeps them
        # (round_robin_submit / http_submit_multi)
        "per_target": {k: dict(v) for k, v in getattr(
            submit, "per_target", {}).items()} or None,
        "ok": accounted and budget_ok and counts["gave_up"] == 0
              and counts["ok"] == streams * requests_per_stream,
    }
    return report


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.loadgen",
        description="Closed-loop serving load generator; nonzero exit "
                    "on SLO-budget violation or lost requests.")
    ap.add_argument("--url", required=True, action="append",
                    help="serving endpoint root, e.g. "
                         "http://127.0.0.1:8080; repeatable — several "
                         "targets round-robin (ISSUE 20) with a "
                         "per-target row in the report")
    ap.add_argument("--streams", type=int, default=8)
    ap.add_argument("--requests", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--budget-ms", type=float, default=None,
                    help="p99 per-token budget (default: the "
                         "serving_p99_budget_ms flag)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    submit = (http_submit(args.url[0]) if len(args.url) == 1
              else http_submit_multi(args.url))
    rep = run_loadgen(submit, streams=args.streams,
                      requests_per_stream=args.requests,
                      max_new_tokens=args.max_new_tokens,
                      temperature=args.temperature,
                      vocab_size=args.vocab,
                      p99_budget_ms=args.budget_ms, seed=args.seed)
    print(json.dumps(rep, indent=1))
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(_main())
