"""Armada fleet plumbing (ISSUE 20): N supervised serving replicas
behind one health-aware router.

:class:`ServingFleet` owns the whole topology: it allocates one port
per replica, puts the PR 5 :class:`~paddle_tpu.distributed.supervisor.
Supervisor` in charge of the worker processes (crash = deterministic
backoff restart on the SAME port, chaos-stripped, so the router's
probe sees the replica RESUME at its old address), and fronts them
with a :class:`~paddle_tpu.serving.router.Router`.  ``spawn_replica``
is the grow verb Helmsman's ``spawn_replica`` action actuates: a new
port, a new supervised rank (``Supervisor.set_world_size`` via the
cmd/env factories), and a new router member that goes ready when its
worker answers /healthz.

``python -m paddle_tpu.serving.fleet_worker <port> --replicas N``
stands up the whole thing for manual poking; tests drive it
in-process (tests/test_router.py soaks).
"""
from __future__ import annotations

import os
import socket
import sys
import time
from typing import Dict, List, Optional

from ..observability import journal as obs_journal
from .router import Router


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def default_worker_env(extra: Optional[Dict[str, str]] = None
                       ) -> Dict[str, str]:
    """Subprocess env for a serving worker: CPU platform pinned, the
    test harness's fake-device XLA_FLAGS and PYTHONPATH stripped (the
    conftest discipline — 8 virtual devices leak into a child as a
    real topology), chaos disarmed unless the caller arms it."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PYTHONPATH", None)
    env.pop("PTPU_CHAOS_SPEC", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra or {})
    return env


class ServingFleet:
    """N supervised serving workers + the router that fronts them."""

    def __init__(self, n_replicas: int, seed: int = 7,
                 env: Optional[Dict[str, str]] = None,
                 replica_envs: Optional[
                     Dict[int, Dict[str, str]]] = None,
                 cwd: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 supervisor_kwargs: Optional[dict] = None,
                 router_kwargs: Optional[dict] = None):
        from ..distributed.supervisor import Supervisor
        self.seed = int(seed)
        self.ports: List[int] = [_free_port() for _ in range(n_replicas)]
        self._env = default_worker_env() if env is None else dict(env)
        replica_envs = dict(replica_envs or {})
        self.supervisor = Supervisor(
            cmds=[self._cmd(r) for r in range(n_replicas)],
            env=self._env,
            envs=[dict(self._replica_env(r), **replica_envs.get(r, {}))
                  for r in range(n_replicas)],
            cwd=cwd, log_dir=log_dir,
            cmd_factory=self._cmd, env_factory=self._replica_env,
            **(supervisor_kwargs or {}))
        self.router = Router(
            [(str(r), self.url(r)) for r in range(n_replicas)],
            **(router_kwargs or {}))

    def _cmd(self, rank: int) -> List[str]:
        while rank >= len(self.ports):
            self.ports.append(_free_port())
        return [sys.executable, "-m", "paddle_tpu.serving.worker",
                str(self.ports[rank]), str(self.seed)]

    def _replica_env(self, rank: int) -> Dict[str, str]:
        return {"PTPU_REPLICA_ID": str(rank)}

    def url(self, rank: int) -> str:
        return f"http://127.0.0.1:{self.ports[rank]}"

    @property
    def world_size(self) -> int:
        return self.supervisor.target_world

    def start(self) -> "ServingFleet":
        self.supervisor.start()
        self.router.start()
        return self

    def wait_ready(self, timeout: float = 120.0) -> "ServingFleet":
        """Block until every replica probes ready (worker cold start:
        interpreter + model build + AOT bucket grid)."""
        deadline = time.time() + timeout
        want = self.world_size
        while time.time() < deadline:
            if self.router.probe_all() >= want:
                return self
            time.sleep(0.3)
        raise RuntimeError(
            f"fleet not ready after {timeout}s: "
            f"{self.router.status_doc()['replicas']} / "
            f"supervisor={self.supervisor.status()}")

    def spawn_replica(self) -> int:
        """Grow the fleet by one replica (the Helmsman actuator): new
        port, new supervised rank, new router member.  Returns the new
        rank; the router routes to it once its probe goes ready."""
        rank = self.world_size
        self.supervisor.set_world_size(rank + 1)
        self.router.add_replica(self.url(rank), rid=str(rank))
        obs_journal.emit("router", "spawn_replica", replica=str(rank),
                         url=self.url(rank))
        return rank

    def stop(self):
        self.router.stop()
        self.supervisor.stop(kill=True)


def _main(argv: Optional[List[str]] = None) -> int:
    """Stand up a fleet + router + observability endpoint and serve
    until SIGTERM (which drains every replica, then exits)."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.fleet_worker",
        description="Armada: N supervised serving replicas behind one "
                    "health-aware router.")
    ap.add_argument("port", type=int, help="router HTTP port")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args(argv)
    from ..observability import server as obs_server
    from . import router as router_mod
    fleet = ServingFleet(args.replicas, seed=args.seed,
                         log_dir=args.log_dir).start()
    fleet.wait_ready()
    router_mod.attach(fleet.router)
    fleet.router.install_signal_handlers()
    srv = obs_server.start_http_server(port=args.port)
    print(f"ROUTER_READY {srv.url} replicas={fleet.world_size}",
          flush=True)
    try:
        while fleet.router.running:
            time.sleep(0.1)
    finally:
        router_mod.reset()
        fleet.stop()
        obs_server.stop_http_server()
    print("ROUTER_DRAINED", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
