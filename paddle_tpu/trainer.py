"""High-level Trainer with event loop and checkpoint rotation/resume.

Capability parity with /root/reference/python/paddle/fluid/contrib/trainer.py
(Trainer:169, event classes :40-99, CheckpointConfig:100, save_checkpoint:663,
load_checkpoint:763): same event-driven train loop (BeginEpoch/EndEpoch/
BeginStep/EndStep), checkpoint cadence + max_num_checkpoints rotation, and
resume-on-construct.  Distributed roles keep the reference's env contract
(_dist_transpile_if_necessary): PADDLE_TRAINING_ROLE=TRAINER with
PADDLE_TRAINERS=N self-applies the DistributeTranspiler rewrite
(c_allreduce per grad) over a data mesh; PSERVER raises with migration
guidance — gradients aggregate via collectives, not parameter servers.
An explicit mesh= argument still works without any env vars.
"""
from __future__ import annotations

import os
import signal
import time
import warnings
from typing import Callable, Dict, List, Optional, Sequence, TYPE_CHECKING

if TYPE_CHECKING:                # runtime import stays lazy (round-7
    # gotcha: CLI modules must be out of the package-import graph)
    from .observability import runlog as obs_runlog

import jax
import numpy as np

from . import io as pio
from . import optimizer as optim
from . import observability
from .core import flags
from .core.enforce import check_arg
from .framework.executor import Executor, Scope
from .framework.program import Program, program_guard
from .observability import costmodel as obs_cost
from .observability import flight as obs_flight
from .observability import journal as obs_journal
from .observability import metrics as obs_metrics
from .observability import server as obs_server
from .observability import tensorstats as obs_tensorstats
from .observability import trace as obs_trace
from .observability import tracectx as obs_tracectx
from .resilience import chaos, guard as rguard, retry as rretry

# --- telemetry: the training-loop view (throughput, loss health) --------
_m_steps = obs_metrics.counter(
    "trainer_steps_total", "Optimizer steps taken by Trainer.train.")
_m_epochs = obs_metrics.counter(
    "trainer_epochs_total", "Epochs completed by Trainer.train.")
_m_step_seconds = obs_metrics.histogram(
    "trainer_step_seconds",
    "Wall time of one Trainer train step (reader next + feed build + "
    "device step + metric fetch) — the sum the anatomy histograms "
    "below decompose.")
# step-time anatomy: input-bound vs compute-bound at a glance —
# data_wait + host + device ~= trainer_step_seconds
_m_data_wait_seconds = obs_metrics.histogram(
    "trainer_data_wait_seconds",
    "Input-pipeline wait per step: reader next() + feed build.  "
    "data_wait >> host+device = input-bound; grow reader.buffered / "
    "xmap_readers.")
_m_host_seconds = obs_metrics.histogram(
    "trainer_host_seconds",
    "Host-side dispatch time of one step (executor run, excluding "
    "device completion; first step per compiled key includes compile).")
_m_device_seconds = obs_metrics.histogram(
    "trainer_device_seconds",
    "Device time of one step: block-until-ready on the fetches plus "
    "the device->host copy of the fetched metrics.")
_m_examples_per_sec = obs_metrics.gauge(
    "trainer_examples_per_sec",
    "Smoothed training throughput in examples/s (tokens/s = this x "
    "sequence length; imgs/s for vision batches).")
_m_loss = obs_metrics.gauge(
    "trainer_loss", "Last fetched training loss.")
_m_loss_ema = obs_metrics.gauge(
    "trainer_loss_ema",
    "Exponential moving average (decay 0.9) of the training loss.")
_m_rollbacks = obs_metrics.counter(
    "trainer_rollbacks_total",
    "Bad steps recovered by restoring the newest valid checkpoint "
    "(nan_policy=rollback).")
_m_skipped = obs_metrics.counter(
    "trainer_skipped_steps_total",
    "Bad steps dropped from the health statistics "
    "(nan_policy=skip_step).")
_m_preemptions = obs_metrics.counter(
    "trainer_preemptions_total",
    "SIGTERM/SIGINT preemptions honored at a step boundary (emergency "
    "checkpoint + clean exit).")
_m_resumes = obs_metrics.counter(
    "trainer_resumes_total",
    "Trainer constructions that resumed from a checkpoint (the "
    "supervisor-restarted-worker path): params restored from the "
    "newest valid serial and the reader fast-forwarded.")
# model-agnostic cost-model gauges (observability/costmodel.py): FLOPs
# come from XLA's accounting of the compiled train step, not from any
# per-architecture formula
_m_flops_per_step = obs_metrics.gauge(
    "trainer_flops_per_step",
    "Cost-model FLOPs of one compiled train step (XLA cost_analysis, "
    "or the jaxpr analytic fallback).")
_m_tflops = obs_metrics.gauge(
    "trainer_tflops",
    "Achieved TFLOP/s of the last train step "
    "(trainer_flops_per_step / step wall time).")
_m_mfu = obs_metrics.gauge(
    "trainer_mfu",
    "Model FLOPs utilization of the last train step vs the device peak "
    "(device_peak_flops flag, or the per-platform table; unset peak = "
    "gauge not exported).")
_m_restart_to_first_step = obs_metrics.gauge(
    "restart_to_first_step_seconds",
    "Cold-start cost: process start (exec, /proc anchor) to the FIRST "
    "completed train step of this process — interpreter + imports + "
    "program build + compile + dispatch.  With the persistent "
    "executable cache armed (jit_cache_dir flag, framework/"
    "jit_cache.py) a warm restart deserializes its executables and "
    "this gauge is the measured win; bench.py publishes it as the "
    "gated restart_to_first_step_{cold,warm}_seconds rows.")
# set once per process: a second train() call is warm, not a restart
_first_step_recorded = False
_EMA_DECAY = 0.9
# device-memory sampling cadence: the live_arrays()/memory_stats() walk
# is O(resident arrays), too heavy for every step of a big model
_MEM_SAMPLE_EVERY = 8
# input-bound warning needs a few steps of evidence: the first step's
# compile dwarfs everything and short smoke runs must stay warning-free
_INPUT_BOUND_MIN_STEPS = 8
# exhaustion sentinel for the anatomy loop: a buggy reader yielding
# None must reach the feeder and fail loudly, not end the epoch early
_END_OF_DATA = object()
# ... and an absolute floor: micro-programs whose whole step is sub-ms
# have data-wait "fractions" that are all noise, not a pipeline problem
_INPUT_BOUND_MIN_WAIT_S = 0.002
# transient-save retry: absorbs flaky-filesystem OSErrors (and the
# checkpoint.save chaos site) without losing the training step
_SAVE_RETRY = rretry.RetryPolicy(name="checkpoint_save",
                                 retry_on=(OSError,))


class BeginEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id: int, step_id: int):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id: int, step_id: int, metrics: List):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """ref contrib/trainer.py:100."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 max_num_checkpoints: int = 3,
                 epoch_interval: int = 1, step_interval: int = 10):
        self.checkpoint_dir = checkpoint_dir or os.getcwd()
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))


class Trainer:
    """train_func builds (loss, [metrics...]) in the default program and
    returns either loss or [loss, metric, ...]."""

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 place=None, param_path: Optional[str] = None,
                 checkpoint_config: Optional[CheckpointConfig] = None,
                 mesh=None, accumulate_steps: int = 1):
        self.checkpoint_cfg = checkpoint_config
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.epoch_offset = 0
        # steps already completed in the resuming epoch (mid-epoch
        # checkpoints): train() fast-forwards the reader past them
        # instead of silently replaying the epoch from the top
        self.step_offset = 0
        # set when train() stopped at a step boundary for SIGTERM/SIGINT
        self.preempted = False

        from .framework import unique_name
        # fresh name namespace so a re-constructed Trainer reproduces the
        # same parameter names (checkpoint resume depends on it)
        with unique_name.guard(), \
                program_guard(self.train_program, self.startup_program):
            ret = train_func()
            if isinstance(ret, (list, tuple)):
                self.loss = ret[0]
                self.metrics = list(ret[1:])
            else:
                self.loss = ret
                self.metrics = []
            opt = optimizer_func()
            check_arg(isinstance(opt, optim.Optimizer),
                      "optimizer_func must return an Optimizer")
            opt.minimize(self.loss, accumulate_steps=accumulate_steps)
        # kept for the runlog's per-step lr field (scalar lr only; a
        # Variable-scheduled lr is the program's business, not ours)
        self._optimizer = opt
        self._runlog: Optional[obs_runlog.RunLog] = None
        self._runlog_pos = (0, 0, 0)     # (epoch, step, global_step)

        self.test_program = self.train_program.clone(for_test=True)
        mesh = self._dist_transpile_if_necessary(mesh)
        self.exe = Executor(place, scope=self.scope, mesh=mesh)
        self.exe.run(self.startup_program)

        if param_path:
            pio.load_persistables(self.exe, param_path,
                                  main_program=self.train_program)
        elif self.checkpoint_cfg:
            serial = self._latest_serial()
            if serial >= 0:
                self._load_checkpoint(serial)
                # a restarted worker (supervisor / scheduler respawn)
                # lands here: make the resume observable — which serial
                # revived it and where training will pick up
                _m_resumes.inc()
                obs_flight.record("trainer", "resumed", serial=serial,
                                  epoch=self.epoch_offset,
                                  step=self.step_offset)
                obs_journal.emit("trainer", "resumed", serial=serial,
                                 epoch=self.epoch_offset,
                                 step=self.step_offset)

    def _dist_transpile_if_necessary(self, mesh):
        """ref contrib/trainer.py _dist_transpile_if_necessary: the same
        PADDLE_* env contract, mapped to the collective plane —
        PADDLE_TRAINING_ROLE=TRAINER + PADDLE_TRAINERS=N applies the
        DistributeTranspiler rewrite (c_allreduce per grad) and runs over
        a data mesh; PSERVER has no TPU role (guidance error)."""
        role = os.environ.get("PADDLE_TRAINING_ROLE")
        if not role:
            return mesh
        if role == "PSERVER":
            raise RuntimeError(
                "PADDLE_TRAINING_ROLE=PSERVER: there are no parameter "
                "servers on TPU — run every process as TRAINER; gradients "
                "aggregate via collectives over the mesh (see "
                "transpiler/distribute_transpiler.py)")
        if role != "TRAINER":
            raise RuntimeError(
                f"unknown PADDLE_TRAINING_ROLE {role!r}: expected "
                f"TRAINER or PSERVER (ref contrib/trainer.py "
                f"_dist_transpile_if_necessary)")
        trainers = int(os.environ.get("PADDLE_TRAINERS", "1"))
        if trainers <= 1:
            return mesh
        trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        from .transpiler.distribute_transpiler import DistributeTranspiler
        t = DistributeTranspiler()
        t.transpile(trainer_id=trainer_id, program=self.train_program,
                    trainers=trainers)
        if mesh is None:
            import jax
            devices = jax.devices()
            check_arg(
                len(devices) >= trainers,
                f"PADDLE_TRAINERS={trainers} needs >= that many devices "
                f"(have {len(devices)}); pass mesh= explicitly for "
                f"multi-host layouts")
            from jax.sharding import Mesh
            mesh = Mesh(np.array(devices[:trainers]), ("data",))
        return mesh

    # -- checkpoint plumbing (ref save_checkpoint:663, rotation) ----------
    # Durable format: incubate/checkpoint.py — per-process shard files,
    # CRC32 + atomic rename (go/pserver/service.go:346 semantics), the
    # manifest as commit point.  A checkpoint torn by a crash fails its
    # CRC and resume falls back to the newest valid serial.

    def _persist_state(self):
        names = [v.name for v in self.train_program.list_vars()
                 if v.persistable]
        return {n: self.scope.find_var(n) for n in names
                if self.scope.has_var(n)}

    def _latest_serial(self) -> int:
        from .incubate import checkpoint as ckpt
        return ckpt.latest_checkpoint(self.checkpoint_cfg.checkpoint_dir)

    def _save_checkpoint(self, epoch_id: int, step_id: int,
                         epoch_complete: bool = False):
        from .incubate import checkpoint as ckpt
        # epoch-boundary checkpoints resume at epoch_id+1 / step 0;
        # mid-epoch (step-interval) checkpoints record the number of
        # COMPLETED steps in their epoch so resume fast-forwards the
        # reader to the step boundary instead of replaying the epoch
        # (the reference replays, contrib/trainer.py:663 — a correctness
        # hazard once the guard can roll back mid-epoch)
        meta = {"epoch": epoch_id + 1 if epoch_complete else epoch_id,
                "step": 0 if epoch_complete else step_id + 1}
        from .observability import goodput as obs_goodput
        t_ck = time.perf_counter() if obs_goodput.enabled() else None
        rretry.call_with_retry(
            ckpt.save_checkpoint, _SAVE_RETRY,
            self.checkpoint_cfg.checkpoint_dir, self._persist_state(),
            meta, max_keep=self.checkpoint_cfg.max_num_checkpoints)
        if t_ck is not None:
            # Timecard: the save is a boundary the step clock already
            # excludes — charge its span to checkpoint_save
            obs_goodput.note_span("checkpoint_save",
                                  time.perf_counter() - t_ck)

    def _load_checkpoint(self, serial: int):
        import jax
        from .incubate import checkpoint as ckpt
        from .observability import goodput as obs_goodput
        t_ck = time.perf_counter() if obs_goodput.enabled() else None
        state, meta, _ = ckpt.load_checkpoint(
            self.checkpoint_cfg.checkpoint_dir, serial)
        device = self.exe.place.jax_device() if self.exe.mesh is None \
            else None
        for name, arr in state.items():
            if device is not None:
                arr = jax.device_put(arr, device)
            self.scope.set_var(name, arr)
        self.epoch_offset = int(meta.get("epoch", 0))
        self.step_offset = int(meta.get("step", 0))
        if t_ck is not None:
            obs_goodput.note_span("checkpoint_restore",
                                  time.perf_counter() - t_ck)

    def _rollback(self) -> bool:
        """Restore the newest valid checkpoint (params + optimizer
        state) after a bad step; False when there is nothing to restore."""
        if not self.checkpoint_cfg:
            return False
        serial = self._latest_serial()
        if serial < 0:
            return False
        epoch_b, step_b = self.epoch_offset, self.step_offset
        self._load_checkpoint(serial)
        # mid-train rollback restores state only; the loop keeps its
        # position (the offsets matter to a FUTURE resume, not this one)
        self.epoch_offset, self.step_offset = epoch_b, step_b
        _m_rollbacks.inc()
        obs_trace.add_instant("trainer.rollback", time.perf_counter(),
                              tid=obs_trace.TRAINER_TID,
                              args={"serial": serial})
        obs_flight.record("trainer", "rollback", serial=serial)
        return True

    # -- loops -------------------------------------------------------------
    def train(self, num_epochs: int, event_handler: Callable,
              reader: Callable, feed_order: Sequence[str],
              prefetch_depth: Optional[int] = None):
        from .data_feeder import DataFeeder
        from .reader.decorator import DeviceBatch, device_prefetch
        block = self.train_program.global_block()
        feed_vars = [block.var(n) for n in feed_order]
        feeder = DataFeeder(feed_vars)
        fetch = [self.loss] + self.metrics
        step_in_total = 0
        self.preempted = False
        health = rguard.NumericGuard(ema_decay=_EMA_DECAY)
        # async input pipeline: a background thread builds feeds and
        # stages them on device (jax.device_put) while the current step
        # runs, so the step's data wait is only the NOT-hidden part and
        # trainer_device_seconds stops charging host->device copies
        depth = int(flags.get_flag("prefetch_depth")
                    if prefetch_depth is None else prefetch_depth)
        prefetch = depth > 0 and self.exe.mesh is None
        if depth > 0 and self.exe.mesh is not None:
            warnings.warn(
                "prefetch_depth ignored under a mesh: feeds must stay "
                "host-global arrays so jit's in_shardings can scatter "
                "them", RuntimeWarning, stacklevel=2)
        if prefetch:
            reader = device_prefetch(
                reader, size=depth, feeder=feeder,
                device=self.exe.place.jax_device())
        stop = self._install_preemption_handlers()
        obs_server.ensure_started()     # obs_http_port flag, 0 = off
        obs_server.note_trainer_running(True)
        # Watchtower (alert_rules_path flag, "" = off): the local alert
        # ticker watches this worker's own registry; imported lazily so
        # the alerts CLI module stays out of the package import graph
        if flags.get_flag("alert_rules_path"):
            from .observability import alerts as obs_alerts
            obs_alerts.ensure_started()
        # durable run history (runlog_path flag, "" = off): one JSONL
        # record per step — loss, lr, throughput, MFU, guard verdicts,
        # sampled tensor stats — surviving the process so two runs can
        # be diffed step-aligned (observability/runlog.py CLI)
        # imported here, not at module top: ``python -m
        # paddle_tpu.observability.runlog`` must not find the CLI module
        # pre-imported via the paddle_tpu package (runpy RuntimeWarning)
        from .observability import runlog as obs_runlog
        self._runlog = obs_runlog.open_runlog(meta={
            "event": "train_start", "num_epochs": num_epochs,
            "resume_epoch": self.epoch_offset,
            "resume_step": self.step_offset,
            "nan_policy": health.policy})
        # fresh-sample watermark: a step record embeds tensor stats only
        # when THIS step fetched a new sample (tensor_stats_interval)
        last_stats_sample = obs_tensorstats.sample_count()
        # step anatomy accumulators for the input-bound diagnosis
        anatomy = {"data_wait": 0.0, "step": 0.0, "n": 0, "warned": False,
                   "prefetch": prefetch}
        try:
            for epoch_id in range(self.epoch_offset, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                batches = iter(reader())
                start_step = 0
                if epoch_id == self.epoch_offset and self.step_offset > 0:
                    # mid-epoch resume: fast-forward past the steps the
                    # checkpoint already covers instead of replaying them
                    for _ in range(self.step_offset):
                        if next(batches, None) is None:
                            break
                    start_step = self.step_offset
                step_id = start_step - 1
                while True:
                    # --- data wait: reader next + feed build ----------
                    t0 = time.perf_counter()
                    batch = next(batches, _END_OF_DATA)
                    data_wait = time.perf_counter() - t0
                    if batch is _END_OF_DATA:
                        break
                    step_id += 1
                    begin = BeginStepEvent(epoch_id, step_id)
                    # user handler time is neither data wait nor
                    # host/device: excluded from the step clock so the
                    # anatomy sum ~= trainer_step_seconds stays true
                    th0 = time.perf_counter()
                    event_handler(begin)
                    handler_s = time.perf_counter() - th0
                    if isinstance(batch, DeviceBatch):
                        # prefetched: feed already built AND on device;
                        # its buffers are single-use -> donate them
                        feed = batch.feed
                        n_examples = batch.size
                        donate = True
                    else:
                        tf = time.perf_counter()
                        feed = feeder.feed(batch)
                        data_wait += time.perf_counter() - tf
                        n_examples = len(batch)
                        donate = False
                    if obs_tensorstats.enabled():
                        # stamp the checkpoint-resumable position onto
                        # any sample this dispatch lands (fleet rows
                        # must align across worker restarts)
                        obs_tensorstats.note_position(epoch_id, step_id)
                    # request-X-ray twin of the serving plane: every
                    # step gets its own trace id; the executor's
                    # dispatch span and any compile it triggers land
                    # inside it (None when request_tracing is off)
                    step_ctx = obs_tracectx.start_trace("trainer.step")
                    with obs_tracectx.activate(step_ctx), \
                            chaos.fault_point("trainer.step"):
                        # --- host: dispatch without blocking ----------
                        th = time.perf_counter()
                        if begin.fetch_metrics:
                            fetched = self.exe.run(self.train_program,
                                                   feed=feed,
                                                   fetch_list=fetch,
                                                   return_numpy=False,
                                                   donate_feeds=donate)
                        else:
                            self.exe.run(self.train_program, feed=feed,
                                         fetch_list=[],
                                         donate_feeds=donate)
                            fetched = []
                        host_s = time.perf_counter() - th
                        # --- device: block-until-ready + D2H copy ----
                        td = time.perf_counter()
                        if fetched:
                            jax.block_until_ready(fetched)
                            metrics = [self.exe.fetch_numpy(v)
                                       for v in fetched]
                        else:
                            metrics = []
                        device_s = time.perf_counter() - td
                    metrics = chaos.poison("trainer.step", metrics)
                    dt = time.perf_counter() - t0 - handler_s
                    _m_steps.inc()
                    self._note_first_step()
                    with obs_tracectx.activate(step_ctx):
                        # step-latency exemplars link the histogram's
                        # slow buckets back to this step's trace
                        _m_step_seconds.observe(dt)
                        _m_data_wait_seconds.observe(data_wait)
                        _m_host_seconds.observe(host_s)
                        if fetched:
                            # no-fetch steps (begin.fetch_metrics=False)
                            # never block on the device; recording
                            # their ~0 would drown the real device
                            # distribution
                            _m_device_seconds.observe(device_s)
                    if step_ctx is not None:
                        self._record_step_spans(
                            step_ctx, epoch_id, step_id, t0, dt,
                            data_wait, th, host_s, td, device_s)
                    obs_trace.add_span("trainer.data_wait", t0, data_wait,
                                       tid=obs_trace.TRAINER_TID,
                                       cat="trainer")
                    obs_trace.add_span("trainer.host", th, host_s,
                                       tid=obs_trace.TRAINER_TID,
                                       cat="trainer")
                    obs_trace.add_span("trainer.device", td, device_s,
                                       tid=obs_trace.TRAINER_TID,
                                       cat="trainer")
                    obs_server.note_trainer_step()
                    self._note_anatomy(anatomy, data_wait, dt)
                    if dt > 0:
                        _m_examples_per_sec.set(n_examples / dt)
                        self._record_mfu(dt)
                    raw_loss = None
                    guard_verdict = None
                    self._runlog_pos = (epoch_id, step_id, step_in_total)
                    self._step_trace_id = (step_ctx.trace_id
                                           if step_ctx else None)
                    # lazy import: perfscope has a `python -m` CLI,
                    # and eager package-graph imports trip runpy's
                    # sys.modules warning (the runlog idiom)
                    from .observability import perfscope \
                        as obs_perfscope
                    if obs_perfscope.enabled():
                        # roofline + regression watch per step: the
                        # cost is the cached analytic view (no extra
                        # compile), the anatomy the measured split
                        obs_perfscope.note_step(
                            "trainer.step", device_s=device_s,
                            data_wait_s=data_wait, host_s=host_s,
                            wall_s=dt,
                            cost=self.exe.last_run_cost(
                                prefer_analytic=True),
                            trace_id=self._step_trace_id)
                    from .observability import goodput as obs_goodput
                    if obs_goodput.enabled():
                        # Timecard: the same measured anatomy
                        # partitions this step's wall into
                        # input_wait/compute/idle chip-seconds
                        obs_goodput.note_step(
                            data_wait_s=data_wait, host_s=host_s,
                            device_s=device_s, wall_s=dt)
                    if metrics:
                        raw_loss = loss_val = \
                            float(np.mean(np.asarray(metrics[0])))
                        if not self._guard_step(health, loss_val):
                            metrics = []    # unhealthy: keep it out of
                            loss_val = None  # EMA/gauges and the event
                            guard_verdict = health.last_verdict
                    if metrics:
                        _m_loss.set(loss_val)
                        # the guard's EMA (healthy steps only, decay
                        # _EMA_DECAY) is the single "expected loss"
                        _m_loss_ema.set(health.ema)
                    last_stats_sample = self._runlog_step(
                        health, epoch_id, step_id, step_in_total, dt,
                        n_examples, raw_loss, guard_verdict,
                        last_stats_sample)
                    if step_in_total % _MEM_SAMPLE_EVERY == 0:
                        # one measurement path: the legacy watermark
                        # gauges AND (flag on) the memscope per-plane
                        # census + ticker arm ride this same call
                        observability.record_device_memory()
                    obs_trace.add_instant(
                        "trainer.step", t0, tid=obs_trace.TRAINER_TID,
                        args={"epoch": epoch_id, "step": step_id})
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                    step_in_total += 1
                    saved = (self.checkpoint_cfg and step_in_total %
                             self.checkpoint_cfg.step_interval == 0)
                    if saved:
                        self._save_checkpoint(epoch_id, step_id)
                    if stop["signum"] is not None:
                        # step boundary: durable state, clean exit — the
                        # preemption contract (SIGTERM from the scheduler)
                        self._emergency_stop(epoch_id, step_id, stop,
                                             already_saved=saved)
                        return
                _m_epochs.inc()
                event_handler(EndEpochEvent(epoch_id))
                saved = (self.checkpoint_cfg and (epoch_id + 1) %
                         self.checkpoint_cfg.epoch_interval == 0)
                if saved:
                    self._save_checkpoint(epoch_id, 0, epoch_complete=True)
                if stop["signum"] is not None:
                    self._emergency_stop(epoch_id + 1, -1, stop,
                                         already_saved=saved)
                    return
        except (rguard.BadStepError, rguard.CircuitBreakerOpen):
            raise               # flight bundle already dumped at the trip
        except Exception as e:
            # post-mortem artifact for ANY uncaught training failure:
            # recent events + metrics + cost summaries, one JSON bundle
            obs_flight.dump("trainer_exception",
                            extra={"error": repr(e)[:500]})
            raise
        finally:
            if self._runlog is not None:
                self._runlog.write(kind="meta", event="train_end",
                                   preempted=self.preempted)
                self._runlog.close()
                self._runlog = None
            obs_server.note_trainer_running(False)
            self._restore_preemption_handlers(stop)

    def _note_anatomy(self, anatomy: Dict, data_wait: float, dt: float):
        """Accumulate the step anatomy and warn ONCE per train() when
        the input pipeline dominates: cumulative data-wait above
        ``input_bound_warn_fraction`` of cumulative step time after
        enough steps for the evidence to mean something.

        Under the device-prefetch pipeline the measured data_wait is
        already the OVERLAPPED wait — only the time the prefetch queue
        could not hide (a hidden reader costs ~0 here, so a fully
        overlapped pipeline stays quiet); the advice then is to deepen
        the pipeline, not to enable it."""
        anatomy["data_wait"] += data_wait
        anatomy["step"] += dt
        anatomy["n"] += 1
        frac = float(flags.get_flag("input_bound_warn_fraction"))
        if (frac > 0 and not anatomy["warned"]
                and anatomy["n"] >= _INPUT_BOUND_MIN_STEPS
                and anatomy["step"] > 0
                and anatomy["data_wait"]
                > _INPUT_BOUND_MIN_WAIT_S * anatomy["n"]
                and anatomy["data_wait"] > frac * anatomy["step"]):
            anatomy["warned"] = True
            pct = 100.0 * anatomy["data_wait"] / anatomy["step"]
            if anatomy.get("prefetch"):
                what = ("un-hidden input wait (reader slower than the "
                        "device even with async device prefetch)")
                fix = ("grow prefetch_depth, parallelize decode "
                       "(xmap_readers) or move it off the training host")
            else:
                what = "data wait (reader next + feed build)"
                fix = ("enable async device prefetch (prefetch_depth "
                       "flag / reader.device_prefetch) or grow "
                       "reader.buffered()/xmap_readers parallelism")
            warnings.warn(
                f"trainer is input-bound: {what} is {pct:.0f}% of step "
                f"time over {anatomy['n']} steps (threshold "
                f"{100 * frac:.0f}%) — {fix}", RuntimeWarning,
                stacklevel=3)

    def _note_first_step(self):
        """Publish restart_to_first_step_seconds ONCE per process —
        the cold-start headline number (ROADMAP item 1): exec() to the
        first completed optimizer step, compile included."""
        global _first_step_recorded
        if _first_step_recorded:
            return
        _first_step_recorded = True
        cold = time.time() - observability.process_start_unix()
        _m_restart_to_first_step.set(cold)
        obs_flight.record("trainer", "first_step",
                          restart_to_first_step_seconds=round(cold, 3))
        if self._runlog is not None:
            self._runlog.write(kind="meta", event="first_step",
                               restart_to_first_step_seconds=cold)

    def _record_step_spans(self, step_ctx, epoch_id, step_id, t0, dt,
                           data_wait, th, host_s, td, device_s):
        """One X-ray trace per train step: the root span plus the
        data-wait/host/device anatomy as children — the same split the
        chrome-trace lanes carry, now addressable by trace id
        (GET /trace/<id>, the xray CLI)."""
        now_unix = time.time()
        root = step_ctx
        def child(name, start_perf, dur, kind):
            obs_tracectx.record_span(
                name, root.trace_id, obs_tracectx.new_span_id(),
                root.span_id, now_unix - (time.perf_counter()
                                          - start_perf),
                start_perf, dur, kind=kind)
        child("trainer.data_wait", t0, data_wait, "input")
        child("trainer.host", th, host_s, "dispatch")
        child("trainer.device", td, device_s, "device")
        obs_tracectx.record_span(
            "trainer.step", root.trace_id, root.span_id, None,
            now_unix - (time.perf_counter() - t0), t0, dt,
            kind="step", attrs={"epoch": epoch_id, "step": step_id})

    # -- resilience plumbing (resilience/, docs/RESILIENCE.md) -------------
    def _record_mfu(self, dt: float):
        """Export the cost-model MFU/TFLOPs gauges for one step.  FLOPs
        come from the cost of the program the step ACTUALLY ran (the
        executor's last compiled program — correct across mid-train
        recompiles, e.g. a final partial batch), computed lazily once
        per compiled program (cost_model flag; prefer_analytic = one
        cheap abstract trace, not a second XLA compile; dot/conv FLOPs
        are exact either way).  Model-agnostic — no per-architecture
        formula."""
        cost = self.exe.last_run_cost(prefer_analytic=True)
        flops = float(cost.flops) if cost else 0.0
        if flops <= 0:
            return
        _m_flops_per_step.set(flops)
        fps = flops / dt
        _m_tflops.set(fps / 1e12)
        peak = obs_cost.device_peak_flops()
        if peak > 0:
            _m_mfu.set(fps / peak)

    def _lr_value(self) -> Optional[float]:
        lr = getattr(getattr(self, "_optimizer", None), "_lr_input", None)
        return float(lr) if isinstance(lr, (int, float)) else None

    def _runlog_step(self, health, epoch_id, step_id, global_step, dt,
                     n_examples, raw_loss, guard_verdict,
                     last_stats_sample: int) -> int:
        """Append one per-step record to the run history (no-op when
        the runlog is off).  Returns the tensorstats sample watermark so
        stats rows land only on the step that actually fetched them."""
        if self._runlog is None:
            return last_stats_sample
        rec = {"kind": "step", "epoch": epoch_id, "step": step_id,
               "global_step": global_step, "step_seconds": dt,
               "lr": self._lr_value()}
        if getattr(self, "_step_trace_id", None):
            # the durable history links each step to its X-ray trace
            rec["trace_id"] = self._step_trace_id
        if dt > 0:
            rec["examples_per_sec"] = n_examples / dt
        if raw_loss is not None:
            rec["loss"] = raw_loss
        if guard_verdict is None and health.ema is not None \
                and raw_loss is not None:
            rec["loss_ema"] = health.ema
        if guard_verdict is not None:
            rec["guard"] = guard_verdict
            rec["attribution"] = health.last_attribution
        mfu = _m_mfu.value
        if mfu > 0:
            rec["mfu"] = mfu
        tflops = _m_tflops.value
        if tflops > 0:
            rec["tflops"] = tflops
        sample = obs_tensorstats.sample_count()
        if sample != last_stats_sample:
            rec["stats"] = obs_tensorstats.fleet_row()
        self._runlog.write(**rec)
        return sample

    def _write_guard_record(self, health, loss_val,
                            breaker: bool = False):
        """Guard trips get their own runlog record — written BEFORE the
        policy raises, so the fatal step's verdict and attribution are
        in the durable history, not just the flight bundle."""
        if self._runlog is None:
            return
        epoch_id, step_id, global_step = self._runlog_pos
        self._runlog.write(
            kind="guard", epoch=epoch_id, step=step_id,
            global_step=global_step, verdict=health.last_verdict,
            loss=float(loss_val), policy=health.policy,
            attribution=health.last_attribution,
            consecutive_bad=health.consecutive_bad,
            circuit_breaker=bool(breaker))

    def _guard_step(self, health: "rguard.NumericGuard",
                    loss_val: float) -> bool:
        """Apply the numeric-guard policy to one fetched loss.  True =
        healthy; False = bad step absorbed (skip/rollback).  Raises on
        policy 'raise' and always on an open circuit breaker."""
        try:
            verdict = health.observe(loss_val)  # raises CircuitBreakerOpen
        except rguard.CircuitBreakerOpen:
            self._write_guard_record(health, loss_val, breaker=True)
            raise
        if verdict == rguard.OK:
            return True
        self._write_guard_record(health, loss_val)
        # first-bad-layer attribution (observability/tensorstats.py):
        # every raise/skip/rollback line names the earliest variable
        # that went NaN/Inf — or 'unattributed(enable tensor_stats)'
        attr = health.last_attribution
        if health.policy == "raise":
            obs_flight.dump("numeric_guard",
                            extra={"verdict": verdict, "loss": loss_val,
                                   "attribution": attr})
            raise rguard.BadStepError(
                f"numeric guard: {verdict} loss {loss_val!r} [{attr}] "
                f"(nan_policy=raise)")
        if health.policy == "rollback":
            if not self._rollback():
                obs_flight.dump("numeric_guard",
                                extra={"verdict": verdict,
                                       "loss": loss_val,
                                       "attribution": attr,
                                       "rollback": "no valid checkpoint"})
                raise rguard.BadStepError(
                    f"numeric guard: {verdict} loss {loss_val!r} "
                    f"[{attr}] and no valid checkpoint to roll back to")
            warnings.warn(
                f"numeric guard: {verdict} loss {loss_val!r} [{attr}] — "
                f"rolled back to the newest valid checkpoint "
                f"(nan_policy=rollback)", RuntimeWarning, stacklevel=3)
        else:
            _m_skipped.inc()
            warnings.warn(
                f"numeric guard: {verdict} loss {loss_val!r} [{attr}] — "
                f"step dropped from the health statistics "
                f"(nan_policy=skip_step)", RuntimeWarning, stacklevel=3)
        return False

    def _install_preemption_handlers(self) -> Dict:
        """SIGTERM/SIGINT set a flag honored at the next step boundary
        (emergency checkpoint + clean exit) — the preemption-notice
        contract of every TPU/Borg-style scheduler.  Returns the stop
        token; signal handlers only exist in the main thread, so
        elsewhere this degrades to no preemption handling."""
        stop: Dict = {"signum": None, "old": {}}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                stop["old"][sig] = signal.signal(
                    sig, lambda signum, frame: stop.update(signum=signum))
            except ValueError:      # not the main thread
                break
        return stop

    def _restore_preemption_handlers(self, stop: Dict):
        for sig, old in stop["old"].items():
            signal.signal(sig, old)

    def _emergency_stop(self, epoch_id: int, step_id: int, stop: Dict,
                        already_saved: bool = False):
        _m_preemptions.inc()
        self.preempted = True
        # the boundary just checkpointed this exact state: a duplicate
        # save would only evict an older serial from the rotation window
        if self.checkpoint_cfg and not already_saved:
            if step_id < 0:
                self._save_checkpoint(epoch_id - 1, 0,
                                      epoch_complete=True)
            else:
                self._save_checkpoint(epoch_id, step_id)
        obs_trace.add_instant(
            "trainer.preempted", time.perf_counter(),
            tid=obs_trace.TRAINER_TID,
            args={"signum": stop["signum"], "epoch": epoch_id,
                  "step": step_id})
        obs_flight.record("trainer", "preempted",
                          signum=stop["signum"], epoch=epoch_id,
                          step=step_id)
        obs_journal.emit("trainer", "preempted", signum=stop["signum"],
                         epoch=epoch_id, step=step_id)
        obs_flight.dump("preemption",
                        extra={"signum": stop["signum"],
                               "epoch": epoch_id, "step": step_id})

    def test(self, reader: Callable, feed_order: Sequence[str]):
        from .data_feeder import DataFeeder
        fetch = [self.loss] + self.metrics
        # Evaluation must be side-effect free: the for_test clone still
        # contains the backward + optimizer (+ grad-accumulation) ops, so
        # running it whole would TRAIN on the test set and corrupt the
        # shared scope.  Prune to the forward slice that produces the
        # fetches (the reference prunes in clone(for_test); here prune()
        # needs the feed names, which arrive per call).
        key = tuple(feed_order)
        if getattr(self, "_test_pruned_key", None) != key:
            self._test_pruned = self.test_program.prune(
                key, [f.name for f in fetch])
            self._test_pruned_key = key
        test_prog = self._test_pruned
        block = test_prog.global_block()
        feed_vars = [block.var(n) for n in feed_order]
        feeder = DataFeeder(feed_vars)
        totals = None
        count = 0
        for batch in reader():
            vals = self.exe.run(test_prog,
                                feed=feeder.feed(batch), fetch_list=fetch)
            vals = [np.asarray(v) for v in vals]
            totals = vals if totals is None else [
                t + v for t, v in zip(totals, vals)]
            count += 1
        check_arg(count > 0, "test reader yielded no batches")
        return [t / count for t in totals]

    def save_params(self, param_path: str):
        pio.save_persistables(self.exe, param_path,
                              main_program=self.train_program)

    def save_inference_model(self, param_path: str,
                             feeded_var_names: Sequence[str],
                             target_vars: Sequence):
        pio.save_inference_model(param_path, feeded_var_names, target_vars,
                                 self.exe, main_program=self.train_program)

    def stop(self):
        self.exe.close()
