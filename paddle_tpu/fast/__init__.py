"""Native (C++) runtime bindings: recordio + async data loader.

ctypes binding to native/libpaddle_tpu_native.so (built by `make -C
native/`); pybind11 is not in this image, so the ABI is plain C (see
native/recordio.cc).  `available()` gates callers; paddle_tpu/recordio.py is
the pure-Python fallback with the identical on-disk format (the two are
cross-tested in tests/test_recordio.py).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, List, Optional, Sequence

_LIB_PATH = os.path.join(os.path.dirname(__file__),
                         "libpaddle_tpu_native.so")
_lib = None
_load_failed = False   # cache build/load failure: never retry the compile


def _try_build() -> bool:
    native_dir = os.path.join(os.path.dirname(__file__), "..", "..",
                              "native")
    if not os.path.isdir(native_dir):
        return False
    try:
        subprocess.run(["make", "-C", native_dir], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _load():
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    if not os.path.exists(_LIB_PATH) and not _try_build():
        _load_failed = True
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.rio_writer_write.restype = ctypes.c_int
    lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64]
    lib.rio_writer_close.restype = ctypes.c_int
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_scanner_open.restype = ctypes.c_void_p
    lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
    lib.rio_scanner_next.restype = ctypes.c_int64
    lib.rio_scanner_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64]
    lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
    lib.loader_create.restype = ctypes.c_void_p
    lib.loader_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_int]
    lib.loader_next.restype = ctypes.c_int64
    lib.loader_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint64]
    lib.loader_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


class NativeRecordIOWriter:
    def __init__(self, path: str, max_chunk_records: int = 1000):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.rio_writer_open(path.encode(), max_chunk_records)
        if not self._h:
            raise IOError(f"cannot open {path!r} for writing")

    def write(self, record: bytes):
        if self._lib.rio_writer_write(self._h, record, len(record)) != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            if self._lib.rio_writer_close(self._h) != 0:
                raise IOError("recordio flush failed")
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def native_scan(path: str) -> Iterator[bytes]:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    h = lib.rio_scanner_open(path.encode())
    if not h:
        raise IOError(f"cannot open {path!r}")
    buf_len = 1 << 20
    buf = ctypes.create_string_buffer(buf_len)
    try:
        while True:
            n = lib.rio_scanner_next(h, buf, buf_len)
            if n == 0:
                break
            if n == -1:
                buf_len *= 2
                buf = ctypes.create_string_buffer(buf_len)
                continue
            yield buf.raw[:n]
    finally:
        lib.rio_scanner_close(h)


class AsyncDataLoader:
    """Multithreaded native prefetch over recordio shards; iterate to get
    raw record bytes (order is nondeterministic across shards)."""

    def __init__(self, files: Sequence[str], num_threads: int = 4,
                 queue_capacity: int = 256):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        joined = "\n".join(files).encode()
        self._h = lib.loader_create(joined, num_threads, queue_capacity)
        if not self._h:
            raise IOError("loader_create failed")

    def __iter__(self):
        buf_len = 1 << 20
        buf = ctypes.create_string_buffer(buf_len)
        while True:
            n = self._lib.loader_next(self._h, buf, buf_len)
            if n == 0:
                break
            if n < 0:
                buf_len = max(buf_len * 2, -n)
                buf = ctypes.create_string_buffer(buf_len)
                continue
            yield buf.raw[:n]

    def close(self):
        if self._h:
            self._lib.loader_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
