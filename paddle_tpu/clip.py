"""Gradient clipping (ref python/paddle/fluid/clip.py:
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
set_gradient_clip).  Applied as program ops on the @GRAD vars between
autodiff and the optimizer updates."""
from __future__ import annotations

from typing import List, Tuple

from .framework.program import Parameter, Program, Variable

_clip_attr_name = "__gradient_clip__"


class BaseGradientClipAttr:
    def append_clip_ops(self, block, param_grads):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def append_clip_ops(self, block, param_grads):
        for p, g in param_grads:
            block.append_op("clip", {"X": [g.name]}, {"Out": [g.name]},
                            {"min": self.min, "max": self.max})


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def append_clip_ops(self, block, param_grads):
        for p, g in param_grads:
            block.append_op("clip_by_norm", {"X": [g.name]},
                            {"Out": [g.name]},
                            {"max_norm": self.clip_norm})


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def append_clip_ops(self, block, param_grads):
        sq_names = []
        for p, g in param_grads:
            sq = f"{g.name}.sq_l2"
            block.create_var(name=sq, shape=[], dtype="float32",
                             stop_gradient=True)
            block.append_op("squared_l2_norm", {"X": [g.name]},
                            {"Out": [sq]}, {})
            sq_names.append(sq)
        gsum = "global_norm.sq_sum"
        block.create_var(name=gsum, shape=[], dtype="float32",
                         stop_gradient=True)
        block.append_op("sum", {"X": sq_names}, {"Out": [gsum]}, {})
        gnorm = "global_norm.value"
        block.create_var(name=gnorm, shape=[], dtype="float32",
                         stop_gradient=True)
        block.append_op("sqrt", {"X": [gsum]}, {"Out": [gnorm]}, {})
        # scale = clip_norm / max(global_norm, clip_norm)
        denom = "global_norm.denom"
        block.create_var(name=denom, shape=[], dtype="float32",
                         stop_gradient=True)
        cn = "global_norm.clip"
        if not block.has_var(cn):
            block.create_var(name=cn, shape=[], dtype="float32",
                             stop_gradient=True)
        block.append_op("fill_constant", {}, {"Out": [cn]},
                        {"shape": [], "dtype": "float32",
                         "value": self.clip_norm})
        block.append_op("elementwise_max", {"X": [gnorm], "Y": [cn]},
                        {"Out": [denom]}, {"axis": -1})
        factor = "global_norm.factor"
        block.create_var(name=factor, shape=[], dtype="float32",
                         stop_gradient=True)
        block.append_op("elementwise_div", {"X": [cn], "Y": [denom]},
                        {"Out": [factor]}, {"axis": -1})
        for p, g in param_grads:
            block.append_op("elementwise_mul", {"X": [g.name],
                                                "Y": [factor]},
                            {"Out": [g.name]}, {"axis": -1})


def set_gradient_clip(clip: BaseGradientClipAttr, param_list=None,
                      program: Program = None):
    from .framework.program import default_main_program
    program = program or default_main_program()
    setattr(program, _clip_attr_name, (clip, param_list))


def append_gradient_clip_ops(program: Program, param_grads):
    clip_info = getattr(program, _clip_attr_name, None)
    if clip_info is None:
        return
    clip, param_list = clip_info
    if param_list is not None:
        names = {p if isinstance(p, str) else p.name for p in param_list}
        param_grads = [(p, g) for p, g in param_grads if p.name in names]
    clip.append_clip_ops(program.global_block(), param_grads)


class ErrorClipByValue:
    """ref clip.py ErrorClipByValue — retained for API parity; under vjp
    autodiff, error clipping maps to clipping the upstream grad, which the
    framework applies via grad-var clip ops."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)
